#!/usr/bin/env bash
# Fast pre-tier-1 gate: syntax + import breakage fails in seconds, not
# after minutes of pytest collection. Run from the repo root:
#
#   bash scripts/smoke.sh
#
# 1. `compileall` over the package — any SyntaxError fails the sweep.
# 2. Import every `kubeflow_tpu` module on the CPU backend — a broken
#    top-level import (missing dep, bad re-export, circular import)
#    fails with the offending module named.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
# match tests/conftest.py: the tunneled-TPU plugin trigger must not be
# able to wedge interpreter startup in a CPU-only sweep
for k in $(env | grep -o '^PALLAS_AXON[^=]*' || true); do unset "$k"; done

echo "== compileall =="
python -m compileall -q kubeflow_tpu tests scripts bench.py

echo "== import sweep =="
python - <<'EOF'
import importlib
import pkgutil
import sys

import kubeflow_tpu

failures = []
mods = sorted(
    m.name
    for m in pkgutil.walk_packages(kubeflow_tpu.__path__, "kubeflow_tpu.")
    # __main__ executes the CLI at import; everything else must be inert
    if not m.name.endswith("__main__")
)
for name in mods:
    try:
        importlib.import_module(name)
    except Exception as e:  # noqa: BLE001 — report every breakage at once
        failures.append((name, f"{type(e).__name__}: {e}"))
print(f"imported {len(mods) - len(failures)}/{len(mods)} modules")
for name, err in failures:
    print(f"FAIL {name}: {err}", file=sys.stderr)
sys.exit(1 if failures else 0)
EOF

echo "== kft lint --strict (repo-native invariant checks) =="
# AST passes over the whole package: lock discipline, metric-name registry,
# JAX hot-loop sync rules, thread/clock hygiene, seedable randomness.
# Anything beyond the pinned lint_baseline.json fails the gate.
python -m kubeflow_tpu lint --strict

echo "== 20-step overlapped Trainer.fit (prefetch on, accum=2) =="
python - <<'EOF'
import os, sys, threading

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import optax  # noqa: E402

from kubeflow_tpu.core.mesh import MeshSpec  # noqa: E402
from kubeflow_tpu.data.synthetic import (  # noqa: E402
    ClassPrototypeDataset, local_shard_iterator,
)
from kubeflow_tpu.models.mnist_cnn import (  # noqa: E402
    MnistCNN, make_init_fn, make_loss_fn,
)
from kubeflow_tpu.train.loop import TrainConfig, Trainer  # noqa: E402
from kubeflow_tpu.train.prefetch import live_kft_threads  # noqa: E402

model = MnistCNN()
trainer = Trainer(
    init_params=make_init_fn(model),
    loss_fn=make_loss_fn(model),
    optimizer=optax.adam(1e-3),
    config=TrainConfig(
        mesh=MeshSpec.data_parallel(jax.device_count()),
        global_batch=16,
        steps=20,
        log_every=10,
        check_numerics="off",
        prefetch_depth=2,
        grad_accum_steps=2,
    ),
)
_, history = trainer.fit(local_shard_iterator(ClassPrototypeDataset(), 16))
assert history and history[-1]["step"] == 20, history
assert history[-1]["steps_per_sec"] > 0, history[-1]
assert "compile_ms" in history[0], history[0]
# clean shutdown: the prefetch producer and metric drain must be joined,
# and nothing non-daemon may be left to wedge interpreter exit
leaked = live_kft_threads()
assert not leaked, f"leaked overlap threads: {leaked}"
non_daemon = [
    t.name for t in threading.enumerate()
    if t is not threading.main_thread() and not t.daemon
]
assert not non_daemon, f"leaked non-daemon threads: {non_daemon}"
print(f"fit OK: steps_per_sec={history[-1]['steps_per_sec']:.3g} "
      f"compile_ms={history[0]['compile_ms']:.1f}")
EOF

echo "== pipelined decode: 2 concurrent requests, carry uploads << chunks =="
python - <<'EOF'
import threading

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, TransformerLM,
)
from kubeflow_tpu.serve.engine import LMEngine  # noqa: E402

cfg = TransformerConfig(
    vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_ff=32, causal=True,
    max_seq_len=128, attn_impl="reference", dtype=jnp.float32,
)
model = TransformerLM(cfg)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
    "params"
]
# eos_id outside the vocab: no completion can EOS early, so the chunk
# count is deterministic (ceil(23/4)=6 decode chunks) and the assertion
# below cannot flake on a lucky sample from the random init
eng = LMEngine(
    model, cfg, params, max_batch=2, max_seq=96, chunk_steps=4,
    prefill_buckets=(16,), eos_id=cfg.vocab_size + 1, pipeline_depth=1,
).start()
try:
    outs = {}

    def worker(i):
        outs[i] = eng.submit([3 + i, 5, 7, 11], max_new_tokens=24)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    assert len(outs) == 2 and all(isinstance(v, list) for v in outs.values())
    chunks = eng.stats["chunks"]
    uploads = eng.overlap["carry_uploads"]  # kft_engine_carry_uploads_total
    # the tentpole invariant: steady-state decode pays ZERO per-chunk H2D —
    # carry uploads track admissions (2 here), never chunks
    assert chunks >= 2 and uploads < chunks, (chunks, uploads)
finally:
    eng.stop()
print(f"pipelined decode OK: chunks={chunks} carry_uploads={uploads}")
EOF

echo "== speculative decode: K=4 byte-identical to K=0, fewer forwards =="
python - <<'EOF'
import jax

jax.config.update("jax_platforms", "cpu")
import flax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, TransformerLM,
)
from kubeflow_tpu.serve.engine import LMEngine  # noqa: E402

cfg = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64, causal=True,
    max_seq_len=256, attn_impl="reference", dtype=jnp.float32,
)
model = TransformerLM(cfg)
params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))[
    "params"
]
# copy-deterministic stand-in for induction behavior on templated traffic:
# zeroing the attention/MLP write-back makes the greedy chain periodic, so
# prompt-lookup drafts are structurally acceptable (not luck); eos outside
# the vocab keeps the chunk count deterministic
flat = flax.traverse_util.flatten_dict(params)
params = flax.traverse_util.unflatten_dict({
    k: (jnp.zeros_like(v) if k[-2] in ("o_proj", "down_proj") else v)
    for k, v in flat.items()
})
prompt = [5, 9, 13, 7] * 4
results = {}
for k in (0, 4):
    eng = LMEngine(
        model, cfg, params, max_batch=2, max_seq=160, chunk_steps=2,
        prefill_buckets=(16,), eos_id=cfg.vocab_size + 1,
        pipeline_depth=1, spec_draft_tokens=k,
    ).start()
    try:
        toks = eng.submit(prompt, max_new_tokens=64)
        results[k] = (toks, eng.stats["chunks"],
                      eng.stats["spec_accepted"])  # kft_engine_spec_accepted_total
    finally:
        eng.stop()
toks0, chunks0, _ = results[0]
toks4, chunks4, accepted = results[4]
# the tentpole contract: speculation changes the forward count, NEVER the
# token stream — and on repetitive traffic it really accepts
assert toks4 == toks0, (toks4[:8], toks0[:8])
assert accepted > 0, "speculative drafts never accepted"
assert chunks0 >= 1.5 * chunks4, (chunks0, chunks4)
print(f"speculative decode OK: tokens={len(toks4)} identical, "
      f"forwards {chunks0}->{chunks4}, spec_accepted={accepted}")
EOF

echo "== paged kernel (interpret): byte-parity vs gather, int8 pool halved =="
python - <<'EOF'
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, TransformerLM,
)
from kubeflow_tpu.serve.engine import LMEngine  # noqa: E402

cfg = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64, causal=True,
    max_seq_len=128, attn_impl="reference", dtype=jnp.float32,
    interpret_kernels=True,  # CPU smoke: Mosaic interpreter, same semantics
)
model = TransformerLM(cfg)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
    "params"
]
prompts = [[3, 5, 7, 11, 13], [2, 4, 6]]


def run(impl, quant="none"):
    eng = LMEngine(
        model, cfg, params, max_batch=2, max_seq=64, chunk_steps=4,
        prefill_buckets=(16,), eos_id=cfg.vocab_size + 1,
        kv_pool_tokens=16 * 10, page_size=16,
        paged_attn_impl=impl, kv_quant=quant,
    ).start()
    try:
        outs = [eng.submit(p, max_new_tokens=16) for p in prompts]
        kv = sum(int(lc[w].nbytes)
                 for lc in eng.cache.values() for w in ("k", "v"))
        sc = sum(int(a.nbytes) for lc in eng.cache.values()
                 for w, a in lc.items() if w.endswith("_scale"))
    finally:
        eng.stop()
    return outs, kv, sc


gather, kv_f32, sc_f32 = run("gather")
kernel, _, _ = run("kernel")
# the read-path swap is a layout change, not a numerics change
assert kernel == gather, (kernel, gather)
_, kv_int8, sc_int8 = run("gather", "int8")
# int8 pool = 1/4 of f32 = 1/2 of the bf16 pool the chip serves from;
# per-token-per-head f32 scales are the 1/head_dim overhead on top
assert kv_int8 * 4 == kv_f32 and sc_f32 == 0, (kv_int8, kv_f32)
assert sc_int8 == kv_int8 * 4 // (cfg.d_model // cfg.n_heads)
print(f"paged kernel OK: byte-identical streams, pool {kv_f32}->{kv_int8} B "
      f"(+{sc_int8} B scales)")
EOF

echo "== kill-and-resume: SIGTERM mid-train -> 143 -> exact-step resume =="
python - <<'EOF'
import os, re, signal, subprocess, sys, tempfile, time

tmp = tempfile.mkdtemp(prefix="kft-smoke-preempt-")
ckpt = os.path.join(tmp, "ckpt")
cmd = [
    sys.executable, "-m", "kubeflow_tpu.examples.mnist",
    "--steps", "8", "--global-batch", "16", "--log-every", "1",
    "--checkpoint-dir", ckpt, "--checkpoint-every", "1",
    "--checkpoint-sync",
]
env = {**os.environ, "PYTHONUNBUFFERED": "1",
       "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
log = os.path.join(tmp, "run0.log")
with open(log, "wb") as f:
    proc = subprocess.Popen(cmd, stdout=f, stderr=subprocess.STDOUT, env=env)
    # preemption notice once training demonstrably reached step >= 2
    deadline = time.time() + 180
    while time.time() < deadline:
        text = open(log, errors="replace").read()
        if re.search(r"^step=2 ", text, re.M):
            proc.send_signal(signal.SIGTERM)
            break
        if proc.poll() is not None:
            sys.exit(f"trainer exited early:\n{text}")
        time.sleep(0.1)
    rc = proc.wait(timeout=120)
text = open(log, errors="replace").read()
assert rc == 143, f"expected preemption exit 143, got {rc}:\n{text}"
assert "preempted at step" in text, text

out = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=300)
assert out.returncode == 0, out.stdout + out.stderr
m = re.search(r"resume_step=(\d+)", out.stdout)
assert m, f"no resume marker:\n{out.stdout}"
resume = int(m.group(1))
steps = [int(s) for s in re.findall(r"^step=(\d+) ", out.stdout, re.M)]
assert resume >= 2 and steps == list(range(resume + 1, 9)), (resume, steps)
print(f"kill-and-resume OK: preempted run exited 143, resumed at "
      f"step {resume + 1}, finished 8")
EOF

echo "== quota scheduler: cohort borrow -> preempt -> resume =="
python - <<'EOF'
import sys, tempfile, time

from kubeflow_tpu.obs.prom import REGISTRY
from kubeflow_tpu.orchestrator import (
    JobSpec, LocalCluster, ReplicaSpec, RestartPolicy, RunPolicy,
    SchedulingPolicy, TPURequest,
)
from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.sched import ClusterQueue, LocalQueue, QueueConfig


def counter(name, **labels):
    metric = REGISTRY._metrics.get(name)
    child = metric._children.get(tuple(sorted(labels.items()))) if metric else None
    return child.value if child else 0.0


# tenant-b owns no quota and borrows tenant-a's; exits 143 on SIGTERM
# (the trainer preemption protocol) and finishes clean after the requeue
PREEMPTIBLE = (
    "import os, signal, sys, time;"
    "signal.signal(signal.SIGTERM, lambda *a: sys.exit(143));"
    "time.sleep(30.0 if os.environ['KFT_ATTEMPT'] == '0' else 0.05);"
    "sys.exit(0)"
)
config = QueueConfig(
    [ClusterQueue("tenant-a", {"v5e": 4}, cohort="shared"),
     ClusterQueue("tenant-b", {"v5e": 0}, cohort="shared",
                  borrowing_limit=4)],
    [LocalQueue("team-a", "tenant-a"), LocalQueue("team-b", "tenant-b")],
)


def job(name, queue, code, chips=4):
    return JobSpec(
        name=name,
        replicas={"worker": ReplicaSpec(
            replicas=1, command=(sys.executable, "-c", code),
            restart_policy=RestartPolicy.EXIT_CODE,
            tpu=TPURequest(chips=chips),
        )},
        run_policy=RunPolicy(scheduling=SchedulingPolicy(queue=queue)),
    )


p0 = counter("kft_preemptions_total", reason="borrowed")
r0 = counter("kft_gang_requeues_total", reason="Preempted")
with LocalCluster(
    fleet=Fleet.homogeneous(1, "2x2"),
    base_dir=tempfile.mkdtemp(prefix="kft-smoke-quota-"),
    queues=config, resync_period=0.05, preemption_grace_seconds=10.0,
) as cluster:
    b_uid = cluster.submit(job("borrower", "team-b", PREEMPTIBLE))
    deadline = time.time() + 60
    while time.time() < deadline:
        st = cluster.status(b_uid)
        if st and st.phase == "Running":
            break
        time.sleep(0.02)
    assert cluster.status(b_uid).phase == "Running", "borrower never started"
    # tenant-a reclaims its nominal quota -> tenant-b's borrower preempted
    a_uid = cluster.submit(
        job("reclaimer", "team-a", "import time; time.sleep(0.3)")
    )
    assert cluster.wait(a_uid, timeout=60).phase == "Succeeded"
    b_status = cluster.wait(b_uid, timeout=60)
    assert b_status.phase == "Succeeded", b_status.phase  # resumed + finished
    assert b_status.restart_count == 0, "preemption burned backoff budget"
assert counter("kft_preemptions_total", reason="borrowed") == p0 + 1
assert counter("kft_gang_requeues_total", reason="Preempted") == r0 + 1
print("quota preempt OK: borrower evicted (143), reclaimer ran, "
      "borrower resumed; kft_preemptions_total asserted")
EOF

echo "== gateway: SIGKILL one of two backends mid-burst, zero failures =="
python - <<'EOF'
import json, os, subprocess, sys, tempfile, time, urllib.request

tmp = tempfile.mkdtemp(prefix="kft-smoke-gw-")
isvc = os.path.join(tmp, "isvc.yaml")
with open(isvc, "w") as f:
    f.write(
        "apiVersion: serving.kubeflow.org/v1beta1\n"
        "kind: InferenceService\n"
        "metadata: {name: echo}\n"
        "spec:\n"
        "  predictor:\n"
        "    model:\n"
        "      modelFormat: {name: bert-tiny}\n"
        "      extra: {attn_impl: reference}\n"  # CPU smoke: no pallas
    )
env = {**os.environ, "PYTHONUNBUFFERED": "1"}


def wait_port(pf, proc, log):
    deadline = time.time() + 180
    while time.time() < deadline:
        if os.path.exists(pf) and open(pf).read().strip():
            return int(open(pf).read())
        if proc.poll() is not None:
            sys.exit(f"process died early:\n{open(log, errors='replace').read()}")
        time.sleep(0.1)
    sys.exit("process never bound a port")


procs = []
try:
    ports = []
    for i in range(2):  # two real ModelServer replicas via the CLI
        pf = os.path.join(tmp, f"port{i}")
        log = os.path.join(tmp, f"srv{i}.log")
        p = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu", "serve", "-f", isvc,
             "--http-port", "0", "--port-file", pf],
            stdout=open(log, "wb"), stderr=subprocess.STDOUT, env=env,
        )
        procs.append(p)
        ports.append((pf, p, log))
    ports = [wait_port(pf, p, log) for pf, p, log in ports]

    gw_yaml = os.path.join(tmp, "gw.yaml")
    with open(gw_yaml, "w") as f:  # YAML is a JSON superset
        json.dump({
            "kind": "InferenceGateway", "metadata": {"name": "edge"},
            "spec": {
                "failureThreshold": 2, "probeIntervalS": 2.0,
                "retryBudgetFloor": 30,
                "services": [{"name": "echo", "backends": [
                    f"http://127.0.0.1:{ports[0]}",
                    f"http://127.0.0.1:{ports[1]}",
                ]}],
            },
        }, f)
    gpf = os.path.join(tmp, "gwport")
    gwlog = os.path.join(tmp, "gw.log")
    gw = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu", "gateway", "run",
         "-f", gw_yaml, "--http-port", "0", "--port-file", gpf],
        stdout=open(gwlog, "wb"), stderr=subprocess.STDOUT, env=env,
    )
    procs.append(gw)
    gwport = wait_port(gpf, gw, gwlog)

    def predict(i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gwport}/v1/models/echo:predict",
            data=json.dumps({"instances": ["the [mask] runs"]}).encode(),
            headers={"Content-Type": "application/json",
                     "x-request-id": f"smoke-{i}"},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            return json.loads(r.read())

    for i in range(4):  # warm both replicas through the compile
        assert "predictions" in predict(i)

    from kubeflow_tpu.chaos.injectors import kill_backend

    kill_backend(procs[1].pid)  # SIGKILL one replica, burst immediately
    for i in range(20):
        out = predict(100 + i)
        assert "predictions" in out, out

    metrics = urllib.request.urlopen(
        f"http://127.0.0.1:{gwport}/metrics", timeout=30
    ).read().decode()

    def metric(prefix):
        for ln in metrics.splitlines():
            if ln.startswith(prefix):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    retries = metric('kft_gateway_retries_total{service="echo"}')
    opens = metric(
        f'kft_gateway_breaker_opens_total{{backend="http://127.0.0.1:{ports[1]}"}}'
    )
    assert retries >= 1, f"no transparent retries observed:\n{metrics}"
    assert opens >= 1, f"breaker never opened for the dead backend:\n{metrics}"
finally:
    for p in procs:
        if p.poll() is None:
            p.kill()
print(f"gateway OK: 20-request burst clean over a dead backend, "
      f"retries={retries:.0f} breaker_opens={opens:.0f}")
EOF

echo "== SRE: wedge an engine behind the gateway; watchdog restarts it, zero failed requests =="
python - <<'EOF'
import asyncio, json, time, urllib.request

import jax, jax.numpy as jnp

from kubeflow_tpu.chaos.injectors import wedge_engine
from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway
from kubeflow_tpu.gateway.router import ServiceRoute
from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.serve.engine import LMEngineModel
from kubeflow_tpu.serve.model import BucketSpec
from kubeflow_tpu.serve.server import ModelServer

cfg = TransformerConfig(vocab_size=89, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, causal=True, max_seq_len=256,
                        attn_impl="reference", dtype=jnp.float32)
tlm = TransformerLM(cfg)
params = tlm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def replica():
    m = LMEngineModel(
        "m", None, config=cfg, max_batch=4, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=6, eos_id=1, watchdog_interval_s=0.1,
        watchdog_min_wedge_s=60.0,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = m._make_engine().start()
    return m


async def main():
    m_a, m_b = replica(), replica()
    ms_a = ModelServer([m_a], http_port=0)
    ms_b = ModelServer([m_b], http_port=0)
    await ms_a.start_async()
    await ms_b.start_async()

    def port_of(ms):
        (site,) = ms._runner.sites
        return site._server.sockets[0].getsockname()[1]

    pa, pb = port_of(ms_a), port_of(ms_b)
    gw = InferenceGateway(GatewayConfig(
        probe_interval_s=0.25, eject_threshold=1, failure_threshold=2,
        recovery_s=60.0, retry_budget_floor=100,
        routes=[ServiceRoute(name="m", max_attempts=4)],
        backends=[("m", f"http://127.0.0.1:{pa}", "default"),
                  ("m", f"http://127.0.0.1:{pb}", "default")],
    ), http_port=0)
    await gw.start_async()
    loop = asyncio.get_running_loop()

    def predict(i, extra=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.http_port}/v1/models/m:predict",
            data=json.dumps(
                {"instances": [{"input_ids": [3 + i % 5, 4, 5]}]}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "x-request-id": f"sre-{i}", **(extra or {})},
        )
        try:
            with urllib.request.urlopen(req, timeout=180) as r:
                return r.status, dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    async def one(i, extra=None):
        return await loop.run_in_executor(None, predict, i, extra)

    try:
        for i in range(6):  # warm both replicas through their compiles
            status, _ = await one(i)
            assert status == 200, status
        for m in (m_a, m_b):
            m.watchdog.config.min_wedge_s = 1.0

        release = wedge_engine(m_a.engine, hold_s=45.0)
        results = await asyncio.gather(*[one(100 + i) for i in range(16)])
        release()
        statuses = [s for s, _ in results]
        assert statuses == [200] * 16, statuses

        # blocking reads must leave the loop thread: the backends are
        # served BY this loop, so an inline urlopen would deadlock
        metrics = (await loop.run_in_executor(
            None,
            lambda: urllib.request.urlopen(
                f"http://127.0.0.1:{pa}/metrics", timeout=30
            ).read(),
        )).decode()
        trips = 0.0
        for ln in metrics.splitlines():
            if ln.startswith('kft_engine_watchdog_trips_total{model="m",reason="wedged"}'):
                trips = float(ln.rsplit(" ", 1)[1])
        assert trips >= 1, f"watchdog never tripped:\n{metrics}"
        assert m_a.ready and m_b.ready

        # correctly-shed tail: an expired deadline is 503 + Retry-After
        status, hdrs = await one(999, {"x-kft-deadline-ms": "0"})
        assert status == 503 and hdrs.get("Retry-After"), (status, hdrs)
        print(f"SRE OK: wedge mid-burst absorbed — watchdog trips={trips:.0f}, "
              "16/16 requests clean, deadline shed 503+Retry-After")
    finally:
        await gw.stop_async()
        m_a.unload()
        m_b.unload()
        await ms_a.stop_async()
        await ms_b.stop_async()

asyncio.run(main())
EOF

echo "== mid-stream failover: kill the streaming replica, client sees one unbroken stream =="
python - <<'EOF'
import asyncio, json, urllib.request

import jax, jax.numpy as jnp

from kubeflow_tpu.chaos.injectors import kill_mid_stream
from kubeflow_tpu.gateway.router import ServiceRoute
from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway
from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.serve.engine import LMEngineModel
from kubeflow_tpu.serve.model import BucketSpec
from kubeflow_tpu.serve.server import ModelServer
from kubeflow_tpu.serve.watchdog import EngineRestarting

cfg = TransformerConfig(vocab_size=89, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, causal=True, max_seq_len=256,
                        attn_impl="reference", dtype=jnp.float32)
tlm = TransformerLM(cfg)
params = tlm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def replica():
    m = LMEngineModel(
        "m", None, config=cfg, max_batch=4, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=6, eos_id=1, watchdog_interval_s=0.1,
        watchdog_min_wedge_s=60.0,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = m._make_engine().start()
    return m


async def main():
    m_a, m_b = replica(), replica()
    ms_a = ModelServer([m_a], http_port=0)
    ms_b = ModelServer([m_b], http_port=0)
    await ms_a.start_async()
    await ms_b.start_async()

    def port_of(ms):
        (site,) = ms._runner.sites
        return site._server.sockets[0].getsockname()[1]

    pa, pb = port_of(ms_a), port_of(ms_b)
    url_a, url_b = (f"http://127.0.0.1:{p}" for p in (pa, pb))
    # session affinity pins the stream to one replica, so the victim is
    # deterministic and the resume provably lands on the peer
    route = ServiceRoute(name="m", affinity="session", max_attempts=4)
    gw = InferenceGateway(GatewayConfig(
        probe_interval_s=0.25, failure_threshold=2, recovery_s=60.0,
        retry_budget_floor=100, routes=[route],
        backends=[("m", url_a, "default"), ("m", url_b, "default")],
    ), http_port=0)
    await gw.start_async()
    loop = asyncio.get_running_loop()

    def stream(req_id):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.http_port}/v2/models/m/generate_stream",
            data=json.dumps({"input_ids": [3, 4, 5]}).encode(),
            headers={"Content-Type": "application/json",
                     "x-session-id": "smoke-s1", "x-request-id": req_id},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            text = r.read().decode()
        return [json.loads(ln[6:]) for ln in text.splitlines()
                if ln.startswith("data: ")]

    def predict(i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.http_port}/v1/models/m:predict",
            data=json.dumps(
                {"instances": [{"input_ids": [3 + i % 5, 4, 5]}]}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            return r.status

    def metric(line_prefix):
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{gw.http_port}/metrics", timeout=30
        ).read().decode()
        for ln in text.splitlines():
            if ln.startswith(line_prefix):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    try:
        for i in range(4):  # warm both replicas through their compiles
            assert await loop.run_in_executor(None, predict, i) == 200
        base = await loop.run_in_executor(None, stream, "smoke-base")
        assert all("error" not in f for f in base), base
        base_toks = [t for f in base for t in f.get("token_ids", [])]

        victim_b = gw._affine_pick(route, "default", "session:smoke-s1")
        victim, peer = (m_a, m_b) if victim_b.url == url_a else (m_b, m_a)
        kill_mid_stream(
            victim.engine, after_tokens=2,
            action=lambda eng: eng.poison(
                EngineRestarting("smoke: replica killed mid-stream")
            ),
        )
        frames = await loop.run_in_executor(None, stream, "smoke-failover")
        assert all("error" not in f for f in frames), frames
        toks = [t for f in frames for t in f.get("token_ids", [])]
        assert toks == base_toks, (toks, base_toks)
        assert frames[-1]["done"] and frames[-1]["n_tokens"] == len(base_toks)
        resumes = await loop.run_in_executor(
            None, metric,
            'kft_gateway_stream_resumes_total{outcome="ok",service="m"}')
        assert resumes >= 1, "no successful stream resume recorded"
        assert peer.engine.stats["resume_admits"] >= 1
        print(f"mid-stream failover OK: {len(toks)} tokens unbroken across "
              f"a replica kill, stream_resumes_ok={resumes:.0f}")
    finally:
        await gw.stop_async()
        m_a.unload()
        m_b.unload()
        await ms_a.stop_async()
        await ms_b.stop_async()

asyncio.run(main())
EOF

echo "== autoscaler burst: 1->3->1->0 scale cycle, zero failures, prefix-KV transfer =="
python - <<'EOF'
import asyncio, json, time, urllib.request

import jax, jax.numpy as jnp

from kubeflow_tpu.autoscale import (
    GatewaySignalSource, KPAConfig, ReplicaFleet, ServingAutoscaler,
)
from kubeflow_tpu.gateway.router import ServiceRoute
from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway
from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.obs.prom import REGISTRY
from kubeflow_tpu.serve.engine import LMEngineModel
from kubeflow_tpu.serve.model import BucketSpec
from kubeflow_tpu.serve.server import ModelServer

cfg = TransformerConfig(vocab_size=89, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, causal=True, max_seq_len=256,
                        attn_impl="reference", dtype=jnp.float32)
tlm = TransformerLM(cfg)
params = tlm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def metric(name, **labels):
    m = REGISTRY._metrics.get(name)
    child = m._children.get(tuple(sorted(labels.items()))) if m else None
    return child.value if child else 0.0


async def main():
    servers = {}

    async def launch(index):
        m = LMEngineModel(
            "m", None, config=cfg, max_batch=4, chunk_steps=2,
            buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
            max_new_tokens=24, eos_id=cfg.vocab_size + 1, watchdog=False,
            prefix_cache_entries=32,
        )
        m.load()
        m._params = jax.device_put(params)  # identical weights per replica
        m.engine.stop()
        m.engine = m._make_engine().start()
        ms = ModelServer([m], http_port=0)
        await ms.start_async()
        (site,) = ms._runner.sites
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}"

        async def stop():
            m.unload()
            await ms.stop_async()

        servers[url] = (m, ms)
        return url, stop

    asc = ServingAutoscaler(tick_interval_s=0.15)
    gw = InferenceGateway(GatewayConfig(
        probe_interval_s=0.25, activation_timeout_s=60.0,
        routes=[ServiceRoute(name="m")],
    ), scale_up=asc.kick)
    fleet = ReplicaFleet("m", launch, pool=gw.pool, model="m")
    source = GatewaySignalSource(gw, "m")
    asc.add_service("m", KPAConfig(
        target=1.0, min_replicas=0, max_replicas=3,
        stable_window_s=3.0, panic_window_s=0.6, panic_threshold=1.5,
        max_scale_down_rate=2.0, scale_to_zero_grace_s=1.2,
    ), source, fleet)
    await fleet.scale_to(1)
    await gw.start_async()
    loop = asyncio.get_running_loop()
    prompts = [[2 + (7 * i + j) % 80 for j in range(17)] for i in range(10)]

    def predict(i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.http_port}/v1/models/m:predict",
            data=json.dumps(
                {"instances": [{"input_ids": prompts[i % len(prompts)]}]}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "x-request-id": f"burst-{i}"},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            return r.status

    try:
        for i in range(3):  # warm replica 0 through its compiles
            assert await loop.run_in_executor(None, predict, i) == 200
        asc.start()
        peak = [fleet.current()]

        async def watch():
            while True:
                peak[0] = max(peak[0], fleet.current())
                await asyncio.sleep(0.03)

        watcher = asyncio.ensure_future(watch())
        # open-loop burst: fixed arrivals, nobody waits on responses
        tasks = []
        for i in range(40):
            tasks.append(loop.run_in_executor(None, predict, 100 + i))
            await asyncio.sleep(0.025)
        statuses = await asyncio.gather(*tasks)
        assert statuses == [200] * 40, statuses
        deadline = time.monotonic() + 90
        while peak[0] < 3 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert peak[0] == 3, f"never panicked to 3 (peak {peak[0]})"
        moved = fleet.stats["kv_entries_moved"]
        assert moved >= 1, "scale-up replicas pulled no prefix KV"
        # quiet: stable window drains, grace expires, replicas -> 0
        deadline = time.monotonic() + 90
        while fleet.current() > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert fleet.current() == 0, fleet.current()
        watcher.cancel()
        # scale-from-zero: parked in the activator, kick relaunches
        acts0 = metric("kft_gateway_activations_total", service="m")
        assert await loop.run_in_executor(None, predict, 999) == 200
        assert fleet.current() == 1
        assert metric("kft_gateway_activations_total", service="m") == acts0 + 1
        print(f"autoscaler OK: 40-request burst 1->3 (panic), idle ->0, "
              f"cold request served via activator; prefix-KV entries "
              f"moved={moved}, "
              f"scale_events_up="
              f"{metric('kft_autoscaler_scale_events_total', service='m', direction='up'):.0f}")
    finally:
        await asc.stop()
        await source.close()
        await fleet.close()
        await gw.stop_async()

asyncio.run(main())
EOF

echo "== tracing: one trace id gateway edge -> decode chunk, TTFT/TPOT histograms, Perfetto export =="
python - <<'EOF'
import asyncio, json, urllib.request

import jax, jax.numpy as jnp

from kubeflow_tpu.gateway.router import ServiceRoute
from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway
from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.obs.trace import TRACER, TraceContext, to_perfetto
from kubeflow_tpu.serve.engine import LMEngineModel
from kubeflow_tpu.serve.model import BucketSpec
from kubeflow_tpu.serve.server import ModelServer

cfg = TransformerConfig(vocab_size=89, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, causal=True, max_seq_len=256,
                        attn_impl="reference", dtype=jnp.float32)
tlm = TransformerLM(cfg)
params = tlm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def replica():
    m = LMEngineModel(
        "m", None, config=cfg, max_batch=4, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=6, eos_id=1,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = m._make_engine().start()
    return m


async def main():
    TRACER.sample_every = 1  # keep every trace in this tiny burst
    m_a, m_b = replica(), replica()
    ms_a = ModelServer([m_a], http_port=0)
    ms_b = ModelServer([m_b], http_port=0)
    await ms_a.start_async()
    await ms_b.start_async()

    def port_of(ms):
        (site,) = ms._runner.sites
        return site._server.sockets[0].getsockname()[1]

    pa, pb = port_of(ms_a), port_of(ms_b)
    gw = InferenceGateway(GatewayConfig(
        probe_interval_s=0.25,
        routes=[ServiceRoute(name="m")],
        backends=[("m", f"http://127.0.0.1:{pa}", "default"),
                  ("m", f"http://127.0.0.1:{pb}", "default")],
    ), http_port=0)
    await gw.start_async()
    loop = asyncio.get_running_loop()
    ctx = TraceContext("ab" * 16, "cd" * 8)  # the "client SDK" span

    def predict(i, extra=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.http_port}/v1/models/m:predict",
            data=json.dumps(
                {"instances": [{"input_ids": [3 + i % 5, 4, 5]}]}
            ).encode(),
            headers={"Content-Type": "application/json", **(extra or {})},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            return r.status

    def fetch(url):
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.read().decode()

    try:
        for i in range(6):
            assert await loop.run_in_executor(None, predict, i) == 200
        assert await loop.run_in_executor(
            None, predict, 99, {"x-kft-trace": ctx.header()}) == 200

        # the client-stamped trace covers EVERY hop, edge to decode chunk
        snap = TRACER.snapshot(limit=64)
        tr = next(t for t in snap["traces"] if t["trace_id"] == ctx.trace_id)
        names = {s["name"] for s in tr["spans"]}
        need = {"route", "proxy", "dataplane", "engine",
                "queue.wait", "prefill", "decode.chunk"}
        assert need <= names, f"span tree incomplete: {sorted(names)}"
        route = next(s for s in tr["spans"] if s["name"] == "route")
        assert route["parent_span_id"] == ctx.span_id

        # the replica's own /debug/traces serves its half of the story
        replica_snap = None
        for port in (pa, pb):
            doc = json.loads(await loop.run_in_executor(
                None, fetch, f"http://127.0.0.1:{port}/debug/traces?limit=64"))
            hit = [t for t in doc["traces"] if t["trace_id"] == ctx.trace_id]
            if hit:
                replica_snap = hit[0]
        assert replica_snap is not None, "trace missing from /debug/traces"
        assert any(s["name"] == "decode.chunk" for s in replica_snap["spans"])

        # Perfetto conversion round-trips through JSON
        perfetto = to_perfetto(snap)
        assert any(e.get("ph") == "X" for e in json.loads(
            json.dumps(perfetto))["traceEvents"])

        # completed streams fed the TTFT/TPOT histograms
        ttft = tpot = 0.0
        for port in (pa, pb):
            for ln in (await loop.run_in_executor(
                    None, fetch, f"http://127.0.0.1:{port}/metrics")).splitlines():
                if ln.startswith('kft_server_ttft_ms_count{model="m"}'):
                    ttft += float(ln.rsplit(" ", 1)[1])
                if ln.startswith('kft_server_tpot_ms_count{model="m"}'):
                    tpot += float(ln.rsplit(" ", 1)[1])
        assert ttft >= 1, f"TTFT observations missing: {ttft}"
        assert tpot >= 1, f"TPOT observations missing: {tpot}"
        print(f"tracing OK: {len(tr['spans'])} spans edge->decode under one "
              f"trace id, ttft_count={ttft:.0f} tpot_count={tpot:.0f}, "
              f"perfetto events={len(perfetto['traceEvents'])}")
    finally:
        await gw.stop_async()
        m_a.unload()
        m_b.unload()
        await ms_a.stop_async()
        await ms_b.stop_async()

asyncio.run(main())
EOF

echo "== disaggregated serving: prefill pool -> KV ship -> decode pool, zero decode-side prefill =="
python - <<'EOF'
import asyncio, json, urllib.request

import jax, jax.numpy as jnp

from kubeflow_tpu.gateway.router import ServiceRoute
from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway
from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.serve.engine import LMEngineModel
from kubeflow_tpu.serve.model import BucketSpec
from kubeflow_tpu.serve.server import ModelServer

cfg = TransformerConfig(vocab_size=89, d_model=32, n_layers=2, n_heads=4,
                        d_ff=64, causal=True, max_seq_len=256,
                        attn_impl="reference", dtype=jnp.float32)
tlm = TransformerLM(cfg)
params = tlm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def replica():
    m = LMEngineModel(
        "m", None, config=cfg, max_batch=4, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=6, eos_id=1,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = m._make_engine().start()
    return m


async def main():
    m_pre, m_dec = replica(), replica()
    ms_pre = ModelServer([m_pre], http_port=0, role="prefill")
    ms_dec = ModelServer([m_dec], http_port=0, role="decode")
    await ms_pre.start_async()
    await ms_dec.start_async()

    def port_of(ms):
        (site,) = ms._runner.sites
        return site._server.sockets[0].getsockname()[1]

    pp, pd = port_of(ms_pre), port_of(ms_dec)
    gw = InferenceGateway(GatewayConfig(
        probe_interval_s=0.25,
        routes=[ServiceRoute(name="m")],
        backends=[("m", f"http://127.0.0.1:{pp}", "default", "prefill"),
                  ("m", f"http://127.0.0.1:{pd}", "default", "decode")],
    ), http_port=0)
    await gw.start_async()
    loop = asyncio.get_running_loop()
    prompts = [[3 + i, 9, 11, 5, 7, 2 + i, 13, 8] for i in range(3)]

    def generate(url, ids):
        req = urllib.request.Request(
            url, data=json.dumps({"input_ids": ids}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=180) as r:
            return json.loads(r.read().decode())

    def metric(port, name):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            for ln in r.read().decode().splitlines():
                if ln.startswith(name + "{") or ln.startswith(name + " "):
                    return float(ln.rsplit(" ", 1)[1])
        return 0.0

    try:
        # the prefill-role replica is NOT traffic-selectable: the gateway
        # must route every client request to the decode backend
        state = gw.state_view()
        roles = {b["url"]: b["role"] for b in state["services"][0]["backends"]}
        assert set(roles.values()) == {"prefill", "decode"}, roles
        via_gw = [
            await loop.run_in_executor(
                None, generate, f"http://127.0.0.1:{gw.http_port}"
                f"/v2/models/m/generate", p)
            for p in prompts
        ]
        # colocated reference: the same prompts straight at the prefill
        # replica (a full server; role only gates gateway selection)
        direct = [
            await loop.run_in_executor(
                None, generate, f"http://127.0.0.1:{pp}/v2/models/m/generate",
                p)
            for p in prompts
        ]
        assert via_gw == direct, (via_gw, direct)

        # the acceptance criterion, metric-asserted off the decode
        # replica: every span was injected, ZERO prefill chunks executed
        # (metric() blocks, and the servers live on THIS loop: executor)
        async def g(port, name):
            return await loop.run_in_executor(None, metric, port, name)
        assert await g(pd, "kubeflow_tpu_engine_prefill_pieces") == 0
        assert await g(pd, "kubeflow_tpu_engine_kv_injected") == 3
        assert await g(pd, "kubeflow_tpu_engine_kv_ship_bytes") > 0
        assert await g(pd, "kubeflow_tpu_engine_kv_ship_fallbacks") == 0
        assert await g(pp, "kubeflow_tpu_engine_kv_spans_exported") == 3
        ship = await g(pd, "kubeflow_tpu_engine_kv_ship_bytes")
        print(f"disagg OK: 3 generates via gateway == colocated tokens, "
              f"decode prefill_pieces=0, kv_injected=3, "
              f"ship_bytes={ship:.0f}")
    finally:
        await gw.stop_async()
        m_pre.unload()
        m_dec.unload()
        await ms_pre.stop_async()
        await ms_dec.stop_async()

asyncio.run(main())
EOF

echo "== loadgen: seeded open-loop goodput 1.0, then wedged-replica dip with zero client failures =="
python - <<'EOF'
import asyncio
import dataclasses

from kubeflow_tpu.chaos.plan import FaultPlan, WedgeEngine
from kubeflow_tpu.loadgen import ChaosOverlay, TenantSpec, WorkloadMix
from kubeflow_tpu.loadgen.harness import HarnessConfig, run_serving_load

# the bench recipe (bench.py serving_load), shortened: a generous WIRE
# deadline (tight ones are unmeetable on CPU and surface as in-stream
# errors) with a tight ACCOUNTING slo, so a wedge shows up as
# completed_late — a goodput dip — never as a client-visible failure
mix = WorkloadMix(
    prompt_lens=(6, 10), output_lens=(4, 8),
    tenants=(
        TenantSpec("interactive", weight=2.0, priority=2,
                   deadline_ms=30_000.0, slo_ms=2_000.0),
        TenantSpec("batch", weight=1.0, adapter="batch-v1",
                   slo_ms=2_000.0),
    ),
    vocab=80, seed=7,
)
steady_cfg = HarnessConfig(
    seed=7, process="poisson", rate_rps=4.0, duration_s=7.0, mix=mix,
    initial_replicas=2, max_replicas=2, min_replicas=2,
)

steady = asyncio.run(run_serving_load(steady_cfg))
g = steady["goodput"]["overall"]
assert g["offered"] > 0, steady["run"]
assert g["error"] == 0, g
assert g["goodput"] == 1.0, g
# server-side histograms (PR 15), baseline-subtracted: the run's own
# traffic must be there, not just warmup's
ttft, tpot = steady["latency"]["ttft_ms"], steady["latency"]["tpot_ms"]
assert ttft["count"] > 0 and ttft["p50"] is not None, ttft
assert tpot["count"] > 0, tpot

chaos_cfg = dataclasses.replace(steady_cfg, duration_s=8.0, chaos=ChaosOverlay(
    plan=FaultPlan((WedgeEngine(model="m", hold_s=3.0),), seed=7),
    at_s=3.0, window_s=5.0,
))
chaos = asyncio.run(run_serving_load(chaos_cfg))
c = chaos["chaos"]
assert c["faults"] == ["WedgeEngine"], c
assert c["client_visible_failures"] == 0, c
assert c["goodput_dip"] is not None and c["goodput_dip"] > 0, c
print(f"loadgen OK: steady goodput={g['goodput']} over {g['offered']} "
      f"(ttft_p50={ttft['p50']:.1f}ms n={ttft['count']}), wedge dip="
      f"{c['goodput_dip']} in {c['window_s']}, zero client failures")
EOF

echo "smoke OK"
