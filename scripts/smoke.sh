#!/usr/bin/env bash
# Fast pre-tier-1 gate: syntax + import breakage fails in seconds, not
# after minutes of pytest collection. Run from the repo root:
#
#   bash scripts/smoke.sh
#
# 1. `compileall` over the package — any SyntaxError fails the sweep.
# 2. Import every `kubeflow_tpu` module on the CPU backend — a broken
#    top-level import (missing dep, bad re-export, circular import)
#    fails with the offending module named.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
# match tests/conftest.py: the tunneled-TPU plugin trigger must not be
# able to wedge interpreter startup in a CPU-only sweep
for k in $(env | grep -o '^PALLAS_AXON[^=]*' || true); do unset "$k"; done

echo "== compileall =="
python -m compileall -q kubeflow_tpu tests scripts bench.py

echo "== import sweep =="
python - <<'EOF'
import importlib
import pkgutil
import sys

import kubeflow_tpu

failures = []
mods = sorted(
    m.name
    for m in pkgutil.walk_packages(kubeflow_tpu.__path__, "kubeflow_tpu.")
    # __main__ executes the CLI at import; everything else must be inert
    if not m.name.endswith("__main__")
)
for name in mods:
    try:
        importlib.import_module(name)
    except Exception as e:  # noqa: BLE001 — report every breakage at once
        failures.append((name, f"{type(e).__name__}: {e}"))
print(f"imported {len(mods) - len(failures)}/{len(mods)} modules")
for name, err in failures:
    print(f"FAIL {name}: {err}", file=sys.stderr)
sys.exit(1 if failures else 0)
EOF

echo "smoke OK"
