#!/usr/bin/env python
"""One-command chip session: everything round 5 needs from TPU time.

The tunnel wedges unpredictably (three times across rounds 4-5), so chip
minutes are precious. This runbook captures, in priority order, exactly
what VERDICT r04 asked for, each stage isolated in a SUBPROCESS with a
timeout so a mid-stage wedge can never take down the stages after it or
hang the caller:

  1. tests_chip/ (bf16 flash S512 fwd+bwd parity, engine-on-chip incl.
     prefix reuse, block sweep + tuned parity, compiled paged-attention
     kernel parity + page sweep)                  [VERDICT item 2 gate]
  2. flash block sweep at BERT + LM head dims PLUS the paged decode
     kernel's page-size sweep, winners persisted to
     ops/flash_blocks_v5e.json (committed → every later run is tuned)
  3. python bench.py — full driver-format suite   [VERDICT item 1]
  4. BERT MFU batch/seq sweep (B32/64 × S128/512) [items 2+3 evidence]

Usage:  python scripts/chip_session.py [--skip-tests] [--out DIR]
Writes: <out>/chip_session_report.json + stage logs. Safe to re-run.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_stage(name, cmd, *, timeout, out_dir, env=None):
    log = os.path.join(out_dir, f"{name}.log")
    t0 = time.time()
    try:
        with open(log, "w") as f:
            proc = subprocess.run(
                cmd, cwd=REPO, stdout=f, stderr=subprocess.STDOUT,
                timeout=timeout, env=env or os.environ.copy(),
            )
        status = "ok" if proc.returncode == 0 else f"rc={proc.returncode}"
    except subprocess.TimeoutExpired:
        status = "timeout"
    wall = round(time.time() - t0, 1)
    print(f"[{name}] {status} ({wall}s) → {log}", flush=True)
    return {"status": status, "wall_s": wall, "log": log}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-tests", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "chip_out"))
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    sys.path.insert(0, REPO)
    from kubeflow_tpu.core.deviceprobe import probe_backend

    backend = probe_backend(timeout_s=150)
    print(f"backend: {backend}", flush=True)
    report = {"backend": backend, "started": time.time(), "stages": {}}
    if backend in ("unreachable", "cpu"):
        report["aborted"] = f"no TPU ({backend})"
        with open(os.path.join(args.out, "chip_session_report.json"), "w") as f:
            json.dump(report, f, indent=1)
        return 1

    if not args.skip_tests:
        report["stages"]["tests_chip"] = run_stage(
            "tests_chip",
            [sys.executable, "-m", "pytest", "tests_chip", "-q"],
            timeout=2400, out_dir=args.out,
        )

    sweep_prog = (
        "import sys; sys.path.insert(0, %r)\n"
        "from kubeflow_tpu.ops.flash_tuning import (sweep_blocks,\n"
        "    sweep_paged_pages)\n"
        "import json\n"
        "r64 = sweep_blocks(seq_lens=(128, 256, 512, 1024), head_dim=64)\n"
        "r128 = sweep_blocks(seq_lens=(256, 512), head_dim=128,\n"
        "                    candidates=((128,128),(128,256),(256,256)))\n"
        "# paged decode kernel: the sweepable block size IS the engine's\n"
        "# page_size (one kv grid step = one pool page HBM->VMEM)\n"
        "rp = sweep_paged_pages(head_dim=64, seq_tokens=1024)\n"
        "print(json.dumps({'d64': {k: v for k, v in r64.items()},\n"
        "                  'd128': {k: v for k, v in r128.items()},\n"
        "                  'paged_d64': rp},\n"
        "                 default=str))\n"
    ) % REPO
    report["stages"]["block_sweep"] = run_stage(
        "block_sweep", [sys.executable, "-c", sweep_prog],
        timeout=1800, out_dir=args.out,
    )

    report["stages"]["bench"] = run_stage(
        "bench", [sys.executable, "bench.py"], timeout=3600, out_dir=args.out,
    )

    mfu_prog = (
        "import sys; sys.path.insert(0, %r)\n"
        "import json, bench\n"
        "out = {}\n"
        "for B, S in ((32, 128), (64, 128), (32, 512), (64, 512)):\n"
        "    bench.BERT_BATCH, bench.BERT_SEQ = B, S\n"
        "    r = bench.bench_bert()\n"
        "    out[f'B{B}/S{S}'] = {'ms': r['value'],\n"
        "        'mfu': r['detail'].get('mfu_pct_vs_v5e_peak')}\n"
        "    print(f'B{B}/S{S}:', out[f'B{B}/S{S}'], flush=True)\n"
        "print('SWEEP', json.dumps(out))\n"
    ) % REPO
    report["stages"]["mfu_sweep"] = run_stage(
        "mfu_sweep", [sys.executable, "-c", mfu_prog],
        timeout=3600, out_dir=args.out,
    )

    # 5. profile artifact for the MFU gap analysis (VERDICT item 3:
    # "profile artifact checked in"): trace ~20 BERT steps
    profile_prog = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax, bench\n"
        "import jax.numpy as jnp, numpy as np, optax\n"
        "from kubeflow_tpu.core.mesh import MeshSpec\n"
        "from kubeflow_tpu.data.synthetic import TokenLMDataset, "
        "local_shard_iterator\n"
        "from kubeflow_tpu.models.bert import bert_base, make_mlm_init_fn, "
        "make_mlm_loss_fn, BertForMaskedLM\n"
        "from kubeflow_tpu.train.loop import TrainConfig, Trainer\n"
        "cfg = bert_base(dtype=jnp.bfloat16)\n"
        "model = BertForMaskedLM(cfg)\n"
        "tr = Trainer(init_params=make_mlm_init_fn(model, 128, 32),\n"
        "    loss_fn=make_mlm_loss_fn(model), optimizer=optax.adamw(1e-4),\n"
        "    config=TrainConfig(mesh=MeshSpec.data_parallel(1),\n"
        "        global_batch=32, steps=50, log_every=1000,\n"
        "        check_numerics='off'))\n"
        "state = tr.init_state(); step = tr._build_step(state)\n"
        "ds = TokenLMDataset(vocab_size=cfg.vocab_size, seq_len=128)\n"
        "it = local_shard_iterator(ds, 32)\n"
        "batches = [tr.global_batch_array(next(it)) for _ in range(4)]\n"
        "for i in range(10):\n"
        "    state, m = step(state, batches[i %% 4])\n"
        "np.asarray(jax.tree_util.tree_leaves(m)[0])\n"
        "with jax.profiler.trace(%r):\n"
        "    for i in range(20):\n"
        "        state, m = step(state, batches[i %% 4])\n"
        "    np.asarray(jax.tree_util.tree_leaves(m)[0])\n"
        "print('profile captured')\n"
    ) % (REPO, os.path.join(args.out, "bert_profile"))
    report["stages"]["profile"] = run_stage(
        "profile", [sys.executable, "-c", profile_prog],
        timeout=1200, out_dir=args.out,
    )

    report["finished"] = time.time()
    with open(os.path.join(args.out, "chip_session_report.json"), "w") as f:
        json.dump(report, f, indent=1)
    print("report:", os.path.join(args.out, "chip_session_report.json"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
