"""Deterministic synthetic datasets (zero-egress environment: no downloads).

Learnable-by-construction stand-ins for the reference examples' datasets
(MNIST for config 1, CIFAR-10 for config 2, token streams for BERT/LM —
SURVEY.md §6): each class has a fixed random prototype and samples are
noisy prototypes, so a real model's loss demonstrably falls while shapes,
dtypes and pipelines match the real thing. Fully seeded: the same (seed,
epoch, index) yields the same example on every host — which is what makes
*sharded* iteration correct without any cross-host coordination.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class ClassPrototypeDataset:
    """Image-classification surrogate (MNIST: 28x28x1/10, CIFAR: 32x32x3/10)."""

    image_shape: tuple[int, ...] = (28, 28, 1)
    num_classes: int = 10
    noise: float = 0.8
    seed: int = 0

    def prototypes(self) -> np.ndarray:
        rng = np.random.RandomState(self.seed)
        return rng.randn(self.num_classes, *self.image_shape).astype(np.float32)

    def batch(self, batch_size: int, *, step: int, offset: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Deterministic batch for (step, offset): images NHWC f32, labels i32."""
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + step * 997 + offset) % (2**31 - 1)
        )
        labels = rng.randint(0, self.num_classes, size=batch_size)
        protos = self.prototypes()[labels]
        x = protos + self.noise * rng.randn(*protos.shape).astype(np.float32)
        return x.astype(np.float32), labels.astype(np.int32)


@dataclasses.dataclass(frozen=True)
class TokenLMDataset:
    """Language-model surrogate: order-k Markov token stream — has real
    structure (so LM loss falls below uniform entropy) without any corpus."""

    vocab_size: int = 512
    seq_len: int = 128
    seed: int = 0
    branching: int = 4  # successors per token: lower = more learnable

    def _table(self) -> np.ndarray:
        rng = np.random.RandomState(self.seed + 7)
        return rng.randint(
            0, self.vocab_size, size=(self.vocab_size, self.branching)
        )

    def batch(self, batch_size: int, *, step: int, offset: int = 0) -> dict:
        rng = np.random.RandomState(
            (self.seed * 999_983 + step * 1009 + offset * 13) % (2**31 - 1)
        )
        table = self._table()
        toks = np.empty((batch_size, self.seq_len + 1), dtype=np.int32)
        toks[:, 0] = rng.randint(0, self.vocab_size, size=batch_size)
        choices = rng.randint(0, self.branching, size=(batch_size, self.seq_len))
        for t in range(self.seq_len):
            toks[:, t + 1] = table[toks[:, t], choices[:, t]]
        return {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def local_shard_iterator(
    dataset,
    global_batch: int,
    *,
    process_index: int | None = None,
    process_count: int | None = None,
    start_step: int = 0,
    host_cost_ms: float = 0.0,
) -> Iterator:
    """Each host draws only its shard of every global batch.

    Determinism contract: host p of P takes ``offset=p`` of a batch that is
    globally defined by ``step`` — no host ever materializes the full batch
    (the input-pipeline discipline multi-host TPU training requires).

    ``host_cost_ms`` adds a fixed per-batch host delay emulating real input
    pipelines (decode/augment cost) — what the ``train_overlap`` microbench
    uses to make the device-prefetch overlap measurable on synthetic data.
    """
    import time

    import jax

    p = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by {n} hosts")
    local = global_batch // n
    step = start_step
    while True:
        if host_cost_ms > 0:
            time.sleep(host_cost_ms / 1e3)
        yield dataset.batch(local, step=step, offset=p)
        step += 1
