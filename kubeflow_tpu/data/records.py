"""Native record IO: the C++ input pipeline's Python surface.

Binds ``native/kftdata.cpp`` (built on demand with g++ into a cache dir)
via ctypes — no pybind11 in this image (SURVEY.md §0). The native library
owns the hot path: record reads, seeded shuffle, batch assembly, and a
bounded prefetch queue run in a C++ producer thread; Python receives one
contiguous buffer per batch and reshapes it zero-copy into numpy arrays
for ``jax.device_put`` / ``make_array_from_process_local_data``.

A record is a fixed-size pack of the example's fields (static shapes are
the XLA-friendly contract). ``RecordSpec`` maps field names/dtypes/shapes
to byte offsets; ``write_records`` / ``RecordLoader`` round-trip it.
``PyRecordLoader`` is the dependency-free fallback with identical
semantics for hosts without a toolchain.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator, Mapping, Sequence

import numpy as np

_SRC = Path(__file__).resolve().parent.parent.parent / "native" / "kftdata.cpp"
_MAGIC = 0x4B465452
_HEADER = np.dtype(
    [("magic", "<u4"), ("record_bytes", "<u4"), ("count", "<u8")]
)

_build_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeBuildError(RuntimeError):
    pass


def _cache_dir() -> Path:
    d = os.environ.get("KFT_NATIVE_CACHE") or os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "kubeflow_tpu",
    )
    Path(d).mkdir(parents=True, exist_ok=True)
    return Path(d)


def ensure_built(force: bool = False) -> Path:
    """Compile libkftdata.so if missing/stale; returns its path. Compiles
    to a per-pid temp name and publishes with os.replace so concurrent
    processes sharing the cache never dlopen a half-written .so."""
    out = _cache_dir() / "libkftdata.so"
    if not force and out.exists() and out.stat().st_mtime >= _SRC.stat().st_mtime:
        return out
    tmp = out.with_suffix(f".so.tmp-{os.getpid()}")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(tmp),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise NativeBuildError(
            f"g++ failed ({proc.returncode}):\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)
    return out


def load_library() -> ctypes.CDLL:
    global _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(str(ensure_built()))
        lib.kft_loader_open.restype = ctypes.c_void_p
        lib.kft_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_uint32,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int32,
        ]
        lib.kft_loader_next.restype = ctypes.c_int
        lib.kft_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kft_loader_close.argtypes = [ctypes.c_void_p]
        lib.kft_write_records.restype = ctypes.c_int64
        lib.kft_write_records.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint32, ctypes.c_uint64,
        ]
        lib.kft_last_error.restype = ctypes.c_char_p
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        load_library()
        return True
    except (NativeBuildError, OSError):
        return False


# --------------------------------------------------------------------- #
# record schema
# --------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: str
    shape: tuple[int, ...]

    @property
    def nbytes(self) -> int:
        return int(np.dtype(self.dtype).itemsize * int(np.prod(self.shape or (1,))))


@dataclasses.dataclass(frozen=True)
class RecordSpec:
    """Fixed-size record layout: fields packed back to back."""

    fields: tuple[Field, ...]

    @classmethod
    def of(cls, **fields: tuple[str, tuple[int, ...]]) -> "RecordSpec":
        return cls(
            tuple(Field(k, dt, tuple(shape)) for k, (dt, shape) in fields.items())
        )

    @property
    def record_bytes(self) -> int:
        return sum(f.nbytes for f in self.fields)

    def pack(self, batch: Mapping[str, np.ndarray]) -> np.ndarray:
        """Dict of [n, *shape] arrays → [n, record_bytes] u8."""
        n = len(next(iter(batch.values())))
        out = np.empty((n, self.record_bytes), dtype=np.uint8)
        off = 0
        for f in self.fields:
            arr = np.ascontiguousarray(batch[f.name], dtype=f.dtype)
            if arr.shape != (n, *f.shape):
                raise ValueError(
                    f"field {f.name!r}: expected {(n, *f.shape)}, got {arr.shape}"
                )
            out[:, off : off + f.nbytes] = arr.reshape(n, -1).view(np.uint8)
            off += f.nbytes
        return out

    def unpack(self, buf: np.ndarray, n: int) -> dict[str, np.ndarray]:
        """[batch, record_bytes] u8 → dict of [n, *shape] arrays (views)."""
        out = {}
        off = 0
        for f in self.fields:
            flat = buf[:n, off : off + f.nbytes]
            out[f.name] = (
                np.ascontiguousarray(flat).view(f.dtype).reshape(n, *f.shape)
            )
            off += f.nbytes
        return out


def write_records(
    path: str | os.PathLike,
    spec: RecordSpec,
    batch: Mapping[str, np.ndarray],
) -> int:
    """Write one KFTR file; returns the record count."""
    packed = spec.pack(batch)
    n = len(packed)
    lib = load_library()
    buf = np.ascontiguousarray(packed)
    written = lib.kft_write_records(
        str(path).encode(),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        spec.record_bytes,
        n,
    )
    if written < 0:
        raise OSError(lib.kft_last_error().decode())
    return int(written)


def write_records_py(
    path: str | os.PathLike,
    spec: RecordSpec,
    batch: Mapping[str, np.ndarray],
) -> int:
    """Pure-Python writer (same format)."""
    packed = spec.pack(batch)
    header = np.zeros(1, dtype=_HEADER)
    header["magic"] = _MAGIC
    header["record_bytes"] = spec.record_bytes
    header["count"] = len(packed)
    with open(path, "wb") as f:
        f.write(header.tobytes())
        f.write(packed.tobytes())
    return len(packed)


# --------------------------------------------------------------------- #
# loaders
# --------------------------------------------------------------------- #


class RecordLoader:
    """Iterate KFTR files as dict-of-ndarray batches via the C++ pipeline.

    ``shard_index/shard_count`` deterministically partition records across
    data-parallel processes; ``epochs=-1`` loops forever (training);
    ``shuffle_records=0/1`` disables shuffling (eval).
    """

    def __init__(
        self,
        files: Sequence[str | os.PathLike],
        spec: RecordSpec,
        *,
        batch_size: int,
        shuffle_records: int = 0,
        seed: int = 0,
        prefetch_batches: int = 2,
        drop_remainder: bool = True,
        shard_index: int = 0,
        shard_count: int = 1,
        epochs: int = 1,
    ):
        self.spec = spec
        self.batch_size = batch_size
        self._lib = load_library()
        arr = (ctypes.c_char_p * len(files))(
            *[str(f).encode() for f in files]
        )
        self._handle = self._lib.kft_loader_open(
            arr, len(files), spec.record_bytes, batch_size,
            shuffle_records, seed, 1, prefetch_batches,
            int(drop_remainder), shard_index, shard_count, epochs,
        )
        if not self._handle:
            raise OSError(self._lib.kft_last_error().decode())
        self._buf = np.empty(
            (batch_size, spec.record_bytes), dtype=np.uint8
        )

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return self

    def __next__(self) -> dict[str, np.ndarray]:
        if self._handle is None:
            raise StopIteration
        n = ctypes.c_uint64(0)
        ok = self._lib.kft_loader_next(
            self._handle,
            self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.byref(n),
        )
        if not ok:
            err = self._lib.kft_last_error().decode()
            self.close()
            if err:
                raise OSError(err)
            raise StopIteration
        # copy out of the reused fill buffer: unpack() returns views, and a
        # consumer holding batch N across next() must not see batch N+1
        return self.spec.unpack(self._buf.copy(), int(n.value))

    def skip(self, n: int) -> "RecordLoader":
        """Consume ``n`` batches without surfacing them (no unpack, no copy
        out of the fill buffer) — the ``start_step → iterator`` resume
        contract for record streams: a factory built as
        ``lambda s: make_loader(...).skip(s)`` replays the stream to the
        restored step so batches buffered in a prefetcher at shutdown are
        regenerated, never lost or double-consumed."""
        m = ctypes.c_uint64(0)
        for _ in range(n):
            ok = self._handle is not None and self._lib.kft_loader_next(
                self._handle,
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.byref(m),
            )
            if not ok:
                err = self._lib.kft_last_error().decode() if self._handle else ""
                self.close()
                if err:
                    raise OSError(err)
                break  # stream shorter than the skip: iteration will stop
        return self

    def close(self) -> None:
        if self._handle is not None:
            self._lib.kft_loader_close(self._handle)
            self._handle = None

    def __enter__(self) -> "RecordLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class PyRecordLoader:
    """Toolchain-free fallback with the same iteration contract (no
    background prefetch; fine for tests and small evals)."""

    def __init__(
        self,
        files: Sequence[str | os.PathLike],
        spec: RecordSpec,
        *,
        batch_size: int,
        shuffle_records: int = 0,
        seed: int = 0,
        drop_remainder: bool = True,
        shard_index: int = 0,
        shard_count: int = 1,
        epochs: int = 1,
        **_ignored,
    ):
        self.files = [str(f) for f in files]
        self.spec = spec
        self.batch_size = batch_size
        self.shuffle = shuffle_records
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.shard_index = shard_index
        self.shard_count = max(1, shard_count)
        self.epochs = epochs
        self._gen = self._iterate()

    def _epoch_records(self) -> Iterator[np.ndarray]:
        """One epoch's worth of this shard's records, in file order."""
        index = 0
        for path in self.files:
            raw = np.fromfile(path, dtype=np.uint8)
            header = raw[: _HEADER.itemsize].view(_HEADER)[0]
            rb = int(header["record_bytes"])
            if header["magic"] != _MAGIC or rb != self.spec.record_bytes:
                # same contract as the native loader: a record-size
                # mismatch must fail fast, never parse at wrong offsets
                raise OSError(f"bad header in {path}")
            body = raw[_HEADER.itemsize :].reshape(-1, rb)
            for rec in body:
                if index % self.shard_count == self.shard_index:
                    yield rec
                index += 1

    def _iterate(self) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.RandomState(self.seed % (2**31 - 1))
        pool: list[np.ndarray] = []
        pending: list[np.ndarray] = []

        def emit(rec):
            pending.append(rec)
            if len(pending) == self.batch_size:
                buf = np.stack(pending)
                pending.clear()
                return buf
            return None

        def drain(keep: int):
            # Fisher-Yates-style random draws, same shape as the native
            # loader's drain_pool (kftdata.cpp): pick, swap last into the
            # hole, emit.
            while len(pool) > keep:
                pick = rng.randint(len(pool))
                pool[pick], pool[-1] = pool[-1], pool[pick]
                out = emit(pool.pop())
                if out is not None:
                    yield self.spec.unpack(out, len(out))

        # Epochs are explicit so the pool FULLY drains at every epoch
        # boundary — the native loader calls drain_pool(true) per epoch, so
        # records never mix across epochs regardless of which loader
        # make_loader picks. The partial batch (`pending`) DOES persist
        # across epochs in both loaders.
        epoch = 0
        while self.epochs < 0 or epoch < self.epochs:
            for rec in self._epoch_records():
                if self.shuffle > 1:
                    pool.append(rec.copy())
                    if len(pool) >= self.shuffle:
                        yield from drain(self.shuffle // 2)
                else:
                    out = emit(rec.copy())
                    if out is not None:
                        yield self.spec.unpack(out, len(out))
            yield from drain(0)
            epoch += 1
        if pending and not self.drop_remainder:
            buf = np.stack(pending)
            yield self.spec.unpack(buf, len(buf))

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def skip(self, n: int) -> "PyRecordLoader":
        """Same resume contract as :meth:`RecordLoader.skip`."""
        for _ in range(n):
            try:
                next(self._gen)
            except StopIteration:
                break
        return self

    def close(self) -> None:
        pass


def make_loader(*args, **kwargs):
    """RecordLoader when the native library builds, else PyRecordLoader."""
    if native_available():
        return RecordLoader(*args, **kwargs)
    return PyRecordLoader(*args, **kwargs)
