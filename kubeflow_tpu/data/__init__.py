"""Input pipeline: synthetic datasets and sharded host iterators."""

from kubeflow_tpu.data.synthetic import (  # noqa: F401
    ClassPrototypeDataset,
    TokenLMDataset,
    local_shard_iterator,
)
