"""SPMD training plane: loop, checkpointing, metric writers."""

from kubeflow_tpu.train.loop import TrainConfig, Trainer  # noqa: F401
from kubeflow_tpu.train.metrics import MetricWriter  # noqa: F401
from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer  # noqa: F401
