"""The SPMD training loop: pjit-sharded steps over a MeshSpec.

The data-plane analog of the reference's ``DDP(model); loss.backward();
allreduce; optimizer.step()`` hot loop (SURVEY.md §3.1): here the whole step
is ONE jitted SPMD program — XLA emits the gradient psum onto ICI from the
sharding layout (params replicated/sharded per rules, batch sharded on the
data axes), so there is no explicit allreduce call to schedule or bucket.
"""

from __future__ import annotations

import dataclasses
import logging
import signal as _signal
import threading
import time
from collections.abc import Iterator
from typing import Any, Callable, Iterable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.mesh import (
    Axis, MeshSpec, build_mesh, mesh_context, per_device_batch,
)
from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer
from kubeflow_tpu.train.metrics import MetricWriter

logger = logging.getLogger(__name__)

#: batch pytrees are sharded over the data-like axes on dim 0.
BATCH_SPEC = P((Axis.DATA, Axis.FSDP))

#: the container convention for SIGTERM death (128+15) — retryable under
#: ``RestartPolicy.EXIT_CODE``, so a preempted gang restarts and resumes.
PREEMPTED_EXIT_CODE = 143


class Preempted(SystemExit):
    """Raised out of ``fit`` after a preemption notice was honored: the
    final checkpoint is on disk and the process should exit ``code`` (143,
    a retryable infra code under ``RestartPolicy.EXIT_CODE``)."""

    def __init__(self, step: int, code: int = PREEMPTED_EXIT_CODE):
        super().__init__(code)
        self.step = step


class TrainState(train_state.TrainState):
    """flax TrainState + a dropout/noise RNG folded per step."""

    rng: jax.Array


@dataclasses.dataclass
class TrainConfig:
    mesh: MeshSpec
    global_batch: int
    steps: int
    log_every: int = 10
    seed: int = 0
    checkpoint: CheckpointConfig | None = None
    #: True/"auto": restore from the newest checkpoint step whose sha256
    #: manifest verifies, walking past corrupt steps (train/checkpoint.py);
    #: False: always start from step 0.
    resume: bool | str = True
    metrics_logdir: str | None = None
    #: install a SIGTERM handler for the duration of ``fit`` (main thread
    #: only — elsewhere the signal machinery is unavailable and the flag
    #: can still be set via ``Trainer.request_preemption``). On delivery
    #: the loop finishes the in-flight step, force-saves a final
    #: preemption checkpoint, and raises ``Preempted`` (SystemExit 143 —
    #: retryable under ``RestartPolicy.EXIT_CODE``, so the orchestrator
    #: restarts the gang and training resumes at the exact next step).
    handle_sigterm: bool = True
    donate_state: bool = True
    #: in-graph gradient accumulation: the jitted step scans over
    #: ``grad_accum_steps`` microbatches (one optimizer update, donated
    #: carry) so ``global_batch`` scales past HBM limits with unchanged
    #: numerics — losses match accum=1 to fp32 tolerance for equal-size
    #: microbatches (mean of microbatch means == full-batch mean).
    grad_accum_steps: int = 1
    #: device-prefetch depth (train/prefetch.py): how many already-placed
    #: global batches the background producer keeps ahead of the step
    #: stream. 0 = fully inline (no thread). Each buffered batch holds
    #: device memory, so this is an HBM budget knob too.
    prefetch_depth: int = 2
    #: numerics discipline (SURVEY.md §5.2):
    #: - "metrics"  (default): the MetricWriter raises NonFiniteMetricError
    #:   the first time a logged metric is NaN/inf — zero overhead on the
    #:   hot path, detection within log_every steps.
    #: - "checkify": every step runs under jax.experimental.checkify
    #:   float_checks — the raise names the exact op and source line that
    #:   produced the first NaN/inf, at ~2x step cost. For debugging runs.
    #: - "off": no checks (bench/microbenchmark mode).
    check_numerics: str = "metrics"
    #: sets jax_debug_nans for the whole process (eager-level NaN isolation;
    #: orthogonal to checkify — use when the NaN is outside the step).
    debug_nans: bool = False

    def __post_init__(self) -> None:
        if self.check_numerics not in ("off", "metrics", "checkify"):
            # a typo here must not silently degrade to default behavior
            raise ValueError(
                f"check_numerics={self.check_numerics!r}; expected "
                "'off', 'metrics', or 'checkify'"
            )
        if self.grad_accum_steps < 1:
            raise ValueError(
                f"grad_accum_steps must be >= 1, got {self.grad_accum_steps}"
            )
        if self.prefetch_depth < 0:
            raise ValueError(
                f"prefetch_depth must be >= 0, got {self.prefetch_depth}"
            )
        if not isinstance(self.resume, bool) and self.resume != "auto":
            # a typo must not silently disable (or mis-enable) resume
            raise ValueError(
                f"resume={self.resume!r}; expected True, False, or 'auto'"
            )
        if self.global_batch % self.grad_accum_steps:
            raise ValueError(
                f"global batch {self.global_batch} not divisible by "
                f"grad_accum_steps={self.grad_accum_steps}"
            )


class Trainer:
    """Generic SPMD trainer.

    ``loss_fn(params, batch, rng) -> (loss, aux_dict)`` — differentiated on
    arg 0. ``init_params(rng) -> params``. ``state_spec_fn`` maps the param
    tree to PartitionSpecs (None = fully replicated = pure DP); FSDP/TP rules
    from ``kubeflow_tpu.parallel`` plug in here.
    """

    def __init__(
        self,
        *,
        init_params: Callable[[jax.Array], Any],
        loss_fn: Callable[[Any, Any, jax.Array], tuple[jax.Array, Mapping[str, Any]]],
        optimizer: Any,
        config: TrainConfig,
        param_spec_fn: Callable[[Any], Any] | None = None,
    ):
        from kubeflow_tpu.core.compcache import enable_compilation_cache

        enable_compilation_cache()  # restarts skip the train-step compile
        self.config = config
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.init_params_fn = init_params
        self.param_spec_fn = param_spec_fn
        self.mesh: Mesh = build_mesh(config.mesh)
        self.batch_sharding = NamedSharding(self.mesh, BATCH_SPEC)
        self.repl = NamedSharding(self.mesh, P())
        self._step_fn = None
        self._state_sharding = None
        #: preemption notice (SIGTERM or an explicit call): the loop checks
        #: it between steps and performs the graceful-exit protocol.
        self._preempt = threading.Event()

    def request_preemption(self) -> None:
        """Deliver a preemption notice in-process (what the SIGTERM handler
        calls): the loop saves a final checkpoint and raises ``Preempted``
        at the next step boundary. Safe from any thread."""
        self._preempt.set()

    # ------------------------------------------------------------------ #

    def init_state(self) -> TrainState:
        """Initialize params ON the mesh with their target shardings (jit of
        init so large params materialize sharded, never on one host)."""
        rng = jax.random.PRNGKey(self.config.seed)

        def mk(rng):
            params = self.init_params_fn(rng)
            return TrainState.create(
                apply_fn=None,
                params=params,
                tx=self.optimizer,
                rng=rng,
            )

        # set_mesh: models read the context mesh for activation sharding
        # constraints and shard_map attention (ring/ulysses/flash).
        with mesh_context(self.mesh):
            if self.param_spec_fn is None:
                out_shardings = self.repl
            else:
                abstract = jax.eval_shape(mk, rng)
                specs = self._specs_for(abstract)
                out_shardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(self.mesh, s), specs
                )
            # SPMD determinism contract (SURVEY.md §5.2): the same seed
            # must yield the same params on EVERY mesh layout. The legacy
            # threefry lowering is not sharding-invariant — jitted init
            # with sharded out_shardings on a hybrid (data x fsdp) mesh
            # draws different values than the replicated/pure layouts; the
            # partitionable lowering derives each element's bits from its
            # global index alone. Scoped to THIS trace/compile (restored
            # after) so the process-wide PRNG stream is untouched for
            # everything else running in-process.
            prev = jax.config.jax_threefry_partitionable
            jax.config.update("jax_threefry_partitionable", True)
            try:
                state = jax.jit(mk, out_shardings=out_shardings)(rng)
            finally:
                jax.config.update("jax_threefry_partitionable", prev)
        self._state_sharding = jax.tree_util.tree_map(lambda x: x.sharding, state)
        return state

    def _specs_for(self, abstract_state) -> Any:
        """PartitionSpec tree for the full TrainState: params per rules,
        optimizer-state subtrees that mirror the params structure get the
        same specs (ZeRO-style colocation), everything else replicated.

        Matching is *structural* (a subtree with the params' treedef), not
        by shape/dtype — same-shaped params with different specs must not
        collide."""
        param_specs = jax.tree_util.tree_map(
            lambda s: s if isinstance(s, P) else (P() if s is None else P(*s)),
            self.param_spec_fn(abstract_state.params),
            is_leaf=lambda x: x is None or isinstance(x, (P, tuple)),
        )
        if jax.tree_util.tree_structure(param_specs) != jax.tree_util.tree_structure(
            abstract_state.params
        ):
            raise ValueError(
                "param_spec_fn must return a tree with the params' structure"
            )
        params_def = jax.tree_util.tree_structure(abstract_state.params)

        def is_params_like(node) -> bool:
            try:
                return jax.tree_util.tree_structure(node) == params_def
            except Exception:  # noqa: BLE001 — unhashable/odd nodes aren't params
                return False

        return jax.tree_util.tree_map(
            lambda node: (
                param_specs
                if is_params_like(node)
                else jax.tree_util.tree_map(lambda _: P(), node)
            ),
            abstract_state,
            is_leaf=is_params_like,
        )

    # ------------------------------------------------------------------ #

    def _build_step(self, state: TrainState):
        loss_fn = self.loss_fn
        accum = self.config.grad_accum_steps
        micro_sharding = NamedSharding(self.mesh, P(None, *BATCH_SPEC))

        def grads_of(params, batch, rng):
            return jax.value_and_grad(loss_fn, has_aux=True)(params, batch, rng)

        def step(state: TrainState, batch):
            rng = jax.random.fold_in(state.rng, state.step)
            if accum == 1:
                (loss, aux), grads = grads_of(state.params, batch, rng)
            else:
                # [B, ...] -> [accum, B/accum, ...]: microbatches stay
                # sharded over the data axes on their own dim 0, the scan
                # axis is replicated — one optimizer update at the end, so
                # numerics match accum=1 (mean of equal-size microbatch
                # means == full-batch mean) while peak activation memory
                # drops by ~accum.
                micro = jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(
                        x.reshape((accum, x.shape[0] // accum) + x.shape[1:]),
                        micro_sharding,
                    ),
                    batch,
                )
                params = state.params

                def body(carry, xs):
                    g_acc, loss_acc, aux_acc = carry
                    mb, i = xs
                    (loss, aux), grads = grads_of(
                        params, mb, jax.random.fold_in(rng, i)
                    )
                    carry = (
                        jax.tree_util.tree_map(jnp.add, g_acc, grads),
                        loss_acc + loss,
                        jax.tree_util.tree_map(jnp.add, aux_acc, aux),
                    )
                    return carry, None

                # microbatch 0 seeds the carry (no zeros-tree dtype
                # guessing); the scan covers 1..accum-1 with donated carry
                (loss_0, aux_0), g_0 = grads_of(
                    params,
                    jax.tree_util.tree_map(lambda x: x[0], micro),
                    jax.random.fold_in(rng, 0),
                )
                (g_sum, loss_sum, aux_sum), _ = jax.lax.scan(
                    body,
                    (g_0, loss_0, aux_0),
                    (
                        jax.tree_util.tree_map(lambda x: x[1:], micro),
                        jnp.arange(1, accum),
                    ),
                )
                grads = jax.tree_util.tree_map(lambda g: g / accum, g_sum)
                loss = loss_sum / accum
                aux = jax.tree_util.tree_map(lambda a: a / accum, aux_sum)
            new_state = state.apply_gradients(grads=grads)
            metrics = {"loss": loss, **aux}
            return new_state, metrics

        state_shardings = self._state_sharding
        if self.config.check_numerics == "checkify":
            from jax.experimental import checkify

            # No donation and inferred shardings: a failed step must leave
            # the caller's state alive so the error can be reported and the
            # run resumed from checkpoint.
            checked = jax.jit(
                checkify.checkify(step, errors=checkify.float_checks)
            )

            def run(state: TrainState, batch):
                err, out = checked(state, batch)
                checkify.check_error(err)  # located: op + source line
                return out

            return run
        return jax.jit(  # kft: noqa[jax-sync] — fit-owned donation: restored trees are re-homed through the non-donating identity before the first donated call
            step,
            in_shardings=(state_shardings, self.batch_sharding),
            out_shardings=(state_shardings, self.repl),
            donate_argnums=(0,) if self.config.donate_state else (),
        )

    def global_batch_array(self, local_batch) -> Any:
        """Process-local numpy batch shards → one global sharded pytree.

        Thread-safe: the device prefetcher calls this from its producer
        thread (explicit NamedSharding, no ambient-mesh dependence), so the
        H2D copy overlaps the running step.
        """
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                self.batch_sharding, np.asarray(x)  # kft: noqa[jax-sync] — operand is the host-resident input batch, pre-placement; no device value exists yet
            ),
            local_batch,
        )

    def local_batch_size(self, process_count: int | None = None) -> int:
        n = jax.process_count() if process_count is None else process_count
        if self.config.global_batch % n:
            raise ValueError(
                f"global batch {self.config.global_batch} not divisible by "
                f"{n} processes — floor division would silently drop "
                f"{self.config.global_batch % n} examples per step"
            )
        return self.config.global_batch // n

    # ------------------------------------------------------------------ #

    def fit(
        self,
        data: Iterator[Any] | Iterable[Any] | Callable[[int], Iterator[Any]],
        *,
        writer: MetricWriter | None = None,
        hooks: list[Callable[[int, Mapping[str, float]], None]] | None = None,
    ) -> tuple[TrainState, list[dict]]:
        """Train for ``config.steps``.

        ``data`` is ideally a *factory* ``start_step -> iterator`` so that a
        checkpoint resume continues the stream where training resumes rather
        than replaying batch 0; a plain iterator is accepted for
        non-resuming runs.
        """
        cfg = self.config
        per_device_batch(cfg.global_batch, cfg.mesh)  # validate divisibility
        # microbatches must also land evenly on the batch partitions, and
        # the per-process shard must be whole (no silent truncation)
        per_device_batch(cfg.global_batch // cfg.grad_accum_steps, cfg.mesh)
        self.local_batch_size()
        if cfg.debug_nans:
            jax.config.update("jax_debug_nans", True)
        own_writer = writer is None
        writer = writer or MetricWriter(
            cfg.metrics_logdir,
            is_writer=jax.process_index() == 0,
            nan_alarm=cfg.check_numerics != "off",
        )

        # Liveness: when launched by the orchestrator, beat automatically so
        # the heartbeat supervisor can tell "compiling/training" from "hung"
        # (SURVEY.md §5.3). No-op outside a gang.
        from kubeflow_tpu.obs.heartbeat import HeartbeatWriter

        hb = HeartbeatWriter.from_env()
        if hb is not None:
            hb.start()

        # Preemption notice: SIGTERM (a slice being reclaimed) sets a flag
        # the loop honors at the next step boundary — final checkpoint,
        # then exit 143 so RestartPolicy.EXIT_CODE treats it as retryable
        # infra. Signal handlers only install on the main thread; elsewhere
        # (a fit driven from a server thread) request_preemption() remains
        # the delivery path.
        self._preempt.clear()
        prev_sigterm = None
        sigterm_installed = False
        if (
            cfg.handle_sigterm
            and threading.current_thread() is threading.main_thread()
        ):
            def _on_sigterm(signum, frame):  # noqa: ARG001
                logger.warning(
                    "SIGTERM received: taking a preemption checkpoint, "
                    "then exiting %d", PREEMPTED_EXIT_CODE,
                )
                self._preempt.set()

            try:
                prev_sigterm = _signal.signal(_signal.SIGTERM, _on_sigterm)
                sigterm_installed = True
            except (ValueError, OSError):  # exotic embeddings
                sigterm_installed = False

        state = self.init_state()
        ckpt: Checkpointer | None = None
        start_step = 0
        if cfg.checkpoint is not None:
            ckpt = Checkpointer(cfg.checkpoint)
            if cfg.resume and ckpt.latest_step() is not None:
                # Walks back to the newest step whose sha256 manifest
                # verifies — a corrupt latest checkpoint costs one save
                # interval, not the run (train/checkpoint.py).
                state = ckpt.restore(state)
                # Re-home the restored tree into XLA-owned buffers (a
                # non-donating jitted identity is a sharded copy). Orbax
                # hands back arrays whose buffers the CPU backend aliases
                # from host memory; donating those into the first step makes
                # XLA reuse/free memory it doesn't own — deterministic heap
                # corruption the moment anything syncs on that step's
                # outputs (which the metric drain now does every step).
                state = jax.jit(lambda s: s)(state)
                start_step = int(jax.device_get(state.step))
                logger.info("resumed from checkpoint at step %d", start_step)
                if jax.process_index() == 0:
                    # machine-readable resume marker for supervisors and
                    # the chaos harness (exact-step resume assertions)
                    print(f"resume_step={start_step}", flush=True)
        if callable(data) and not hasattr(data, "__next__"):
            it = iter(data(start_step))
        else:
            if start_step and not isinstance(data, Iterator):
                logger.warning(
                    "resuming at step %d with a plain iterator: the data "
                    "stream restarts from its beginning; pass a "
                    "start_step->iterator factory for a faithful resume",
                    start_step,
                )
            it = iter(data)

        step_fn = self._build_step(state)
        history: list[dict] = []
        t_last = time.perf_counter()
        last_logged = start_step
        try:
            with mesh_context(self.mesh):
                return self._fit_loop(
                    state, step_fn, it, ckpt, writer, hooks, history,
                    start_step, t_last, last_logged, hb,
                )
        finally:
            if sigterm_installed:
                try:
                    _signal.signal(
                        _signal.SIGTERM,
                        prev_sigterm if prev_sigterm is not None
                        else _signal.SIG_DFL,
                    )
                except (ValueError, OSError):
                    pass
            if hb is not None:
                hb.stop()
            if ckpt is not None:
                ckpt.close()  # preemption path: blocks until durable
            if own_writer:
                writer.close()

    def _fit_loop(
        self, state, step_fn, it, ckpt, writer, hooks, history,
        start_step, t_last, last_logged, hb=None,
    ):
        """The overlapped hot loop (train/prefetch.py):

        - input: a bounded producer thread assembles + places batches
          ``prefetch_depth`` ahead, so ``next(it)`` + H2D never sit between
          step dispatches;
        - output: every step's *device* metrics go to a drain thread that
          blocks on them there — the loop thread never syncs on the step
          stream, and the writer's NaN alarm re-raises here via ``poll()``
          with bounded lag;
        - timing: the first step is blocked on explicitly (``compile_ms``),
          and the rate clock re-stamps at its readiness so the first logged
          ``steps_per_sec`` window measures steady state, not XLA.
        """
        from kubeflow_tpu.train.prefetch import MetricsDrain, make_fetcher

        cfg = self.config
        fetcher = make_fetcher(
            it, self.global_batch_array, depth=cfg.prefetch_depth
        )
        # the drain stamps every completed step into the heartbeat file, so
        # the supervisor's progress watchdog sees real trainer advancement
        # (not just thread liveness) without touching the hot loop thread
        drain = MetricsDrain(
            writer, history=history, hooks=hooks, heartbeat=hb
        )
        compile_ms = None
        try:
            for step in range(start_step, cfg.steps):
                drain.poll()  # bounded-lag NaN alarm / drain-error surface
                if self._preempt.is_set():
                    self._preemption_save(ckpt, state, step)
                    raise Preempted(step)
                batch = next(fetcher)
                if compile_ms is None:
                    # block on step 1 so the compile is measured apart; the
                    # drain's rate clock starts at this step's readiness, so
                    # no later steps_per_sec window includes it. Sync via a
                    # HOST TRANSFER of a metric scalar, not
                    # block_until_ready: a transfer cannot complete before
                    # the compute producing it (the bench.py contract), and
                    # block_until_ready on this jaxlib corrupts the heap
                    # when the donated state came from an Orbax restore.
                    t0 = time.perf_counter()
                    state, metrics = step_fn(state, batch)
                    np.asarray(jax.tree_util.tree_leaves(metrics)[0])  # kft: noqa[jax-sync] — the one sanctioned sync: compile measurement via single-leaf host transfer, once, before steady state
                    compile_ms = (time.perf_counter() - t0) * 1e3
                else:
                    state, metrics = step_fn(state, batch)
                if ckpt is not None:
                    ckpt.save(step + 1, state)
                is_log = (step + 1) % cfg.log_every == 0 or step + 1 == cfg.steps
                extra = None
                if is_log:
                    now = time.perf_counter()
                    # dispatch-side rate (compile-inclusive, like the old
                    # loop): the drain only falls back to it for the
                    # degenerate first window where no ready-to-ready
                    # interval exists yet
                    elapsed = max(now - t_last, 1e-9)
                    extra = {
                        "fallback_steps_per_sec": max(
                            step + 1 - last_logged, 1
                        ) / elapsed,
                        **fetcher.window_stats(),
                    }
                    if compile_ms:
                        # first log boundary: report the compile apart,
                        # exactly once
                        extra["compile_ms"] = compile_ms
                        compile_ms = 0.0
                    t_last, last_logged = now, step + 1
                drain.put(step + 1, metrics, log=is_log, extra=extra)
            drain.close()  # flush; surfaces a pending NaN alarm
        finally:
            fetcher.close()
            drain.shutdown()  # idempotent, no-raise (exception paths)
            if ckpt is not None:
                self._final_save(ckpt, state)
        drain.poll()
        return state, history

    @staticmethod
    def _preemption_save(
        ckpt: Checkpointer | None, state: TrainState, step: int
    ) -> None:
        """The graceful half of preemption: force-save the current state
        (the loop-top invariant is ``state.step == step``) so the restarted
        gang resumes at exactly ``step + 1``. Durability is guaranteed by
        ``ckpt.close()`` in ``fit``'s finally before the exit code lands."""
        if ckpt is not None and ckpt.latest_step() != step:
            ckpt.save(step, state, force=True)
        logger.warning(
            "preempted at step %d: final checkpoint %s; exiting %d",
            step,
            "saved" if ckpt is not None else "unavailable (no checkpoint "
            "config)",
            PREEMPTED_EXIT_CODE,
        )

    @staticmethod
    def _final_save(ckpt: Checkpointer, state: TrainState) -> None:
        """Best-effort final checkpoint; with donated buffers the state may
        be dead if the last step raised — never mask the original error."""
        leaves = jax.tree_util.tree_leaves(state)
        if any(
            isinstance(x, jax.Array) and x.is_deleted() for x in leaves
        ):
            logger.warning("skipping final checkpoint: state buffers donated "
                           "to a failed step")
            return
        final_step = int(jax.device_get(state.step))
        if ckpt.latest_step() != final_step:
            ckpt.save(final_step, state, force=True)
