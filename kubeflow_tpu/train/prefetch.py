"""The training hot loop's overlap layer: device prefetch + async metric drain.

The reference stack's throughput comes from exactly two overlaps
(SURVEY.md §3.1): DataLoader workers assemble batches while the step runs,
and CUDA-stream async dispatch keeps the device queue full while the Python
loop races ahead. The SPMD analog here:

- :class:`DevicePrefetcher` — a bounded background producer pulls host
  batches from the iterator (the native ``data/records.py`` C++ queue or any
  Python iterator) and performs the H2D placement
  (``make_array_from_process_local_data`` with the batch sharding) N batches
  ahead, so host batch assembly and H2D copies fully overlap the running
  step. The consumer only waits when the producer is behind — that wait is
  the window's ``data_stall_ms``.

- :class:`MetricsDrain` — the jitted step returns *device* metrics; a
  background thread blocks on them (``jax.block_until_ready``), so the loop
  thread never syncs on the step stream. The gap between consecutive ready
  times IS the device step time (``device_step_ms``). Log-boundary items are
  converted to floats and fed to the ``MetricWriter`` — whose NaN alarm now
  fires on this thread and is re-raised on the loop thread at the next
  ``poll()``/``close()``, i.e. with bounded detection lag instead of a
  per-window pipeline drain.

Both threads are named ``kft-*`` and joined by ``close()``; a crashed
producer/drain never deadlocks the loop (sentinels + discard-after-failure).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Mapping

PREFETCH_THREAD_NAME = "kft-prefetch"
DRAIN_THREAD_NAME = "kft-metrics-drain"


class _End:
    """Producer sentinel: end-of-stream or a carried producer error."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException | None = None):
        self.error = error


class _Fetcher:
    """Interface shared by the threaded and inline fetchers."""

    def __iter__(self):
        return self

    def __next__(self) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    def window_stats(self) -> dict[str, float]:  # pragma: no cover - abstract
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class DevicePrefetcher(_Fetcher):
    """Bounded background producer: host batches → device-resident arrays.

    ``place`` maps one host batch to its global sharded device form
    (``Trainer.global_batch_array``). ``depth`` bounds how many *placed*
    batches may be in flight — placed batches hold device memory, so the
    bound is an HBM budget, not just a queue size.

    Shutdown contract: ``close()`` is idempotent, unblocks a producer parked
    on a full queue, joins the thread, and discards any buffered batches.
    Buffered batches are *consumed from the iterator* — a checkpoint-resuming
    caller must therefore rebuild the stream from a ``start_step → iterator``
    factory rather than reuse a partially-drained iterator (see
    ``Trainer.fit``).
    """

    def __init__(
        self,
        it: Iterator[Any] | Iterable[Any],
        place: Callable[[Any], Any],
        *,
        depth: int = 2,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = iter(it)
        self._place = place
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._stall_s = 0.0
        self._h2d_s = 0.0
        self._batches = 0
        self._thread = threading.Thread(
            target=self._run, name=PREFETCH_THREAD_NAME, daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ #

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    host = next(self._it)
                except StopIteration:
                    self._put(_End())
                    return
                t0 = time.perf_counter()
                placed = self._place(host)
                dt = time.perf_counter() - t0
                with self._lock:
                    self._h2d_s += dt
                if not self._put(placed):
                    return
        except BaseException as e:  # noqa: BLE001 — carried to the consumer
            self._put(_End(e))

    def _put(self, item: Any) -> bool:
        """Queue.put that never outlives close(): False once stopped."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------------ #

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # no sentinel and no producer: defensive fail-fast
                    # rather than a silent hang
                    raise RuntimeError(
                        "prefetch producer thread died without a sentinel"
                    )
        if isinstance(item, _End):
            self.close()
            if item.error is not None:
                raise item.error
            raise StopIteration
        with self._lock:
            self._stall_s += time.perf_counter() - t0
            self._batches += 1
        return item

    def window_stats(self) -> dict[str, float]:
        """Pop the overlap counters accumulated since the last call.

        ``data_stall_ms``/``h2d_ms`` are per-batch means over the window so
        they read on the same scale as ``device_step_ms``.
        """
        with self._lock:
            stall, h2d, n = self._stall_s, self._h2d_s, self._batches
            self._stall_s = self._h2d_s = 0.0
            self._batches = 0
        scale = 1e3 / max(n, 1)
        return {"data_stall_ms": stall * scale, "h2d_ms": h2d * scale}

    def close(self) -> None:
        self._stop.set()
        # unblock a producer parked on a full queue, drop buffered batches
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)


class InlineFetcher(_Fetcher):
    """``prefetch_depth=0`` path: same interface, no thread.

    ``next(it)`` + placement run inline on the caller, and their full cost
    is charged to ``data_stall_ms``/``h2d_ms`` — so the gauges stay honest
    about what turning prefetch off costs.
    """

    def __init__(self, it: Iterator[Any] | Iterable[Any], place: Callable[[Any], Any]):
        self._it = iter(it)
        self._place = place
        self._stall_s = 0.0
        self._h2d_s = 0.0
        self._batches = 0

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        host = next(self._it)
        t1 = time.perf_counter()
        placed = self._place(host)
        self._stall_s += t1 - t0
        self._h2d_s += time.perf_counter() - t1
        self._batches += 1
        return placed

    def window_stats(self) -> dict[str, float]:
        stall, h2d, n = self._stall_s, self._h2d_s, self._batches
        self._stall_s = self._h2d_s = 0.0
        self._batches = 0
        scale = 1e3 / max(n, 1)
        return {"data_stall_ms": stall * scale, "h2d_ms": h2d * scale}

    def close(self) -> None:
        pass


def make_fetcher(
    it: Iterator[Any] | Iterable[Any],
    place: Callable[[Any], Any],
    *,
    depth: int,
) -> _Fetcher:
    """Depth 0 → inline; depth >= 1 → threaded device prefetch."""
    if depth <= 0:
        return InlineFetcher(it, place)
    return DevicePrefetcher(it, place, depth=depth)


# --------------------------------------------------------------------- #
# metric drain
# --------------------------------------------------------------------- #

_STOP = object()


class MetricsDrain:
    """Asynchronous consumer of per-step device metrics.

    The loop thread hands over every step's device-metric pytree via
    :meth:`put` (a bounded, non-syncing enqueue) and never reads device
    values itself. This thread blocks until each step's metrics are ready;
    log-boundary items are additionally converted to scalars and written.

    Error contract: any exception here (``NonFiniteMetricError`` from the
    writer's NaN alarm above all) is stored, the thread keeps *draining and
    discarding* so the loop can never deadlock on a full queue, and the
    error is re-raised on the loop thread at the next :meth:`poll` or
    :meth:`close` — detection lag is bounded by the queue depth.
    """

    def __init__(
        self,
        writer,
        *,
        history: list[dict],
        hooks=(),
        depth: int = 64,
        heartbeat=None,
    ):
        from kubeflow_tpu.train.metrics import set_overlap_gauges, _to_scalar

        self._to_scalar = _to_scalar
        self._set_gauges = set_overlap_gauges
        self._writer = writer
        #: obs.heartbeat.HeartbeatWriter (or None): every drained step is
        #: stamped into the beat file, so the orchestrator supervisor's
        #: ``progress_timeout_seconds`` watches real step advancement — a
        #: wedged loop thread with a live beat thread is detectable.
        self._hb = heartbeat
        self._history = history
        self._hooks = tuple(hooks or ())
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._error: BaseException | None = None
        self._raised = False
        self._last_ready: float | None = None
        self._win_step_s = 0.0
        self._win_steps = 0
        self._t_logged: float | None = None
        self._step_logged: int | None = None
        self._thread = threading.Thread(
            target=self._run, name=DRAIN_THREAD_NAME, daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ #

    def put(
        self,
        step: int,
        metrics: Mapping[str, Any],
        *,
        log: bool,
        extra: Mapping[str, float] | None = None,
    ) -> None:
        """Enqueue one step's device metrics; throttles (never deadlocks)."""
        item = (step, metrics, log, dict(extra or ()))
        while True:
            try:
                self._q.put(item, timeout=0.5)
                return
            except queue.Full:
                if not self._thread.is_alive():
                    return  # poll()/close() will surface whatever killed it

    def poll(self) -> None:
        """Re-raise a drain-side error on the caller (bounded-lag alarm)."""
        if self._error is not None and not self._raised:
            self._raised = True
            raise self._error

    def close(self) -> None:
        """Flush + join, then surface any pending drain error."""
        self.shutdown()
        self.poll()

    def shutdown(self) -> None:
        """Idempotent no-raise join (exception-path cleanup)."""
        if self._thread.is_alive():
            while True:
                try:
                    self._q.put(_STOP, timeout=0.5)
                    break
                except queue.Full:
                    if not self._thread.is_alive():
                        break
            self._thread.join(timeout=30.0)

    # ------------------------------------------------------------------ #

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            if self._error is not None:
                continue  # drain-and-discard: the loop must never block
            try:
                self._process(*item)
            except BaseException as e:  # noqa: BLE001 — re-raised via poll()
                self._error = e

    def _process(self, step, metrics, log, extra) -> None:
        import jax
        import numpy as np

        leaves = jax.tree_util.tree_leaves(metrics)
        if leaves:
            # sync via a HOST TRANSFER of one metric scalar, never
            # block_until_ready: a transfer cannot complete before the
            # compute producing it (bench.py's honest-timing contract), and
            # block_until_ready here corrupts the heap on this jaxlib when
            # the step's donated state came from an Orbax restore
            np.asarray(leaves[0])  # kft: noqa[jax-sync] — drain-thread-only single-leaf host transfer; the loop thread never blocks here
        if self._hb is not None:
            # step N's metrics are ready ⇒ step N completed on device:
            # the honest progress stamp for the supervisor's watchdog
            self._hb.beat(step)
        now = time.perf_counter()
        if self._last_ready is not None:
            self._win_step_s += now - self._last_ready
            self._win_steps += 1
        self._last_ready = now
        if self._t_logged is None:
            # first step's readiness re-stamps the rate clock: compile time
            # never pollutes steps_per_sec (it's reported as compile_ms)
            self._t_logged = now
            self._step_logged = step
        if not log:
            return
        m = {k: self._to_scalar(v) for k, v in metrics.items()}
        steps = step - self._step_logged
        elapsed = now - self._t_logged
        if steps > 0 and elapsed > 0:
            m["steps_per_sec"] = steps / elapsed
        else:
            # degenerate window (the first step is itself a log boundary):
            # the loop's dispatch-side estimate is the only clock available
            m["steps_per_sec"] = float(extra.pop("fallback_steps_per_sec", 0.0))
        if self._win_steps:
            m["device_step_ms"] = self._win_step_s / self._win_steps * 1e3
        self._win_step_s = 0.0
        self._win_steps = 0
        self._t_logged = now
        self._step_logged = step
        extra.pop("fallback_steps_per_sec", None)
        m.update(extra)
        self._set_gauges(m)
        self._writer.write(step, m)
        self._history.append({"step": step, **m})
        for h in self._hooks:
            h(step, m)


def live_kft_threads() -> list[str]:
    """Names of still-alive overlap threads — the leak check smoke.sh runs."""
    return [
        t.name
        for t in threading.enumerate()
        if t.name in (PREFETCH_THREAD_NAME, DRAIN_THREAD_NAME) and t.is_alive()
    ]
