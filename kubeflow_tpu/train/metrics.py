"""Metric writers: stdout (tuner-scrapable), JSONL, optional TensorBoard.

The reference's clever observability bit is the Katib metrics-collector
sidecar that regex-parses trial stdout (SURVEY.md §5.5) — user code needs no
SDK. We emit the same ``key=value`` stdout format our tuner's collector
scrapes (``kubeflow_tpu.tune``), plus a JSONL stream for programmatic
readers, plus TensorBoard events when a writer is available (the TFEvents
path of the reference collector).
"""

from __future__ import annotations

import json
import math
import sys
import time
from pathlib import Path
from typing import Any, IO, Mapping


#: Overlap instrumentation (train/prefetch.py): the hot-loop split, exported
#: as process gauges on the shared /metrics endpoint (obs.prom.REGISTRY,
#: served by ObsServer) and mirrored into every MetricWriter log line.
#: - data_stall_ms:  mean per-batch wait for the prefetcher this window
#: - h2d_ms:         mean per-batch host-assembly + H2D placement cost
#: - device_step_ms: mean device step time (ready-to-ready on the drain)
#: - compile_ms:     first-step jit compile, reported once — so
#:   steps_per_sec never conflates compile with steady state
def _overlap_gauges():
    from kubeflow_tpu.obs import names, prom

    return {
        key: prom.REGISTRY.gauge(metric, help_)  # kft: noqa[metric-registry] — `metric` ranges over the names.TRAIN_* constants in the tuple below; no literal can enter
        for key, metric, help_ in (
            ("data_stall_ms", names.TRAIN_DATA_STALL_MS,
             "mean ms/batch the loop waited on input data"),
            ("h2d_ms", names.TRAIN_H2D_MS,
             "mean ms/batch of host batch assembly + H2D copy"),
            ("device_step_ms", names.TRAIN_DEVICE_STEP_MS,
             "mean device step ms (drain ready-to-ready)"),
            ("compile_ms", names.TRAIN_COMPILE_MS,
             "first-step jit compile ms"),
            ("steps_per_sec", names.TRAIN_STEPS_PER_SEC,
             "steady-state training steps per second"),
        )
    }


def set_overlap_gauges(scalars: Mapping[str, Any]) -> None:
    """Mirror overlap keys present in ``scalars`` onto the prom gauges."""
    gauges = _overlap_gauges()
    for k, g in gauges.items():
        v = scalars.get(k)
        if v is not None:
            g.set(float(v))


class NonFiniteMetricError(RuntimeError):
    """A training metric went NaN/inf — fail fast, don't train into noise.

    SURVEY.md §5.2 (numerics discipline): the reference relies on user-side
    vigilance; here the metric writer itself is the alarm, so every trainer
    and tuner trial gets it for free."""

#: stdout format, one line per step: ``step=3 loss=1.23 accuracy=0.9``
#: (floats rendered with repr-precision; scrapers parse ``(\w+)=([^ ]+)``).


class MetricWriter:
    """Rank-0-gated multi-sink metric writer."""

    def __init__(
        self,
        logdir: str | Path | None = None,
        *,
        is_writer: bool = True,
        stdout: IO[str] | None = None,
        tensorboard: bool = False,
        nan_alarm: bool = True,
    ):
        self.is_writer = is_writer
        #: raise NonFiniteMetricError on NaN/inf metrics — on EVERY rank
        #: (a poisoned loss replicates; non-writer ranks must stop too)
        self.nan_alarm = nan_alarm
        self.logdir = Path(logdir) if logdir else None
        self._stdout = stdout or sys.stdout
        self._jsonl: IO[str] | None = None
        self._tb = None
        if not self.is_writer:
            return
        if self.logdir:
            self.logdir.mkdir(parents=True, exist_ok=True)
            self._jsonl = open(self.logdir / "metrics.jsonl", "a")
        if tensorboard and self.logdir:
            try:  # torch's pure-python event writer; optional
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=str(self.logdir / "tb"))
            except Exception:  # noqa: BLE001 — TB is best-effort
                self._tb = None

    def write(self, step: int, metrics: Mapping[str, Any]) -> None:
        # one device sync per metric: _to_scalar blocks on device arrays, so
        # convert once and share between the alarm and the sinks
        scalars = {k: _to_scalar(v) for k, v in metrics.items()}
        if self.nan_alarm:
            bad = {k: v for k, v in scalars.items() if not math.isfinite(v)}
            if bad:
                raise NonFiniteMetricError(
                    f"non-finite metrics at step {step}: {bad} — a batch or "
                    "the optimizer state is poisoned; enable "
                    "TrainConfig.check_numerics='checkify' to locate the op"
                )
        if not self.is_writer:
            return
        line = " ".join(
            [f"step={step}"] + [f"{k}={v:.6g}" for k, v in scalars.items()]
        )
        print(line, file=self._stdout, flush=True)
        if self._jsonl:
            self._jsonl.write(
                json.dumps({"step": step, "time": time.time(), **scalars}) + "\n"
            )
            self._jsonl.flush()
        if self._tb:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, step)

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._tb:
            self._tb.close()
            self._tb = None

    def __enter__(self) -> "MetricWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _to_scalar(v: Any) -> float:
    """Device arrays → python floats (blocks; call off the hot path)."""
    try:
        return float(v)
    except TypeError:
        import numpy as np

        return float(np.asarray(v).mean())


def parse_stdout_metrics(text: str) -> list[dict[str, float]]:
    """Inverse of ``write``: scrape ``key=value`` lines (the collector's
    regex format). Non-numeric tokens are skipped."""
    import re

    out = []
    for line in text.splitlines():
        found = dict()
        for k, v in re.findall(r"(\w+)=([^\s]+)", line):
            try:
                found[k] = float(v)
            except ValueError:
                continue
        if "step" in found and len(found) > 1:
            out.append(found)
    return out
