"""Orbax checkpoint/restore: sharding-aware, multi-host, async-capable.

The reference platform leaves training checkpoints entirely to user code
(torch.save to PVC — SURVEY.md §5.4); TPU-natively this is a first-class
subsystem because checkpoint-restart IS the elasticity model for static SPMD
worlds (SURVEY.md §5.3). Key capability: restore onto a *different* mesh
shape than the one that saved (elastic-by-restart after losing a slice) —
Orbax re-shards on load given target shardings.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    save_every_steps: int = 100
    max_to_keep: int = 3
    async_save: bool = True


class Checkpointer:
    """Thin lifecycle wrapper over ``ocp.CheckpointManager``."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        path = Path(config.directory).absolute()
        path.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            save_interval_steps=config.save_every_steps,
            enable_async_checkpointing=config.async_save,
        )
        self._mgr = ocp.CheckpointManager(path, options=options)
        #: registering saves whose async write is not yet durable:
        #: [(step, RegisterOnSave)] — ingested on the next interval check
        #: (any later ``save``) or at ``wait()``/``close()``.
        self._pending_register: list[tuple[int, Any]] = []

    # ------------------------------------------------------------------ #

    def save(
        self, step: int, state: Any, *, force: bool = False,
        register: Any | None = None,
    ) -> bool:
        """Save if the interval policy says so (or ``force``). Async when
        configured — overlaps the HBM→host copy with the next steps.

        ``register`` (a ``registry.spec.RegisterOnSave``) links training
        into the model registry: a step that actually saved is ingested
        as a new ModelVersion with a ``checkpoint`` lineage edge (and
        optionally promoted to a stage). The registry must never hash a
        half-written checkpoint, but blocking the hot loop on durability
        here would defeat ``async_save`` — so for async managers the
        registration is *deferred*: it runs on a later ``save`` call once
        the write has completed (a non-blocking probe), or at
        ``wait()``/``close()`` at the latest. The registered version is
        exposed as ``self.last_registered``."""
        self._ingest_ready()  # previous interval's save may be durable now
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved and register is not None:
            self._pending_register.append((step, register))
            if self.config.async_save:
                self._ingest_ready()  # fast saves may already be durable
            else:
                self._ingest_ready(block=True)  # sync save: durable now
        return saved

    def _ingest_ready(self, block: bool = False) -> None:
        """Register pending saves whose checkpoint write is durable."""
        if not self._pending_register:
            return
        if block:
            self._mgr.wait_until_finished()
        elif self._saving_in_progress():
            return
        pending, self._pending_register = self._pending_register, []
        for step, register in pending:
            ckpt = self._step_dir(step)
            self.last_registered = register.store.register_version(
                register.name,
                ckpt,
                source_uri="file://" + ckpt,
                metadata={**dict(register.metadata), "step": int(step)},
                stage=register.stage,
                lineage=[(
                    "checkpoint",
                    f"{self.config.directory}@{step}",
                    {"step": int(step)},
                )],
            )

    def _saving_in_progress(self) -> bool:
        """Non-blocking durability probe; pessimistic when the installed
        Orbax can't answer without blocking (registration then waits for
        the next ``wait()``/``close()`` instead of stalling the loop)."""
        probe = getattr(self._mgr, "is_saving_in_progress", None)
        if probe is None:
            return True
        try:
            return bool(probe())
        except Exception:  # noqa: BLE001 — never break a save over a probe
            return True

    #: the ModelVersion produced by the most recent registering save
    last_registered: Any | None = None

    def _step_dir(self, step: int) -> str:
        """The on-disk directory Orbax wrote for ``step``."""
        base = Path(self.config.directory).absolute()
        direct = base / str(step)
        if direct.exists():
            return str(direct)
        # step-format prefixes/padding vary across Orbax configs: match
        # any directory whose digits spell this step
        for cand in sorted(base.iterdir()) if base.exists() else []:
            digits = "".join(ch for ch in cand.name if ch.isdigit())
            if cand.is_dir() and digits and int(digits) == int(step):
                return str(cand)
        raise FileNotFoundError(
            f"no checkpoint directory for step {step} under {base}"
        )

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, target_state: Any, step: int | None = None) -> Any:
        """Restore into the shardings of ``target_state`` (an abstract or
        concrete pytree). Because the target carries its own NamedShardings,
        restoring onto a different mesh shape than the writer's is exactly
        the same call — the elastic-restart path."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.config.directory}"
            )
        abstract = jax.tree_util.tree_map(_abstractify, target_state)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        """Block until async saves are durable (call before exit)."""
        self._mgr.wait_until_finished()
        self._ingest_ready(block=True)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._ingest_ready(block=True)
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _abstractify(x: Any) -> Any:
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x
