"""Orbax checkpoint/restore: sharding-aware, multi-host, async-capable.

The reference platform leaves training checkpoints entirely to user code
(torch.save to PVC — SURVEY.md §5.4); TPU-natively this is a first-class
subsystem because checkpoint-restart IS the elasticity model for static SPMD
worlds (SURVEY.md §5.3). Key capability: restore onto a *different* mesh
shape than the one that saved (elastic-by-restart after losing a slice) —
Orbax re-shards on load given target shardings.

Integrity (the chaos-harness contract): every durable save gets a per-file
sha256 manifest (``_KFT_MANIFEST.json`` inside the step dir, GC'd with it),
``verify_step`` rechecks it, and ``restore`` walks back to the newest step
that verifies — a corrupt latest checkpoint costs ``save_every_steps`` of
progress instead of the whole run. Orbax's own commit is atomic (staged dir
rename), so a step that exists but predates its manifest write is trusted;
the manifest catches the silent cases atomicity can't: bit-rot, torn
copies, and chaos-injected corruption.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from kubeflow_tpu.obs import names, prom

logger = logging.getLogger(__name__)

#: per-file sha256 manifest written inside each step dir once the (possibly
#: async) save is durable; Orbax's max_to_keep GC removes it with the step.
MANIFEST_NAME = "_KFT_MANIFEST.json"

RESTORE_FALLBACKS = prom.REGISTRY.counter(
    names.CHECKPOINT_FALLBACKS_TOTAL,
    "restores that walked past a corrupt/unreadable checkpoint step",
)


class CorruptCheckpointError(RuntimeError):
    """A checkpoint step failed its sha256 manifest verification."""


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    save_every_steps: int = 100
    max_to_keep: int = 3
    async_save: bool = True


class Checkpointer:
    """Thin lifecycle wrapper over ``ocp.CheckpointManager``."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        path = Path(config.directory).absolute()
        path.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            save_interval_steps=config.save_every_steps,
            enable_async_checkpointing=config.async_save,
        )
        self._mgr = ocp.CheckpointManager(path, options=options)
        #: registering saves whose async write is not yet durable:
        #: [(step, RegisterOnSave)] — ingested on the next interval check
        #: (any later ``save``) or at ``wait()``/``close()``.
        self._pending_register: list[tuple[int, Any]] = []
        #: saved steps whose integrity manifest is not yet written (async
        #: saves: the files must be durable before they can be hashed).
        self._pending_manifest: list[int] = []

    # ------------------------------------------------------------------ #

    def save(
        self, step: int, state: Any, *, force: bool = False,
        register: Any | None = None,
    ) -> bool:
        """Save if the interval policy says so (or ``force``). Async when
        configured — overlaps the HBM→host copy with the next steps.

        ``register`` (a ``registry.spec.RegisterOnSave``) links training
        into the model registry: a step that actually saved is ingested
        as a new ModelVersion with a ``checkpoint`` lineage edge (and
        optionally promoted to a stage). The registry must never hash a
        half-written checkpoint, but blocking the hot loop on durability
        here would defeat ``async_save`` — so for async managers the
        registration is *deferred*: it runs on a later ``save`` call once
        the write has completed (a non-blocking probe), or at
        ``wait()``/``close()`` at the latest. The registered version is
        exposed as ``self.last_registered``. The integrity manifest is
        deferred the same way, for the same reason."""
        self._ingest_ready()  # previous interval's save may be durable now
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            self._pending_manifest.append(step)
            if register is not None:
                self._pending_register.append((step, register))
            if self.config.async_save:
                self._ingest_ready()  # fast saves may already be durable
            else:
                self._ingest_ready(block=True)  # sync save: durable now
        return saved

    def _ingest_ready(self, block: bool = False) -> None:
        """Finalize saves whose checkpoint write is durable: write their
        sha256 manifests, then run any deferred registrations."""
        if not (self._pending_register or self._pending_manifest):
            return
        if block:
            self._mgr.wait_until_finished()
        elif self._saving_in_progress():
            return
        manifests, self._pending_manifest = self._pending_manifest, []
        for step in manifests:
            try:
                self._write_manifest(step)
            except OSError as e:  # GC'd before finalize / disk trouble
                logger.warning("manifest for step %d not written: %s", step, e)
        pending, self._pending_register = self._pending_register, []
        for step, register in pending:
            ckpt = self._step_dir(step)
            self.last_registered = register.store.register_version(
                register.name,
                ckpt,
                source_uri="file://" + ckpt,
                metadata={**dict(register.metadata), "step": int(step)},
                stage=register.stage,
                lineage=[(
                    "checkpoint",
                    f"{self.config.directory}@{step}",
                    {"step": int(step)},
                )],
            )

    def _saving_in_progress(self) -> bool:
        """Non-blocking durability probe; pessimistic when the installed
        Orbax can't answer without blocking (registration then waits for
        the next ``wait()``/``close()`` instead of stalling the loop)."""
        probe = getattr(self._mgr, "is_saving_in_progress", None)
        if probe is None:
            return True
        try:
            return bool(probe())
        except Exception:  # noqa: BLE001 — never break a save over a probe
            return True

    #: the ModelVersion produced by the most recent registering save
    last_registered: Any | None = None

    def _step_dir(self, step: int) -> str:
        """The on-disk directory Orbax wrote for ``step``."""
        base = Path(self.config.directory).absolute()
        direct = base / str(step)
        if direct.exists():
            return str(direct)
        # step-format prefixes/padding vary across Orbax configs: match
        # any directory whose digits spell this step
        for cand in sorted(base.iterdir()) if base.exists() else []:
            digits = "".join(ch for ch in cand.name if ch.isdigit())
            if cand.is_dir() and digits and int(digits) == int(step):
                return str(cand)
        raise FileNotFoundError(
            f"no checkpoint directory for step {step} under {base}"
        )

    # -- integrity ------------------------------------------------------ #

    def _write_manifest(self, step: int) -> None:
        """Hash every file of a durable step; rank 0 writes, atomically.
        Multi-process runs: each process's files are already committed by
        Orbax's barrier before ``wait_until_finished`` returns, so rank 0
        sees the complete tree."""
        if jax.process_index() != 0:
            return
        step_dir = Path(self._step_dir(step))
        manifest = {"step": int(step), "files": _hash_tree(step_dir)}
        tmp = step_dir / (MANIFEST_NAME + ".tmp")
        tmp.write_text(json.dumps(manifest, sort_keys=True))
        os.replace(tmp, step_dir / MANIFEST_NAME)

    def verify_step(self, step: int) -> bool | None:
        """True: manifest present and every file matches. False: mismatch
        or unreadable (corrupt). None: no manifest (pre-manifest save or a
        crash between Orbax's atomic commit and the manifest write) —
        trusted, since Orbax never commits a partial step."""
        try:
            step_dir = Path(self._step_dir(step))
        except FileNotFoundError:
            return False
        mpath = step_dir / MANIFEST_NAME
        if not mpath.exists():
            return None
        try:
            manifest = json.loads(mpath.read_text())
            want = manifest["files"]
        except (OSError, ValueError, KeyError):
            return False  # torn manifest: can't vouch for the data
        try:
            have = _hash_tree(step_dir)
        except OSError:
            return False
        return have == want

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(int(s) for s in self._mgr.all_steps())

    def latest_valid_step(self) -> int | None:
        """Newest step whose manifest verifies (or that predates
        manifests); None when every step is corrupt or none exist."""
        for step in reversed(self.all_steps()):
            if self.verify_step(step) is not False:
                return step
        return None

    # ------------------------------------------------------------------ #

    def restore(
        self, target_state: Any, step: int | None = None, *,
        verify: bool = True,
    ) -> Any:
        """Restore into the shardings of ``target_state`` (an abstract or
        concrete pytree). Because the target carries its own NamedShardings,
        restoring onto a different mesh shape than the writer's is exactly
        the same call — the elastic-restart path.

        With ``step=None`` the newest *valid* step is restored: a step that
        fails its sha256 manifest (or whose Orbax read raises) is skipped
        with a warning and the walk falls back to the previous one — a
        corrupt latest checkpoint degrades to lost progress, not a dead
        job. An explicitly requested ``step`` is never silently
        substituted: corruption raises ``CorruptCheckpointError``."""
        abstract = jax.tree_util.tree_map(_abstractify, target_state)
        if step is not None:
            if verify and self.verify_step(step) is False:
                raise CorruptCheckpointError(
                    f"checkpoint step {step} under {self.config.directory} "
                    "fails its sha256 manifest"
                )
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint found under {self.config.directory}"
            )
        last_err: Exception | None = None
        for s in reversed(steps):
            if verify and self.verify_step(s) is False:
                logger.warning(
                    "checkpoint step %d fails its sha256 manifest; "
                    "falling back to the previous step", s,
                )
                RESTORE_FALLBACKS.inc()
                continue
            try:
                return self._mgr.restore(
                    s, args=ocp.args.StandardRestore(abstract)
                )
            except Exception as e:  # noqa: BLE001 — unreadable ≈ corrupt
                last_err = e
                logger.warning(
                    "checkpoint step %d failed to restore (%s: %s); "
                    "falling back", s, type(e).__name__, e,
                )
                RESTORE_FALLBACKS.inc()
        raise CorruptCheckpointError(
            f"every checkpoint under {self.config.directory} is corrupt "
            f"or unreadable (steps {steps})"
        ) from last_err

    def wait(self) -> None:
        """Block until async saves are durable (call before exit)."""
        self._mgr.wait_until_finished()
        self._ingest_ready(block=True)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._ingest_ready(block=True)
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _hash_tree(root: Path) -> dict[str, str]:
    """relpath → sha256 over every file under ``root`` (manifest excluded)."""
    out: dict[str, str] = {}
    for dirpath, _, files in os.walk(root):
        for name in sorted(files):
            if name == MANIFEST_NAME or name == MANIFEST_NAME + ".tmp":
                continue
            p = Path(dirpath) / name
            h = hashlib.sha256()
            with open(p, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[os.path.relpath(p, root)] = h.hexdigest()
    return out


def _abstractify(x: Any) -> Any:
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x
