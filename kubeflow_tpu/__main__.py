"""``python -m kubeflow_tpu`` → the ``kft`` CLI (see cli.py)."""

from kubeflow_tpu.cli import main

raise SystemExit(main())
