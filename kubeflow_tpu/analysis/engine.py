"""Lint engine: file discovery, pass dispatch, suppressions, baseline.

The engine is deliberately small — all domain knowledge lives in the
passes (:mod:`kubeflow_tpu.analysis.passes`). What the engine owns:

- **discovery** — walk the configured include roots for ``*.py`` files,
  minus exclude globs;
- **dispatch** — parse each file once, hand the ``FileContext`` to every
  enabled pass (``check``), then collect cross-file findings (``finish``);
- **scoping** — rules listed in ``LintConfig.scopes`` only apply to their
  configured paths (e.g. the JAX sync lint only patrols the hot-loop files);
- **suppression** — ``# kft: noqa[rule]`` (or bare ``# kft: noqa``) on the
  finding's line; policy requires the comment to state the invariant that
  makes the line safe;
- **baseline** — ``lint_baseline.json`` pins legacy findings by
  ``(rule, path, message)`` fingerprint (no line numbers, so unrelated
  edits don't shake the pin loose) while anything new fails the run.

Config comes from ``[tool.kft-lint]`` in ``pyproject.toml``; Python 3.10
has no ``tomllib``, so a minimal single-line-value parser covers the
subset this table uses when the stdlib module is absent.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from collections import Counter
from typing import Iterable, Sequence

NOQA_RE = re.compile(
    r"#\s*kft:\s*noqa(?:\[(?P<rules>[A-Za-z0-9_,\- ]+)\])?"
)

SEVERITIES = ("warning", "error")

#: Default per-rule path scoping (overridable via [tool.kft-lint.scopes]).
#: A rule absent from this map applies everywhere.
DEFAULT_SCOPES: dict[str, tuple[str, ...]] = {
    # PR 2's hard-won hot-loop rules: these files must never sync the
    # device on the loop thread nor donate trees they don't own.
    "jax-sync": (
        "kubeflow_tpu/train/loop.py",
        "kubeflow_tpu/train/prefetch.py",
        "kubeflow_tpu/serve/engine.py",
        "kubeflow_tpu/ops/paged_attention.py",
    ),
    # Supervision clocks must survive wall-clock jumps (NTP step, VM
    # migration): grace/staleness/progress math is monotonic-only here.
    "monotonic-clock": (
        "kubeflow_tpu/obs/heartbeat.py",
        "kubeflow_tpu/orchestrator/supervisor.py",
        "kubeflow_tpu/platform/notebooks.py",
    ),
    # Both planes are contractually seedable (FaultPlan.seed, jitter_seed).
    "unseeded-random": (
        "kubeflow_tpu/chaos",
        "kubeflow_tpu/sched",
    ),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit. ``fingerprint`` intentionally omits the line number:
    baselines must survive unrelated edits above the pinned site."""

    rule: str
    path: str
    line: int
    severity: str
    message: str

    def fingerprint(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.severity}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class FileContext:
    """One parsed file as every pass sees it."""

    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.Module
    lines: list[str]


class LintPass:
    """Base class: per-file ``check`` + cross-file ``finish``."""

    name = "abstract"
    rules: tuple[str, ...] = ()

    def begin(self, config: "LintConfig") -> None:  # pragma: no cover
        pass

    def check(self, ctx: FileContext) -> list[Finding]:
        return []

    def finish(self) -> list[Finding]:
        return []


def default_passes() -> list[LintPass]:
    from kubeflow_tpu.analysis.passes import (
        jaxsync,
        locks,
        metricnames,
        randomness,
        threads,
    )

    return [
        locks.LockDisciplinePass(),
        metricnames.MetricRegistryPass(),
        jaxsync.JaxSyncPass(),
        threads.ThreadHygienePass(),
        randomness.RandomnessPass(),
    ]


def all_rules(passes: Iterable[LintPass] | None = None) -> tuple[str, ...]:
    out: list[str] = []
    for p in passes or default_passes():
        out.extend(p.rules)
    return tuple(out)


@dataclasses.dataclass
class LintConfig:
    root: str = "."
    include: tuple[str, ...] = ("kubeflow_tpu",)
    exclude: tuple[str, ...] = ()
    #: None → every registered rule.
    rules: tuple[str, ...] | None = None
    #: repo-relative path, or None to disable baselining.
    baseline: str | None = "lint_baseline.json"
    scopes: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=lambda: {k: v for k, v in DEFAULT_SCOPES.items()}
    )


def _mini_toml_table(path: str, table: str) -> dict:
    """Fallback ``[table]`` reader for Python 3.10 (no tomllib): handles
    ``key = "str"`` and (possibly multi-line) ``key = ["a", "b"]`` string
    arrays — the only shapes ``[tool.kft-lint]`` uses. Sub-tables become
    nested dicts. TOML's string-array syntax is valid Python literal
    syntax, so values parse via ``ast.literal_eval`` once comment lines
    are stripped."""
    out: dict = {}
    current: dict | None = None
    try:
        text = open(path, encoding="utf-8").read()
    except OSError:
        return out
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            header = line.strip("[]").strip().strip('"')
            if header == table:
                current = out
            elif header.startswith(table + "."):
                current = out.setdefault(header[len(table) + 1 :], {})
            else:
                current = None
            continue
        if current is None or "=" not in line:
            continue
        def strip_comment(s: str) -> str:
            # safe when the part before '#' has balanced quotes (no '#'
            # inside a string — true for every shape this table uses)
            before = s.split("#", 1)[0]
            if s != before and before.count('"') % 2 == 0:
                return before.strip()
            return s

        key, _, value = line.partition("=")
        value = strip_comment(value.strip())
        # multi-line array: accumulate until the brackets balance
        while value.count("[") > value.count("]") and i < len(lines):
            cont = strip_comment(lines[i].strip())
            i += 1
            value += " " + cont
        try:
            current[key.strip().strip('"')] = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            continue
    return out


def _pyproject_table(root: str) -> dict:
    path = os.path.join(root, "pyproject.toml")
    if not os.path.exists(path):
        return {}
    try:
        import tomllib  # Python >= 3.11
    except ModuleNotFoundError:
        return _mini_toml_table(path, "tool.kft-lint")
    with open(path, "rb") as f:
        data = tomllib.load(f)
    return data.get("tool", {}).get("kft-lint", {})


def load_config(root: str = ".") -> LintConfig:
    """LintConfig from ``[tool.kft-lint]`` (defaults where absent)."""
    table = _pyproject_table(root)
    cfg = LintConfig(root=root)
    if "include" in table:
        cfg.include = tuple(table["include"])
    if "exclude" in table:
        cfg.exclude = tuple(table["exclude"])
    if "rules" in table:
        cfg.rules = tuple(table["rules"])
    if "baseline" in table:
        cfg.baseline = table["baseline"] or None
    scopes = table.get("scopes", {})
    if isinstance(scopes, dict):
        for rule, paths in scopes.items():
            cfg.scopes[rule] = tuple(paths)
    return cfg


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    files: int
    enabled_rules: tuple[str, ...]
    baseline_matched: int = 0
    noqa_suppressed: int = 0
    #: baseline entries nothing matched this run — prune them.
    stale_baseline: list[tuple[str, str, str]] = dataclasses.field(
        default_factory=list
    )
    parse_errors: list[str] = dataclasses.field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "files": self.files,
            "rules": list(self.enabled_rules),
            "findings": [f.to_dict() for f in self.findings],
            "baseline_matched": self.baseline_matched,
            "noqa_suppressed": self.noqa_suppressed,
            "stale_baseline": [list(fp) for fp in self.stale_baseline],
            "parse_errors": list(self.parse_errors),
        }


def discover_files(config: LintConfig, paths: Sequence[str] | None = None) -> list[str]:
    """Repo-relative ``*.py`` paths under the include roots (or explicit
    ``paths``), minus exclude globs, sorted for deterministic output."""
    roots = [os.path.normpath(p) for p in (paths or config.include)]
    out: set[str] = set()
    for rel in roots:
        full = os.path.join(config.root, rel)
        if os.path.isfile(full) and rel.endswith(".py"):
            out.add(rel.replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if not fn.endswith(".py"):
                    continue
                relpath = os.path.relpath(
                    os.path.join(dirpath, fn), config.root
                ).replace(os.sep, "/")
                out.add(relpath)
    def excluded(p: str) -> bool:
        return any(
            fnmatch.fnmatch(p, pat) or p.startswith(pat.rstrip("/") + "/")
            for pat in config.exclude
        )
    return sorted(p for p in out if not excluded(p))


def _in_scope(path: str, scope: tuple[str, ...] | None) -> bool:
    if scope is None:
        return True
    return any(
        path == entry or path.startswith(entry.rstrip("/") + "/")
        for entry in scope
    )


def _noqa_rules(line: str) -> set[str] | None:
    """None → no noqa; empty set → blanket noqa; else the named rules."""
    m = NOQA_RE.search(line)
    if m is None:
        return None
    rules = m.group("rules")
    if not rules:
        return set()
    return {r.strip() for r in rules.split(",") if r.strip()}


def load_baseline(path: str) -> Counter:
    """Baseline file → fingerprint multiset. Missing file → empty."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError:
        return Counter()
    entries = doc.get("findings", doc) if isinstance(doc, dict) else doc
    out: Counter = Counter()
    for e in entries:
        out[(e["rule"], e["path"], e["message"])] += 1
    return out


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    doc = {
        "version": 1,
        "comment": (
            "Pinned legacy lint findings — new findings fail `kft lint`. "
            "Burn this file down; never grow it."
        ),
        "findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def run_lint(
    config: LintConfig | None = None,
    *,
    rules: Sequence[str] | None = None,
    paths: Sequence[str] | None = None,
    baseline: bool = True,
) -> LintResult:
    """One full lint run. ``rules`` narrows to specific rule ids;
    ``paths`` narrows discovery; ``baseline=False`` ignores the pin file
    (what ``--no-baseline`` and baseline regeneration use)."""
    config = config or load_config()
    passes = default_passes()
    known = set(all_rules(passes))
    enabled = set(config.rules) if config.rules is not None else set(known)
    if rules is not None:
        unknown = sorted(set(rules) - known)
        if unknown:
            raise ValueError(
                f"unknown rule(s) {unknown}; known: {sorted(known)}"
            )
        enabled &= set(rules)
    active = [p for p in passes if enabled & set(p.rules)]

    files = discover_files(config, paths)
    raw: list[Finding] = []
    lines_by_path: dict[str, list[str]] = {}
    parse_errors: list[str] = []
    for p in active:
        p.begin(config)
    for rel in files:
        full = os.path.join(config.root, rel)
        try:
            source = open(full, encoding="utf-8").read()
            tree = ast.parse(source, filename=rel)
        except (OSError, SyntaxError) as e:
            parse_errors.append(f"{rel}: {e}")
            continue
        ctx = FileContext(
            path=rel, source=source, tree=tree, lines=source.splitlines()
        )
        lines_by_path[rel] = ctx.lines
        for p in active:
            raw.extend(p.check(ctx))
    for p in active:
        raw.extend(p.finish())

    # rule enablement + scope
    raw = [
        f
        for f in raw
        if f.rule in enabled and _in_scope(f.path, config.scopes.get(f.rule))
    ]

    # inline noqa suppression
    kept: list[Finding] = []
    noqa_suppressed = 0
    for f in raw:
        lines = lines_by_path.get(f.path, ())
        line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        suppress = _noqa_rules(line)
        if suppress is not None and (not suppress or f.rule in suppress):
            noqa_suppressed += 1
            continue
        kept.append(f)

    # baseline pinning
    baseline_matched = 0
    stale: list[tuple[str, str, str]] = []
    if baseline and config.baseline:
        pins = load_baseline(os.path.join(config.root, config.baseline))
        unpinned: list[Finding] = []
        for f in sorted(kept, key=lambda f: (f.path, f.line)):
            if pins.get(f.fingerprint(), 0) > 0:
                pins[f.fingerprint()] -= 1
                baseline_matched += 1
            else:
                unpinned.append(f)
        kept = unpinned
        stale = sorted(fp for fp, n in pins.items() if n > 0)

    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(
        findings=kept,
        files=len(files),
        enabled_rules=tuple(sorted(enabled)),
        baseline_matched=baseline_matched,
        noqa_suppressed=noqa_suppressed,
        stale_baseline=stale,
        parse_errors=parse_errors,
    )


# --------------------------------------------------------------------- #
# shared AST helpers the passes lean on
# --------------------------------------------------------------------- #


def is_self_attr(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def call_name(func: ast.AST) -> str | None:
    """Dotted name of a call target: ``threading.Thread`` → that string,
    bare ``Thread`` → ``"Thread"``; anything dynamic → None."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_docstring(tree: ast.Module, node: ast.Constant) -> bool:
    """True when ``node`` is the docstring constant of the module or of
    any class/function in it."""
    for parent in ast.walk(tree):
        if isinstance(
            parent,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = parent.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and body[0].value is node
            ):
                return True
    return False
