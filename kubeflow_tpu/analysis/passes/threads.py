"""Thread-hygiene lints: ``thread-join`` and ``monotonic-clock``.

``thread-join`` — a non-daemon ``threading.Thread`` that no
``stop()``/``close()``/``shutdown()``/``__exit__`` joins will wedge
interpreter exit (the exact leak smoke.sh's post-fit thread check hunts).
Spawns must either pass ``daemon=True`` explicitly or live in a class
whose teardown method joins.

``monotonic-clock`` — supervision clocks (heartbeat staleness, startup
grace, progress timeouts, notebook idle culling) measure *durations*; on
``time.time()`` they silently mis-fire across NTP steps and wall-clock
jumps. Within the scoped files every ``time.time`` reference is flagged —
stamp and compare with ``time.monotonic()`` (shared across processes on
the same host: CLOCK_MONOTONIC is boot-relative system-wide on Linux).
"""

from __future__ import annotations

import ast

from kubeflow_tpu.analysis.engine import (
    FileContext,
    Finding,
    LintPass,
    call_name,
)

JOIN_RULE = "thread-join"
CLOCK_RULE = "monotonic-clock"

TEARDOWN_METHODS = {"stop", "close", "shutdown", "__exit__", "join"}


class ThreadHygienePass(LintPass):
    name = "threads"
    rules = (JOIN_RULE, CLOCK_RULE)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        findings.extend(self._check_spawns(ctx))
        findings.extend(self._check_clocks(ctx))
        return findings

    # -- thread-join ---------------------------------------------------- #

    def _check_spawns(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for cls, call in self._thread_ctors(ctx.tree):
            daemon = None
            for kw in call.keywords:
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
            if daemon:
                continue
            if cls is not None and self._class_joins(cls):
                continue
            where = f" in class {cls.name}" if cls is not None else ""
            findings.append(
                Finding(
                    rule=JOIN_RULE,
                    path=ctx.path,
                    line=call.lineno,
                    severity="error",
                    message=(
                        "non-daemon Thread spawned"
                        + where
                        + " with no join in any stop()/close()/shutdown()/"
                        "__exit__ — it will outlive its owner and wedge "
                        "interpreter exit; pass daemon=True or join it in "
                        "teardown"
                    ),
                )
            )
        return findings

    def _thread_ctors(self, tree: ast.Module):
        """Yield ``(enclosing_class_or_None, Thread(...) call)`` pairs."""
        def walk(node, cls):
            for child in ast.iter_child_nodes(node):
                child_cls = child if isinstance(child, ast.ClassDef) else cls
                if (
                    isinstance(child, ast.Call)
                    and call_name(child.func)
                    in ("threading.Thread", "Thread")
                ):
                    yield (cls, child)
                yield from walk(child, child_cls)

        yield from walk(tree, None)

    def _class_joins(self, cls: ast.ClassDef) -> bool:
        for m in cls.body:
            if (
                isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and m.name in TEARDOWN_METHODS
            ):
                for n in ast.walk(m):
                    if (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "join"
                    ):
                        return True
        return False

    # -- monotonic-clock ------------------------------------------------ #

    def _check_clocks(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "time"
                and isinstance(node.value, ast.Name)
                and node.value.id == "time"
            ):
                findings.append(
                    Finding(
                        rule=CLOCK_RULE,
                        path=ctx.path,
                        line=node.lineno,
                        severity="error",
                        message=(
                            "time.time() in a supervision/duration "
                            "context — wall-clock jumps (NTP step, VM "
                            "migrate) break grace and progress clocks; "
                            "use time.monotonic()"
                        ),
                    )
                )
        return findings
