"""``lock-discipline`` — the ThreadSanitizer-shaped race detector.

For every class that owns a ``threading.Lock``/``RLock`` attribute, an
attribute is *guarded* once any method mutates it inside
``with self.<lock>:``. The invariant is then all-or-nothing: every other
mutation of that attribute must also hold a lock. A bare mutation is a
candidate race — and a near-certain one when it happens in a method that
some ``threading.Thread(target=self.<m>)`` spawn uses as an entry point.

Repo conventions honored:

- ``__init__`` mutations are construction (single-threaded by contract);
- methods named ``*_locked`` document "caller holds the lock" (the
  ``_admit_locked``/``_plan_locked``/``_usage_locked`` idiom) and are
  treated as locked context;
- single-writer fields that are deliberately lock-free must carry
  ``# kft: noqa[lock-discipline]`` plus a one-line invariant comment.

Reads are only reported in thread-entry methods: a bare read elsewhere is
usually a caller-synchronized snapshot, but a thread entry point reading
guarded state without the lock races the writers by construction.
"""

from __future__ import annotations

import ast
import dataclasses

from kubeflow_tpu.analysis.engine import (
    FileContext,
    Finding,
    LintPass,
    call_name,
    is_self_attr,
)

RULE = "lock-discipline"

#: receiver-method names that mutate common containers in place
MUTATORS = {
    "append", "add", "insert", "extend", "appendleft", "extendleft",
    "pop", "popitem", "popleft", "remove", "discard", "clear",
    "update", "setdefault", "sort", "reverse",
}

LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
THREAD_CTORS = {"threading.Thread", "Thread"}


@dataclasses.dataclass
class _Access:
    method: str
    line: int
    locked: bool
    write: bool


class LockDisciplinePass(LintPass):
    name = "locks"
    rules = (RULE,)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, ctx))
        return findings

    # ------------------------------------------------------------------ #

    def _check_class(self, cls: ast.ClassDef, ctx: FileContext) -> list[Finding]:
        methods = [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        lock_attrs = self._lock_attrs(methods)
        if not lock_attrs:
            return []
        thread_entries = self._thread_entries(methods)

        accesses: dict[str, list[_Access]] = {}
        for m in methods:
            locked_whole = m.name.endswith("_locked")
            for stmt in m.body:
                self._visit(
                    stmt, locked_whole, lock_attrs, accesses, m.name
                )

        findings: list[Finding] = []
        for attr, acc in sorted(accesses.items()):
            if attr in lock_attrs:
                continue
            guarded = any(a.locked and a.write for a in acc)
            if not guarded:
                continue
            for a in acc:
                if a.locked or a.method == "__init__":
                    continue
                if a.write:
                    entry = (
                        " (thread-entry method)"
                        if a.method in thread_entries
                        else ""
                    )
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=ctx.path,
                            line=a.line,
                            severity="error",
                            message=(
                                f"{cls.name}.{a.method}: self.{attr} is "
                                f"lock-guarded elsewhere in {cls.name} but "
                                f"mutated here without the lock{entry}"
                            ),
                        )
                    )
                elif a.method in thread_entries:
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=ctx.path,
                            line=a.line,
                            severity="error",
                            message=(
                                f"{cls.name}.{a.method}: thread entry point "
                                f"reads lock-guarded self.{attr} without "
                                "the lock"
                            ),
                        )
                    )
        return findings

    def _lock_attrs(self, methods) -> set[str]:
        out: set[str] = set()
        for m in methods:
            for n in ast.walk(m):
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                    if call_name(n.value.func) in LOCK_CTORS:
                        for t in n.targets:
                            if is_self_attr(t):
                                out.add(t.attr)
        return out

    def _thread_entries(self, methods) -> set[str]:
        out: set[str] = set()
        for m in methods:
            for n in ast.walk(m):
                if (
                    isinstance(n, ast.Call)
                    and call_name(n.func) in THREAD_CTORS
                ):
                    for kw in n.keywords:
                        if kw.arg == "target" and is_self_attr(kw.value):
                            out.add(kw.value.attr)
        return out

    # ------------------------------------------------------------------ #

    def _visit(
        self,
        node: ast.AST,
        locked: bool,
        lock_attrs: set[str],
        accesses: dict[str, list[_Access]],
        mname: str,
    ) -> None:
        """Single-visit walk carrying the ``with self.<lock>`` context."""

        def rec(attr: str, line: int, write: bool) -> None:
            accesses.setdefault(attr, []).append(
                _Access(method=mname, line=line, locked=locked, write=write)
            )

        def record_target(t: ast.AST) -> None:
            if is_self_attr(t):
                rec(t.attr, t.lineno, True)
            elif isinstance(t, ast.Subscript):
                if is_self_attr(t.value):
                    rec(t.value.attr, t.lineno, True)
                self._visit(t.slice, locked, lock_attrs, accesses, mname)
            elif isinstance(t, (ast.Tuple, ast.List, ast.Starred)):
                for el in ast.iter_child_nodes(t):
                    record_target(el)

        if isinstance(node, ast.With):
            holds = any(
                is_self_attr(item.context_expr)
                and item.context_expr.attr in lock_attrs
                for item in node.items
            )
            for item in node.items:
                self._visit(
                    item.context_expr, locked, lock_attrs, accesses, mname
                )
            for s in node.body:
                self._visit(s, locked or holds, lock_attrs, accesses, mname)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs run later, possibly on another thread: the
            # enclosing lock is NOT held when they execute
            for s in node.body:
                self._visit(s, False, lock_attrs, accesses, mname)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node.body, False, lock_attrs, accesses, mname)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                record_target(t)
            self._visit(node.value, locked, lock_attrs, accesses, mname)
            return
        if isinstance(node, ast.AugAssign):
            record_target(node.target)
            self._visit(node.value, locked, lock_attrs, accesses, mname)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                record_target(node.target)
                self._visit(node.value, locked, lock_attrs, accesses, mname)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                record_target(t)
            return
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and is_self_attr(node.func.value)
            and node.func.attr in MUTATORS
        ):
            rec(node.func.value.attr, node.lineno, True)
            for arg in node.args:
                self._visit(arg, locked, lock_attrs, accesses, mname)
            for kw in node.keywords:
                self._visit(kw.value, locked, lock_attrs, accesses, mname)
            return
        if (
            isinstance(node, ast.Attribute)
            and is_self_attr(node)
            and isinstance(node.ctx, ast.Load)
        ):
            rec(node.attr, node.lineno, False)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, locked, lock_attrs, accesses, mname)
