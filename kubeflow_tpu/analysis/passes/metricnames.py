"""``metric-registry`` — one definition site for every metric name.

The platform's observability contract is its ``kft_*`` /
``kubeflow_tpu_*`` exposition names: dashboards, the chaos harness, and
smoke assertions all key off them, so a typo'd or drifting name is a
silent outage of the signal. This pass enforces:

1. **single definition site** — every metric-name string literal lives in
   ``kubeflow_tpu/obs/names.py``; anywhere else a bare literal (including
   an f-string prefix like ``f"kubeflow_tpu_engine_{key}"``) is flagged;
2. **known names only** — a literal whose value matches no ``names.py``
   constant is recorded-but-never-registered (usually a typo);
3. **kind coherence** — the same name registered as counter at one site
   and gauge/histogram at another is flagged at the later site;
4. **label coherence** — the same name registered with different label
   sets drifts the exposition schema and is flagged;
5. **dead names** — a ``names.py`` constant nothing references is a
   warning (the registration it documented is gone).

Registration sites are recognized as ``<...>REGISTRY.counter|gauge|
histogram(name, ...)`` calls (the ``obs.prom`` first-party registry).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from kubeflow_tpu.analysis.engine import (
    FileContext,
    Finding,
    LintPass,
    is_docstring,
)

RULE = "metric-registry"

NAMES_PATH = "kubeflow_tpu/obs/names.py"
METRIC_RE = re.compile(r"^(?:kft|kubeflow_tpu)_[a-z0-9_]+$")
#: metric name at the START of an f-string literal chunk (exposition lines
#: and dynamic-name construction both begin with the name/prefix)
FSTRING_RE = re.compile(r"^(?:kft|kubeflow_tpu)_[a-z0-9_]+")
REG_METHODS = ("counter", "gauge", "histogram")


@dataclasses.dataclass
class _Registration:
    path: str
    line: int
    kind: str
    #: ("lit", value) | ("ref", identifier) | ("dyn", None)
    name: tuple[str, str | None]
    labels: tuple[str, ...] | None  # None = not statically known


class MetricRegistryPass(LintPass):
    name = "metricnames"
    rules = (RULE,)

    def begin(self, config) -> None:
        self._constants: dict[str, str] = {}  # identifier → value
        self._used_idents: set[str] = set()
        self._registrations: list[_Registration] = []
        self._literal_findings: list[tuple[str, int, str]] = []
        self._literal_seen: set[tuple[str, int, str]] = set()
        #: dead-name warnings need the usage scan to have covered the
        #: whole package; a narrowed `kft lint some/path` run hasn't
        self._names_scanned = False
        # the constants themselves must resolve even when discovery is
        # narrowed to a path subset that excludes names.py
        path = os.path.join(config.root, NAMES_PATH)
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except (OSError, SyntaxError):
            return
        self._collect_constants(tree)

    def _collect_constants(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._constants[t.id] = node.value.value

    # ------------------------------------------------------------------ #

    def check(self, ctx: FileContext) -> list[Finding]:
        is_names = ctx.path.endswith(NAMES_PATH) or ctx.path == NAMES_PATH
        if is_names:
            self._names_scanned = True
            self._collect_constants(ctx.tree)
            return []

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                self._maybe_registration(node, ctx)
            if isinstance(node, ast.Name):
                self._used_idents.add(node.id)
            if isinstance(node, ast.Attribute):
                self._used_idents.add(node.attr)
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and METRIC_RE.match(node.value)
                and not is_docstring(ctx.tree, node)
            ):
                self._add_literal(ctx.path, node.lineno, node.value)
            if isinstance(node, ast.JoinedStr):
                for part in node.values:
                    if isinstance(part, ast.Constant) and isinstance(
                        part.value, str
                    ):
                        m = FSTRING_RE.match(part.value)
                        if m:
                            self._add_literal(
                                ctx.path, node.lineno, m.group(0)
                            )
        return []

    def _add_literal(self, path: str, line: int, value: str) -> None:
        # dedupe: an f-string's literal chunk is also walked as a Constant
        key = (path, line, value)
        if key not in self._literal_seen:
            self._literal_seen.add(key)
            self._literal_findings.append(key)

    def _maybe_registration(self, call: ast.Call, ctx: FileContext) -> None:
        func = call.func
        if not (
            isinstance(func, ast.Attribute) and func.attr in REG_METHODS
        ):
            return
        recv = func.value
        recv_name = (
            recv.id
            if isinstance(recv, ast.Name)
            else recv.attr
            if isinstance(recv, ast.Attribute)
            else None
        )
        if recv_name not in ("REGISTRY", "registry"):
            return
        if not call.args:
            return
        name_arg = call.args[0]
        if isinstance(name_arg, ast.Constant) and isinstance(
            name_arg.value, str
        ):
            name = ("lit", name_arg.value)
        elif isinstance(name_arg, ast.Attribute):
            name = ("ref", name_arg.attr)
        elif isinstance(name_arg, ast.Name):
            name = ("ref", name_arg.id)
        else:
            name = ("dyn", None)
        labels = self._labels_of(call)
        self._registrations.append(
            _Registration(
                path=ctx.path,
                line=call.lineno,
                kind=func.attr,
                name=name,
                labels=labels,
            )
        )

    def _labels_of(self, call: ast.Call) -> tuple[str, ...] | None:
        node = None
        if len(call.args) >= 3:
            node = call.args[2]
        for kw in call.keywords:
            if kw.arg in ("labels", "label_names"):
                node = kw.value
        if node is None:
            return ()
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for el in node.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.append(el.value)
                else:
                    return None
            return tuple(out)
        return None

    # ------------------------------------------------------------------ #

    def finish(self) -> list[Finding]:
        findings: list[Finding] = []
        known_values = set(self._constants.values())

        # (1)+(2): bare literals outside names.py
        for path, line, value in self._literal_findings:
            extra = ""
            if value not in known_values:
                extra = (
                    " — and it matches no obs/names.py constant "
                    "(recorded but never registered? typo?)"
                )
            findings.append(
                Finding(
                    rule=RULE,
                    path=path,
                    line=line,
                    severity="error",
                    message=(
                        f'bare metric-name literal "{value}"; use the '
                        f"constant from kubeflow_tpu/obs/names.py{extra}"
                    ),
                )
            )

        # resolve registrations to concrete values
        by_value: dict[str, list[tuple[_Registration, str]]] = {}
        for reg in self._registrations:
            mode, ident = reg.name
            if mode == "dyn":
                findings.append(
                    Finding(
                        rule=RULE,
                        path=reg.path,
                        line=reg.line,
                        severity="error",
                        message=(
                            f"dynamic metric name at {reg.kind}() "
                            "registration; register each name via an "
                            "obs/names.py constant"
                        ),
                    )
                )
                continue
            if mode == "lit":
                value = ident
            else:
                value = self._constants.get(ident or "")
                if value is None:
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=reg.path,
                            line=reg.line,
                            severity="error",
                            message=(
                                f"metric registered via {ident!r}, which is "
                                "not a kubeflow_tpu/obs/names.py constant"
                            ),
                        )
                    )
                    continue
            by_value.setdefault(value, []).append((reg, reg.kind))

        # (3) kind coherence + (4) label coherence
        for value, regs in sorted(by_value.items()):
            kinds = {k for _, k in regs}
            if len(kinds) > 1:
                first = regs[0][0]
                for reg, kind in regs[1:]:
                    if kind != regs[0][1]:
                        findings.append(
                            Finding(
                                rule=RULE,
                                path=reg.path,
                                line=reg.line,
                                severity="error",
                                message=(
                                    f'metric "{value}" registered as '
                                    f"{kind} here but as {regs[0][1]} at "
                                    f"{first.path}:{first.line}"
                                ),
                            )
                        )
            labelsets = {
                reg.labels for reg, _ in regs if reg.labels is not None
            }
            if len(labelsets) > 1:
                first = regs[0][0]
                for reg, _ in regs[1:]:
                    if reg.labels is not None and reg.labels != first.labels:
                        findings.append(
                            Finding(
                                rule=RULE,
                                path=reg.path,
                                line=reg.line,
                                severity="error",
                                message=(
                                    f'metric "{value}" label set '
                                    f"{list(reg.labels)} drifts from "
                                    f"{list(first.labels or ())} at "
                                    f"{first.path}:{first.line}"
                                ),
                            )
                        )

        # (5) dead names — only meaningful when the usage scan covered the
        # package (names.py itself was among the scanned files)
        if not self._names_scanned:
            return findings
        for ident in sorted(self._constants):
            if ident not in self._used_idents:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=NAMES_PATH,
                        line=1,
                        severity="warning",
                        message=(
                            f"names.{ident} is defined but never referenced "
                            "by any recorder/registrar"
                        ),
                    )
                )
        return findings
