"""``unseeded-random`` — the chaos and sched planes must stay seedable.

Chaos runs are replayable by contract (``FaultPlan.seed`` drives every
victim/byte choice) and the quota scheduler's jittered cooldowns take a
``jitter_seed``; a single call into the process-global ``random`` module
(or ``np.random``) silently breaks that determinism. This pass flags:

- ``random.<fn>(...)`` module-level draws (``random.random``,
  ``random.choice``, ...) — everything except constructing a seeded
  ``random.Random(seed)``;
- ``random.Random()`` constructed with *no* seed;
- ``np.random.<fn>(...)`` global-state draws — ``default_rng(seed)``
  with an explicit seed is the allowed spelling;
- ``from random import choice``-style imports that smuggle the global
  API in under a bare name.
"""

from __future__ import annotations

import ast

from kubeflow_tpu.analysis.engine import FileContext, Finding, LintPass

RULE = "unseeded-random"


class RandomnessPass(LintPass):
    name = "randomness"
    rules = (RULE,)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            findings.append(
                Finding(
                    rule=RULE,
                    path=ctx.path,
                    line=node.lineno,
                    severity="error",
                    message=message,
                )
            )

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "random":
                bad = [
                    a.name
                    for a in node.names
                    if a.name not in ("Random", "SystemRandom")
                ]
                if bad:
                    flag(
                        node,
                        f"from random import {', '.join(bad)} pulls the "
                        "process-global RNG into a seedable plane; thread "
                        "an explicit random.Random(seed) instead",
                    )
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            # random.<fn>(...) and random.Random()
            if isinstance(func.value, ast.Name) and func.value.id == "random":
                if func.attr == "Random":
                    if not node.args and not node.keywords:
                        flag(
                            node,
                            "random.Random() without a seed breaks chaos/"
                            "sched replayability; pass the plan's seed",
                        )
                elif func.attr != "SystemRandom":
                    flag(
                        node,
                        f"random.{func.attr}() draws from the process-"
                        "global RNG; chaos/sched are contractually "
                        "seedable — use an injected random.Random(seed)",
                    )
            # np.random.<fn>(...)
            if (
                isinstance(func.value, ast.Attribute)
                and func.value.attr == "random"
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id in ("np", "numpy")
            ):
                if func.attr == "default_rng" and (
                    node.args or node.keywords
                ):
                    continue  # seeded generator: the allowed spelling
                flag(
                    node,
                    f"np.random.{func.attr} uses numpy's global RNG; use "
                    "np.random.default_rng(seed) threaded from the plan",
                )
        return findings
