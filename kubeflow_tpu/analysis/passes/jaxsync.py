"""``jax-sync`` — hot-loop device-sync and donation lint.

PR 2's overlap work bought its throughput with two rules that nothing
but review enforced until now:

- the loop/step threads must never force a device sync:
  ``jax.block_until_ready``, ``.item()``, and ``np.asarray`` on device
  values all drain dispatch and serialize the pipeline. The one
  sanctioned sync (the metric drain's single-leaf host transfer) carries
  a ``# kft: noqa[jax-sync]`` stating why it is safe;
- ``donate_argnums`` may only donate trees the step owns. Donating an
  Orbax-restored tree corrupts the heap on this jaxlib (CPU backend
  aliases restore buffers) — every donation site must either be
  provably fit-owned (and say so in its noqa) or go through the
  non-donating re-homing identity first.

Scoped (``[tool.kft-lint].scopes``) to the hot-loop files:
``train/loop.py``, ``train/prefetch.py``, ``serve/engine.py``.
"""

from __future__ import annotations

import ast

from kubeflow_tpu.analysis.engine import FileContext, Finding, LintPass

RULE = "jax-sync"


class JaxSyncPass(LintPass):
    name = "jaxsync"
    rules = (RULE,)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            findings.append(
                Finding(
                    rule=RULE,
                    path=ctx.path,
                    line=node.lineno,
                    severity="error",
                    message=message,
                )
            )

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "block_until_ready":
                    flag(
                        node,
                        "block_until_ready forces a device sync on the hot "
                        "path (and corrupts the heap after a donated Orbax "
                        "restore on this jaxlib); sync via a host transfer "
                        "off the loop thread instead",
                    )
                elif func.attr == "item" and not node.args and not node.keywords:
                    flag(
                        node,
                        ".item() blocks the calling thread on device "
                        "compute; convert on the metric-drain thread via "
                        "a host transfer instead",
                    )
                elif (
                    func.attr == "asarray"
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("np", "numpy")
                ):
                    flag(
                        node,
                        "np.asarray on a device value is a blocking D2H "
                        "sync; keep it off the loop thread (or noqa with "
                        "the invariant that proves the operand is "
                        "host-resident)",
                    )
            for kw in node.keywords:
                if kw.arg == "donate_argnums":
                    flag(
                        node,
                        "donate_argnums: donated trees must be owned by "
                        "this step — donating an Orbax-restored tree "
                        "corrupts the heap; re-home restored state through "
                        "the non-donating identity first (noqa with the "
                        "ownership invariant once proven)",
                    )
        return findings
