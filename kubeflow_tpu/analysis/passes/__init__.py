"""Lint passes — each module owns one repo-specific invariant family.

- :mod:`.locks`       — ``lock-discipline``: guarded state mutated bare
- :mod:`.metricnames` — ``metric-registry``: one definition site + kind/
  label coherence for every ``kft_*``/``kubeflow_tpu_*`` metric name
- :mod:`.jaxsync`     — ``jax-sync``: no device syncs / foreign donation
  on the training and serving hot loops
- :mod:`.threads`     — ``thread-join`` + ``monotonic-clock``
- :mod:`.randomness`  — ``unseeded-random`` in the seedable planes
"""
