"""``kft lint`` — repo-native AST static analysis.

The reference stack ships correctness tooling alongside the code: Go
controllers run ``go vet`` + ThreadSanitizer-adjacent race checks in
presubmit, and Kueue/training-operator gate every PR on repo-specific
linters. This package is that layer for the TPU platform: an AST-walking
engine (:mod:`.engine`) plus passes (:mod:`.passes`) that machine-check the
invariants this codebase discovered the hard way — lock discipline around
background threads, a single definition site for every ``kft_*`` metric
name, no device syncs on the training/serving hot loops, thread + clock
hygiene, and seedable randomness in the chaos/sched planes.

Suppressions are inline (``# kft: noqa[RULE]``) and must carry the
invariant that makes the flagged line safe; legacy findings are pinned in
``lint_baseline.json`` so new ones fail while the baseline burns down.
"""

from kubeflow_tpu.analysis.engine import (
    Finding,
    LintConfig,
    LintResult,
    load_config,
    run_lint,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintConfig",
    "LintResult",
    "load_config",
    "run_lint",
    "write_baseline",
]
