"""gRPC suggestion service: the Katib algorithm-pod boundary.

Reference analog: [katib] pkg/apis/manager/v1beta1/api.proto with
``SuggestionService.GetSuggestions`` and the per-algorithm Python services
behind it (UNVERIFIED, mount empty, SURVEY.md §0). Katib deploys one
suggestion pod per experiment and the controller calls it over gRPC — the
algorithm lives out-of-process so experiments survive controller restarts
and algorithms scale independently.

This image has grpcio but no protoc Python plugin (SURVEY.md §0), so the
service uses grpc *generic handlers* with JSON payloads — the same process
boundary and RPC names, minus generated stubs. Methods:

- ``/kubeflow_tpu.Suggestion/GetSuggestions``
- ``/kubeflow_tpu.Suggestion/ValidateAlgorithmSettings``
- ``/kubeflow_tpu.EarlyStopping/GetEarlyStoppingRules`` (rule echo)
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Any

import grpc

from kubeflow_tpu.tune.spec import ExperimentSpec, TrialAssignment
from kubeflow_tpu.tune.suggest import Suggester, make_suggester

_SERVICE = "kubeflow_tpu.Suggestion"


def _ser(obj: Any) -> bytes:
    return json.dumps(obj).encode()


def _des(b: bytes) -> Any:
    return json.loads(b.decode())


class SuggestionService:
    """Stateful per-experiment suggester registry behind the RPC surface."""

    def __init__(self, seed: int = 0):
        self._suggesters: dict[str, Suggester] = {}
        self._seed = seed

    # RPC bodies ------------------------------------------------------------

    def get_suggestions(self, request: dict) -> dict:
        spec = ExperimentSpec.from_dict(request["experiment"])
        sug = self._suggesters.get(spec.name)
        if sug is None:
            sug = make_suggester(spec, self._seed)
            self._suggesters[spec.name] = sug
        history = [(h["parameters"], float(h["objective"])) for h in request.get("history", [])]
        assignments = sug.suggest(int(request.get("count", 1)), history)
        return {
            "assignments": [
                {"trial_id": a.trial_id, "parameters": a.parameters}
                for a in assignments
            ]
        }

    def validate(self, request: dict) -> dict:
        try:
            spec = ExperimentSpec.from_dict(request["experiment"])
            spec.validate()
            make_suggester(spec, self._seed)
            return {"valid": True, "message": ""}
        except Exception as e:
            return {"valid": False, "message": str(e)}

    # grpc plumbing ---------------------------------------------------------

    def handler(self) -> grpc.GenericRpcHandler:
        svc = self

        def get_suggestions(req: bytes, ctx) -> bytes:
            return _ser(svc.get_suggestions(_des(req)))

        def validate(req: bytes, ctx) -> bytes:
            return _ser(svc.validate(_des(req)))

        return grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                "GetSuggestions": grpc.unary_unary_rpc_method_handler(
                    get_suggestions
                ),
                "ValidateAlgorithmSettings": grpc.unary_unary_rpc_method_handler(
                    validate
                ),
            },
        )


def serve(port: int = 0, seed: int = 0) -> tuple[grpc.Server, int]:
    """Start the suggestion server; returns (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((SuggestionService(seed).handler(),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, bound


class SuggestionClient:
    """Controller-side stub for a remote suggestion service."""

    def __init__(self, address: str):
        self._channel = grpc.insecure_channel(address)
        self._get = self._channel.unary_unary(
            f"/{_SERVICE}/GetSuggestions",
            request_serializer=_ser,
            response_deserializer=_des,
        )
        self._validate = self._channel.unary_unary(
            f"/{_SERVICE}/ValidateAlgorithmSettings",
            request_serializer=_ser,
            response_deserializer=_des,
        )

    def get_suggestions(
        self,
        experiment: ExperimentSpec,
        history: list[tuple[dict, float]],
        count: int,
    ) -> list[TrialAssignment]:
        resp = self._get(
            {
                "experiment": experiment.to_dict(),
                "history": [{"parameters": p, "objective": v} for p, v in history],
                "count": count,
            }
        )
        return [
            TrialAssignment(parameters=a["parameters"], trial_id=a["trial_id"])
            for a in resp["assignments"]
        ]

    def validate(self, experiment: ExperimentSpec) -> tuple[bool, str]:
        resp = self._validate({"experiment": experiment.to_dict()})
        return bool(resp["valid"]), resp["message"]

    def close(self) -> None:
        self._channel.close()


class RemoteSuggester(Suggester):
    """Adapter: ExperimentController-compatible Suggester over the RPC."""

    def __init__(self, spec: ExperimentSpec, client: SuggestionClient):
        self.spec = spec
        self.client = client

    def suggest(self, count, history):
        return self.client.get_suggestions(self.spec, list(history), count)
