"""Experiment / Trial API types with Katib v1beta1 semantics.

Reference analog: [katib] pkg/apis/controller/{experiments,suggestions,
trials}/v1beta1/*_types.go (UNVERIFIED, mount empty, SURVEY.md §0):
search space (feasible ranges), objective (metric, goal, type), algorithm,
``parallelTrialCount``/``maxTrialCount``/``maxFailedTrialCount``, trial
template with ``${trialParameters.x}`` substitution, resume policy.
"""

from __future__ import annotations

import dataclasses
import enum
import math
import uuid
from typing import Any, Mapping, Sequence


class ParameterType(str, enum.Enum):
    DOUBLE = "double"
    INT = "int"
    CATEGORICAL = "categorical"
    DISCRETE = "discrete"


@dataclasses.dataclass(frozen=True)
class ParameterSpec:
    """One search-space dimension (Katib FeasibleSpace)."""

    name: str
    type: ParameterType
    min: float | None = None
    max: float | None = None
    values: tuple[Any, ...] = ()  # categorical/discrete
    log_scale: bool = False  # sample in log10 space (lr-style params)
    step: float | None = None  # grid step for double/int

    def __post_init__(self):
        if self.type in (ParameterType.DOUBLE, ParameterType.INT):
            if self.min is None or self.max is None or self.min > self.max:
                raise ValueError(f"{self.name}: numeric params need min<=max")
            if self.log_scale and self.min <= 0:
                raise ValueError(f"{self.name}: log scale needs min>0")
        elif not self.values:
            raise ValueError(f"{self.name}: {self.type.value} params need values")

    # -- numeric <-> unit-interval mapping (optimizers work in [0,1]^d) -----

    def to_unit(self, v: Any) -> float:
        if self.type is ParameterType.CATEGORICAL or self.type is ParameterType.DISCRETE:
            return self.values.index(v) / max(1, len(self.values) - 1)
        lo, hi = float(self.min), float(self.max)
        if self.log_scale:
            lo, hi, v = math.log10(lo), math.log10(hi), math.log10(float(v))
        return 0.0 if hi == lo else (float(v) - lo) / (hi - lo)

    def from_unit(self, u: float) -> Any:
        u = min(1.0, max(0.0, u))
        if self.type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            return self.values[min(len(self.values) - 1, int(u * len(self.values)))]
        lo, hi = float(self.min), float(self.max)
        if self.log_scale:
            v = 10 ** (math.log10(lo) + u * (math.log10(hi) - math.log10(lo)))
        else:
            v = lo + u * (hi - lo)
        return int(round(v)) if self.type is ParameterType.INT else v

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "type": self.type.value,
            "min": self.min,
            "max": self.max,
            "values": list(self.values),
            "log_scale": self.log_scale,
            "step": self.step,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ParameterSpec":
        return cls(
            name=d["name"],
            type=ParameterType(d["type"]),
            min=d.get("min"),
            max=d.get("max"),
            values=tuple(d.get("values", ())),
            log_scale=bool(d.get("log_scale", False)),
            step=d.get("step"),
        )

    def grid(self, n: int = 5) -> list[Any]:
        if self.type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            return list(self.values)
        if self.step is not None:
            k = int(round((float(self.max) - float(self.min)) / self.step)) + 1
            vals = [float(self.min) + i * self.step for i in range(k)]
        else:
            vals = [self.from_unit(i / max(1, n - 1)) for i in range(n)]
        if self.type is ParameterType.INT:
            vals = sorted({int(round(v)) for v in vals})
        return vals


class ObjectiveType(str, enum.Enum):
    MINIMIZE = "minimize"
    MAXIMIZE = "maximize"


@dataclasses.dataclass(frozen=True)
class Objective:
    metric: str
    type: ObjectiveType = ObjectiveType.MINIMIZE
    goal: float | None = None  # reach it ⇒ experiment complete
    additional_metrics: tuple[str, ...] = ()

    def better(self, a: float, b: float) -> bool:
        """True if a is strictly better than b."""
        return a < b if self.type is ObjectiveType.MINIMIZE else a > b

    def reached(self, v: float) -> bool:
        if self.goal is None:
            return False
        return v <= self.goal if self.type is ObjectiveType.MINIMIZE else v >= self.goal


@dataclasses.dataclass(frozen=True)
class AlgorithmSpec:
    name: str = "random"  # random | grid | bayesian | tpe | hyperband | cmaes
    settings: Mapping[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class EarlyStoppingSpec:
    name: str = "medianstop"  # or "none"
    min_trials_required: int = 3
    start_step: int = 4


class TrialState(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    EARLY_STOPPED = "EarlyStopped"
    KILLED = "Killed"


@dataclasses.dataclass
class TrialAssignment:
    """One suggested parameter set (Katib's ParameterAssignment list)."""

    parameters: dict[str, Any]
    trial_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:8])


@dataclasses.dataclass
class Trial:
    assignment: TrialAssignment
    state: TrialState = TrialState.CREATED
    observations: list[tuple[int, float]] = dataclasses.field(default_factory=list)
    metrics: dict[str, float] = dataclasses.field(default_factory=dict)
    message: str = ""

    @property
    def objective_value(self) -> float | None:
        return self.metrics.get("__objective__")


@dataclasses.dataclass(frozen=True)
class ExperimentSpec:
    name: str
    parameters: tuple[ParameterSpec, ...]
    objective: Objective
    algorithm: AlgorithmSpec = dataclasses.field(default_factory=AlgorithmSpec)
    parallel_trial_count: int = 3
    max_trial_count: int = 12
    max_failed_trial_count: int = 3
    early_stopping: EarlyStoppingSpec | None = None
    # Template: JobSpec-shaped dict; "${trialParameters.x}" placeholders are
    # substituted per-trial (Katib trial-template semantics).
    trial_template: Mapping[str, Any] | None = None

    def validate(self) -> None:
        if not self.parameters:
            raise ValueError("experiment needs at least one parameter")
        if self.parallel_trial_count < 1 or self.max_trial_count < 1:
            raise ValueError("trial counts must be >= 1")
        names = [p.name for p in self.parameters]
        if len(set(names)) != len(names):
            raise ValueError("duplicate parameter names")

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "parameters": [p.to_dict() for p in self.parameters],
            "objective": {
                "metric": self.objective.metric,
                "type": self.objective.type.value,
                "goal": self.objective.goal,
                "additional_metrics": list(self.objective.additional_metrics),
            },
            "algorithm": {
                "name": self.algorithm.name,
                "settings": dict(self.algorithm.settings),
            },
            "parallel_trial_count": self.parallel_trial_count,
            "max_trial_count": self.max_trial_count,
            "max_failed_trial_count": self.max_failed_trial_count,
            **(
                {"trial_template": self.trial_template}
                if self.trial_template is not None
                else {}
            ),
            **(
                {
                    "early_stopping": {
                        "name": self.early_stopping.name,
                        "min_trials_required":
                            self.early_stopping.min_trials_required,
                        "start_step": self.early_stopping.start_step,
                    }
                }
                if self.early_stopping is not None
                else {}
            ),
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ExperimentSpec":
        obj = d.get("objective", {})
        alg = d.get("algorithm", {})
        return cls(
            name=d["name"],
            parameters=tuple(ParameterSpec.from_dict(p) for p in d["parameters"]),
            objective=Objective(
                metric=obj["metric"],
                type=ObjectiveType(obj.get("type", "minimize")),
                goal=obj.get("goal"),
                additional_metrics=tuple(obj.get("additional_metrics", ())),
            ),
            algorithm=AlgorithmSpec(
                name=alg.get("name", "random"), settings=dict(alg.get("settings", {}))
            ),
            parallel_trial_count=int(d.get("parallel_trial_count", 3)),
            max_trial_count=int(d.get("max_trial_count", 12)),
            max_failed_trial_count=int(d.get("max_failed_trial_count", 3)),
            # without these, a manifest-borne Experiment would silently lose
            # its trial command — the one thing that makes it runnable
            trial_template=d.get("trial_template"),
            early_stopping=(
                EarlyStoppingSpec(
                    name=es.get("name", "medianstop"),
                    min_trials_required=int(es.get("min_trials_required", 3)),
                    start_step=int(es.get("start_step", 4)),
                )
                if (es := d.get("early_stopping")) is not None
                else None
            ),
        )


def substitute_template(template: Any, parameters: Mapping[str, Any]) -> Any:
    """Recursively substitute ``${trialParameters.<name>}`` placeholders."""
    mapping = {f"trialParameters.{k}": str(v) for k, v in parameters.items()}
    if isinstance(template, str):
        # string.Template with dotted identifiers needs braces form
        out = template
        for k, v in mapping.items():
            out = out.replace("${" + k + "}", v)
        return out
    if isinstance(template, Mapping):
        return {k: substitute_template(v, parameters) for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        return type(template)(substitute_template(v, parameters) for v in template)
    return template
