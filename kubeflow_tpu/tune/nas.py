"""Neural architecture search: differentiable (DARTS-style) one-shot NAS.

The reference ships NAS as Katib suggestion services (ENAS, DARTS — Katib
pkg/suggestion/v1beta1/nas/{enas,darts}/ upstream analog, UNVERIFIED,
SURVEY.md §0) whose trials train torch supernets. TPU-natively the whole
search IS one SPMD program: the supernet's mixed edge — a softmax(alpha)-
weighted sum over candidate ops — is dense math XLA fuses onto the MXU, and
the bilevel step (weights on the train split, architecture params on the
val split) is two jitted updates. No controller/service split is needed;
the searcher runs in-process or inside any JAXJob trial.

Search space: a single cell DAG of ``nodes`` intermediate nodes; every
edge (i→j) mixes the candidate ops. ``derive()`` returns the discrete
architecture (argmax op per edge, top-2 edges per node, DARTS-style).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

#: candidate op name → flax module factory (channels → module)
OPS: dict[str, Callable[[int], nn.Module]] = {}


def _register(name):
    def deco(factory):
        OPS[name] = factory
        return factory

    return deco


@_register("conv3")
def _conv3(ch):
    return nn.Conv(ch, (3, 3), padding="SAME")


@_register("conv1")
def _conv1(ch):
    return nn.Conv(ch, (1, 1))


@_register("skip")
def _skip(ch):
    class Skip(nn.Module):
        @nn.compact
        def __call__(self, x):
            return x

    return Skip()


@_register("zero")
def _zero(ch):
    class Zero(nn.Module):
        @nn.compact
        def __call__(self, x):
            return jnp.zeros_like(x)

    return Zero()


@_register("maxpool")
def _maxpool(ch):
    class Pool(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.max_pool(
                x, (3, 3), strides=(1, 1), padding="SAME"
            )

    return Pool()


@dataclasses.dataclass(frozen=True)
class NASSpace:
    """Cell-based space (the Katib NAS operations/graph config analog)."""

    ops: tuple[str, ...] = ("conv3", "conv1", "skip", "maxpool", "zero")
    nodes: int = 3  # intermediate nodes; node j gets edges from all i<j+1
    channels: int = 16
    num_classes: int = 10
    #: [H, W, C] of the images the searcher will see — the stem conv's
    #: params are initialized against this shape
    input_shape: tuple[int, int, int] = (8, 8, 1)

    def __post_init__(self):
        unknown = [o for o in self.ops if o not in OPS]
        if unknown:
            raise ValueError(f"unknown ops {unknown}; have {sorted(OPS)}")

    @property
    def edges(self) -> list[tuple[int, int]]:
        """(from_node, to_node); node 0 is the cell input."""
        return [(i, j) for j in range(1, self.nodes + 1) for i in range(j)]


class SuperNet(nn.Module):
    """One-shot model: stem → mixed-op cell → head. Architecture weights
    ``alpha`` [n_edges, n_ops] come in as an argument so the same apply
    serves both bilevel updates. ``weights_are_probs`` makes alpha rows
    direct mixing weights (ENAS passes hard one-hot/zero rows — weight
    sharing: one parameter set, many sampled paths) instead of logits."""

    space: NASSpace
    weights_are_probs: bool = False

    @nn.compact
    def __call__(self, x, alpha):
        sp = self.space
        x = nn.Conv(sp.channels, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        states = [x]
        for j in range(1, sp.nodes + 1):
            acc = 0.0
            for e, (i, jj) in enumerate(sp.edges):
                if jj != j:
                    continue
                w = (
                    alpha[e] if self.weights_are_probs
                    else jax.nn.softmax(alpha[e])
                )
                mixed = 0.0
                for k, op_name in enumerate(sp.ops):
                    op = OPS[op_name](sp.channels)
                    mixed = mixed + w[k] * op(states[i])
                acc = acc + mixed
            states.append(nn.relu(nn.LayerNorm()(acc)))
        out = jnp.mean(states[-1], axis=(1, 2))
        return nn.Dense(sp.num_classes)(out)


@dataclasses.dataclass
class DerivedCell:
    """Discrete architecture: chosen op per kept edge."""

    edges: list[tuple[int, int, str]]  # (from, to, op)

    def to_dict(self) -> dict:
        return {"edges": [list(e) for e in self.edges]}


class DARTSSearcher:
    """First-order DARTS: alternate w-steps (train split) and alpha-steps
    (val split), both jitted; ``derive`` reads off the discrete cell."""

    def __init__(
        self,
        space: NASSpace,
        *,
        w_lr: float = 1e-2,
        alpha_lr: float = 3e-3,
        seed: int = 0,
    ):
        self.space = space
        self.net = SuperNet(space)
        rng = jax.random.PRNGKey(seed)
        n_edges, n_ops = len(space.edges), len(space.ops)
        self.alpha = jnp.zeros((n_edges, n_ops))
        dummy = jnp.zeros((1, *space.input_shape))
        self.w = self.net.init(rng, dummy, self.alpha)
        self.w_opt = optax.adam(w_lr)
        self.a_opt = optax.adam(alpha_lr)
        self.w_state = self.w_opt.init(self.w)
        self.a_state = self.a_opt.init(self.alpha)
        self._w_step = jax.jit(self._make_step(wrt="w"))
        self._a_step = jax.jit(self._make_step(wrt="alpha"))

    def _loss(self, w, alpha, batch):
        logits = self.net.apply(w, batch["image"], alpha)
        labels = batch["label"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    def _make_step(self, wrt: str):
        def step(w, alpha, opt_state, batch):
            if wrt == "w":
                loss, g = jax.value_and_grad(self._loss, argnums=0)(
                    w, alpha, batch
                )
                updates, opt_state = self.w_opt.update(g, opt_state, w)
                return optax.apply_updates(w, updates), opt_state, loss
            loss, g = jax.value_and_grad(self._loss, argnums=1)(
                w, alpha, batch
            )
            updates, opt_state = self.a_opt.update(g, opt_state, alpha)
            return optax.apply_updates(alpha, updates), opt_state, loss

        return step

    def step(
        self,
        train_batch: Mapping[str, Any],
        val_batch: Mapping[str, Any],
    ) -> dict[str, float]:
        """One bilevel iteration; returns both losses."""
        self.w, self.w_state, w_loss = self._w_step(
            self.w, self.alpha, self.w_state, train_batch
        )
        self.alpha, self.a_state, a_loss = self._a_step(
            self.w, self.alpha, self.a_state, val_batch
        )
        return {"w_loss": float(w_loss), "alpha_loss": float(a_loss)}

    def search(
        self,
        data: Callable[[int], tuple[Mapping[str, Any], Mapping[str, Any]]],
        steps: int,
    ) -> DerivedCell:
        for i in range(steps):
            train_batch, val_batch = data(i)
            self.step(train_batch, val_batch)
        return self.derive()

    def derive(self, keep_per_node: int = 2) -> DerivedCell:
        """Discrete cell: per edge the argmax non-zero op; per node keep the
        ``keep_per_node`` strongest incoming edges (DARTS derivation)."""
        sp = self.space
        alpha = np.asarray(self.alpha)
        zero_idx = sp.ops.index("zero") if "zero" in sp.ops else None
        chosen: list[tuple[int, int, str, float]] = []
        for e, (i, j) in enumerate(sp.edges):
            probs = np.exp(alpha[e] - alpha[e].max())
            probs = probs / probs.sum()
            order = np.argsort(-probs)
            best = next(
                (k for k in order if zero_idx is None or k != zero_idx),
                order[0],
            )
            chosen.append((i, j, sp.ops[int(best)], float(probs[best])))
        edges: list[tuple[int, int, str]] = []
        for j in range(1, sp.nodes + 1):
            incoming = sorted(
                (c for c in chosen if c[1] == j), key=lambda c: -c[3]
            )[:keep_per_node]
            edges.extend((i, jj, op) for i, jj, op, _ in incoming)
        return DerivedCell(edges=edges)

    def alpha_entropy(self) -> float:
        """Mean per-edge entropy of the op distribution — falls as the
        search commits to an architecture."""
        p = jax.nn.softmax(self.alpha, axis=-1)
        ent = -(p * jnp.log(p + 1e-9)).sum(-1)
        return float(ent.mean())


# --------------------------------------------------------------------------- #
# ENAS: RL-controller NAS with weight sharing
# --------------------------------------------------------------------------- #


class ControllerNet(nn.Module):
    """ENAS's autoregressive LSTM controller over the micro cell space
    (Katib pkg/suggestion/v1beta1/nas/enas upstream analog — UNVERIFIED,
    SURVEY.md §0). For each intermediate node it emits two (input-node,
    op) decisions, each conditioned on everything sampled so far through
    the LSTM state; invalid input nodes (>= current node) are masked. The
    decision count is static, so the whole rollout — sampling included —
    is one jitted program.

    ``__call__(rng, greedy)`` → (inputs [nodes,2], ops [nodes,2],
    sum-log-prob of the taken decisions, total policy entropy)."""

    space: NASSpace
    hidden: int = 64

    @nn.compact
    def __call__(self, rng, greedy: bool = False):
        sp = self.space
        n_in = sp.nodes + 1  # candidate input nodes (0 = cell input)
        cell = nn.OptimizedLSTMCell(features=self.hidden)
        carry = cell.initialize_carry(jax.random.PRNGKey(0), (1, self.hidden))
        inp_embed = self.param(
            "inp_embed", nn.initializers.normal(0.1), (n_in, self.hidden)
        )
        op_embed = self.param(
            "op_embed", nn.initializers.normal(0.1),
            (len(sp.ops), self.hidden),
        )
        start = self.param(
            "start", nn.initializers.normal(0.1), (self.hidden,)
        )
        head_in = nn.Dense(n_in, name="head_input")
        head_op = nn.Dense(len(sp.ops), name="head_op")

        def pick(rng, logits):
            p = jax.nn.log_softmax(logits)
            choice = jnp.where(
                greedy, jnp.argmax(logits), jax.random.categorical(rng, logits)
            )
            ent = -(jnp.exp(p) * p).sum()
            return choice, p[choice], ent

        x = start[None]
        inputs, ops = [], []
        logp = 0.0
        entropy = 0.0
        for j in range(1, sp.nodes + 1):
            row_in, row_op = [], []
            for _slot in range(2):
                carry, h = cell(carry, x)
                mask = jnp.where(jnp.arange(n_in) < j, 0.0, -1e9)
                rng, k = jax.random.split(rng)
                i, lp, ent = pick(k, head_in(h)[0] + mask)
                logp, entropy = logp + lp, entropy + ent
                x = inp_embed[i][None]
                carry, h = cell(carry, x)
                rng, k = jax.random.split(rng)
                o, lp, ent = pick(k, head_op(h)[0])
                logp, entropy = logp + lp, entropy + ent
                x = op_embed[o][None]
                row_in.append(i)
                row_op.append(o)
            inputs.append(jnp.stack(row_in))
            ops.append(jnp.stack(row_op))
        return jnp.stack(inputs), jnp.stack(ops), logp, entropy


class ENASSearcher:
    """ENAS (Pham et al.): weight sharing + REINFORCE.

    Alternates two jitted phases per :meth:`step`: (1) train the SHARED
    supernet weights on the train split through one controller-sampled
    path (hard one-hot edge weights — the TPU-idiom form of ENAS's
    subgraph activation: dense masked compute instead of a dynamic
    graph); (2) update the controller by REINFORCE on the sampled path's
    validation accuracy against a moving-average baseline, with an
    entropy bonus. ``derive()`` is the greedy controller rollout.
    """

    def __init__(
        self,
        space: NASSpace,
        *,
        w_lr: float = 1e-2,
        ctrl_lr: float = 3e-3,
        entropy_coef: float = 1e-3,
        baseline_decay: float = 0.8,
        seed: int = 0,
    ):
        self.space = space
        self.net = SuperNet(space, weights_are_probs=True)
        self.controller = ControllerNet(space)
        rng = jax.random.PRNGKey(seed)
        r_w, r_c, self._rng = jax.random.split(rng, 3)
        n_edges, n_ops = len(space.edges), len(space.ops)
        dummy_alpha = jnp.zeros((n_edges, n_ops))
        dummy = jnp.zeros((1, *space.input_shape))
        self.w = self.net.init(r_w, dummy, dummy_alpha)
        self.ctrl = self.controller.init(r_c, jax.random.PRNGKey(0))
        self.w_opt = optax.adam(w_lr)
        self.c_opt = optax.adam(ctrl_lr)
        self.w_state = self.w_opt.init(self.w)
        self.c_state = self.c_opt.init(self.ctrl)
        self.entropy_coef = entropy_coef
        self.baseline_decay = baseline_decay
        self.baseline = 0.0

        #: edge index lookup: (from, to) → position in space.edges
        self._edge_idx = {e: n for n, e in enumerate(space.edges)}

        def arch_weights(inputs, ops):
            """Sampled decisions → hard [n_edges, n_ops] mixing weights.
            Unselected edges are all-zero rows; a node picking the same
            input twice keeps weight 1 (jnp.maximum, not sum)."""
            A = jnp.zeros((n_edges, n_ops))
            for j in range(1, space.nodes + 1):
                for slot in range(2):
                    i, o = inputs[j - 1, slot], ops[j - 1, slot]
                    # one-hot over the incoming edges of node j
                    for src in range(j):
                        e = self._edge_idx[(src, j)]
                        A = A.at[e].max(
                            (i == src) * jax.nn.one_hot(o, n_ops)
                        )
            return A

        self._arch_weights = arch_weights

        def w_step(w, w_state, ctrl, rng, batch):
            inputs, ops, _, _ = self.controller.apply(ctrl, rng)
            A = arch_weights(inputs, ops)

            def loss_fn(w):
                logits = self.net.apply(w, batch["image"], A)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, batch["label"]
                ).mean()

            loss, g = jax.value_and_grad(loss_fn)(w)
            updates, w_state = self.w_opt.update(g, w_state, w)
            return optax.apply_updates(w, updates), w_state, loss

        def ctrl_step(ctrl, c_state, w, rng, batch, baseline):
            def loss_fn(ctrl):
                inputs, ops, logp, entropy = self.controller.apply(ctrl, rng)
                A = arch_weights(inputs, ops)
                logits = self.net.apply(w, batch["image"], A)
                acc = (jnp.argmax(logits, -1) == batch["label"]).mean()
                reward = jax.lax.stop_gradient(acc)
                loss = (
                    -(reward - baseline) * logp
                    - self.entropy_coef * entropy
                )
                return loss, reward

            (loss, reward), g = jax.value_and_grad(loss_fn, has_aux=True)(
                ctrl
            )
            updates, c_state = self.c_opt.update(g, c_state, ctrl)
            return optax.apply_updates(ctrl, updates), c_state, loss, reward

        self._w_step = jax.jit(w_step)
        self._ctrl_step = jax.jit(ctrl_step)
        self._greedy = jax.jit(
            lambda ctrl, rng: self.controller.apply(ctrl, rng, greedy=True)
        )

    def step(
        self,
        train_batch: Mapping[str, Any],
        val_batch: Mapping[str, Any],
    ) -> dict[str, float]:
        """One ENAS iteration: shared-weight step on a sampled path, then
        a REINFORCE controller step on validation reward."""
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        self.w, self.w_state, w_loss = self._w_step(
            self.w, self.w_state, self.ctrl, k1, train_batch
        )
        self.ctrl, self.c_state, c_loss, reward = self._ctrl_step(
            self.ctrl, self.c_state, self.w, k2, val_batch, self.baseline
        )
        reward = float(reward)
        d = self.baseline_decay
        self.baseline = d * self.baseline + (1 - d) * reward
        return {
            "w_loss": float(w_loss),
            "ctrl_loss": float(c_loss),
            "reward": reward,
            "baseline": self.baseline,
        }

    def search(
        self,
        data: Callable[[int], tuple[Mapping[str, Any], Mapping[str, Any]]],
        steps: int,
    ) -> DerivedCell:
        for i in range(steps):
            train_batch, val_batch = data(i)
            self.step(train_batch, val_batch)
        return self.derive()

    def derive(self) -> DerivedCell:
        """Greedy (argmax) controller rollout → discrete cell, same
        DerivedCell shape the DARTS searcher emits."""
        inputs, ops, _, _ = self._greedy(self.ctrl, jax.random.PRNGKey(0))
        inputs, ops = np.asarray(inputs), np.asarray(ops)
        edges: list[tuple[int, int, str]] = []
        for j in range(1, self.space.nodes + 1):
            seen: set[tuple[int, str]] = set()
            for slot in range(2):
                i = int(inputs[j - 1, slot])
                op = self.space.ops[int(ops[j - 1, slot])]
                # same input with DIFFERENT ops is a real architecture the
                # reward was measured on (the edge computes op_a + op_b) —
                # keep both; only an exact duplicate collapses
                if (i, op) in seen:
                    continue
                seen.add((i, op))
                edges.append((i, j, op))
        return DerivedCell(edges=edges)
