"""Neural architecture search: differentiable (DARTS-style) one-shot NAS.

The reference ships NAS as Katib suggestion services (ENAS, DARTS — Katib
pkg/suggestion/v1beta1/nas/{enas,darts}/ upstream analog, UNVERIFIED,
SURVEY.md §0) whose trials train torch supernets. TPU-natively the whole
search IS one SPMD program: the supernet's mixed edge — a softmax(alpha)-
weighted sum over candidate ops — is dense math XLA fuses onto the MXU, and
the bilevel step (weights on the train split, architecture params on the
val split) is two jitted updates. No controller/service split is needed;
the searcher runs in-process or inside any JAXJob trial.

Search space: a single cell DAG of ``nodes`` intermediate nodes; every
edge (i→j) mixes the candidate ops. ``derive()`` returns the discrete
architecture (argmax op per edge, top-2 edges per node, DARTS-style).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

#: candidate op name → flax module factory (channels → module)
OPS: dict[str, Callable[[int], nn.Module]] = {}


def _register(name):
    def deco(factory):
        OPS[name] = factory
        return factory

    return deco


@_register("conv3")
def _conv3(ch):
    return nn.Conv(ch, (3, 3), padding="SAME")


@_register("conv1")
def _conv1(ch):
    return nn.Conv(ch, (1, 1))


@_register("skip")
def _skip(ch):
    class Skip(nn.Module):
        @nn.compact
        def __call__(self, x):
            return x

    return Skip()


@_register("zero")
def _zero(ch):
    class Zero(nn.Module):
        @nn.compact
        def __call__(self, x):
            return jnp.zeros_like(x)

    return Zero()


@_register("maxpool")
def _maxpool(ch):
    class Pool(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.max_pool(
                x, (3, 3), strides=(1, 1), padding="SAME"
            )

    return Pool()


@dataclasses.dataclass(frozen=True)
class NASSpace:
    """Cell-based space (the Katib NAS operations/graph config analog)."""

    ops: tuple[str, ...] = ("conv3", "conv1", "skip", "maxpool", "zero")
    nodes: int = 3  # intermediate nodes; node j gets edges from all i<j+1
    channels: int = 16
    num_classes: int = 10
    #: [H, W, C] of the images the searcher will see — the stem conv's
    #: params are initialized against this shape
    input_shape: tuple[int, int, int] = (8, 8, 1)

    def __post_init__(self):
        unknown = [o for o in self.ops if o not in OPS]
        if unknown:
            raise ValueError(f"unknown ops {unknown}; have {sorted(OPS)}")

    @property
    def edges(self) -> list[tuple[int, int]]:
        """(from_node, to_node); node 0 is the cell input."""
        return [(i, j) for j in range(1, self.nodes + 1) for i in range(j)]


class SuperNet(nn.Module):
    """One-shot model: stem → mixed-op cell → head. Architecture weights
    ``alpha`` [n_edges, n_ops] come in as an argument so the same apply
    serves both bilevel updates."""

    space: NASSpace

    @nn.compact
    def __call__(self, x, alpha):
        sp = self.space
        x = nn.Conv(sp.channels, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        states = [x]
        for j in range(1, sp.nodes + 1):
            acc = 0.0
            for e, (i, jj) in enumerate(sp.edges):
                if jj != j:
                    continue
                w = jax.nn.softmax(alpha[e])
                mixed = 0.0
                for k, op_name in enumerate(sp.ops):
                    op = OPS[op_name](sp.channels)
                    mixed = mixed + w[k] * op(states[i])
                acc = acc + mixed
            states.append(nn.relu(nn.LayerNorm()(acc)))
        out = jnp.mean(states[-1], axis=(1, 2))
        return nn.Dense(sp.num_classes)(out)


@dataclasses.dataclass
class DerivedCell:
    """Discrete architecture: chosen op per kept edge."""

    edges: list[tuple[int, int, str]]  # (from, to, op)

    def to_dict(self) -> dict:
        return {"edges": [list(e) for e in self.edges]}


class DARTSSearcher:
    """First-order DARTS: alternate w-steps (train split) and alpha-steps
    (val split), both jitted; ``derive`` reads off the discrete cell."""

    def __init__(
        self,
        space: NASSpace,
        *,
        w_lr: float = 1e-2,
        alpha_lr: float = 3e-3,
        seed: int = 0,
    ):
        self.space = space
        self.net = SuperNet(space)
        rng = jax.random.PRNGKey(seed)
        n_edges, n_ops = len(space.edges), len(space.ops)
        self.alpha = jnp.zeros((n_edges, n_ops))
        dummy = jnp.zeros((1, *space.input_shape))
        self.w = self.net.init(rng, dummy, self.alpha)
        self.w_opt = optax.adam(w_lr)
        self.a_opt = optax.adam(alpha_lr)
        self.w_state = self.w_opt.init(self.w)
        self.a_state = self.a_opt.init(self.alpha)
        self._w_step = jax.jit(self._make_step(wrt="w"))
        self._a_step = jax.jit(self._make_step(wrt="alpha"))

    def _loss(self, w, alpha, batch):
        logits = self.net.apply(w, batch["image"], alpha)
        labels = batch["label"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    def _make_step(self, wrt: str):
        def step(w, alpha, opt_state, batch):
            if wrt == "w":
                loss, g = jax.value_and_grad(self._loss, argnums=0)(
                    w, alpha, batch
                )
                updates, opt_state = self.w_opt.update(g, opt_state, w)
                return optax.apply_updates(w, updates), opt_state, loss
            loss, g = jax.value_and_grad(self._loss, argnums=1)(
                w, alpha, batch
            )
            updates, opt_state = self.a_opt.update(g, opt_state, alpha)
            return optax.apply_updates(alpha, updates), opt_state, loss

        return step

    def step(
        self,
        train_batch: Mapping[str, Any],
        val_batch: Mapping[str, Any],
    ) -> dict[str, float]:
        """One bilevel iteration; returns both losses."""
        self.w, self.w_state, w_loss = self._w_step(
            self.w, self.alpha, self.w_state, train_batch
        )
        self.alpha, self.a_state, a_loss = self._a_step(
            self.w, self.alpha, self.a_state, val_batch
        )
        return {"w_loss": float(w_loss), "alpha_loss": float(a_loss)}

    def search(
        self,
        data: Callable[[int], tuple[Mapping[str, Any], Mapping[str, Any]]],
        steps: int,
    ) -> DerivedCell:
        for i in range(steps):
            train_batch, val_batch = data(i)
            self.step(train_batch, val_batch)
        return self.derive()

    def derive(self, keep_per_node: int = 2) -> DerivedCell:
        """Discrete cell: per edge the argmax non-zero op; per node keep the
        ``keep_per_node`` strongest incoming edges (DARTS derivation)."""
        sp = self.space
        alpha = np.asarray(self.alpha)
        zero_idx = sp.ops.index("zero") if "zero" in sp.ops else None
        chosen: list[tuple[int, int, str, float]] = []
        for e, (i, j) in enumerate(sp.edges):
            probs = np.exp(alpha[e] - alpha[e].max())
            probs = probs / probs.sum()
            order = np.argsort(-probs)
            best = next(
                (k for k in order if zero_idx is None or k != zero_idx),
                order[0],
            )
            chosen.append((i, j, sp.ops[int(best)], float(probs[best])))
        edges: list[tuple[int, int, str]] = []
        for j in range(1, sp.nodes + 1):
            incoming = sorted(
                (c for c in chosen if c[1] == j), key=lambda c: -c[3]
            )[:keep_per_node]
            edges.extend((i, jj, op) for i, jj, op, _ in incoming)
        return DerivedCell(edges=edges)

    def alpha_entropy(self) -> float:
        """Mean per-edge entropy of the op distribution — falls as the
        search commits to an architecture."""
        p = jax.nn.softmax(self.alpha, axis=-1)
        ent = -(p * jnp.log(p + 1e-9)).sum(-1)
        return float(ent.mean())
