"""AutoML plane: the Katib equivalent (SURVEY.md §2.3, §7 step 6).

- ``spec``      — Experiment / Trial / search-space / objective types.
- ``suggest``   — suggestion algorithms behind one interface: random, grid,
                  bayesian (GP+EI), TPE, hyperband, CMA-ES.
- ``metrics``   — metrics collectors: stdout-regex scraper (zero-SDK, the
                  Katib sidecar trick) and TFEvents reader.
- ``earlystop`` — median-stop early stopping.
- ``controller``— Experiment controller: parallel trials through callables
                  or the orchestrator, optimal tracking, goal completion.
- ``service``   — gRPC suggestion service boundary (Katib's algorithm-pod
                  analog), JSON payloads over grpc generic handlers.
"""

from kubeflow_tpu.tune.spec import (
    ExperimentSpec,
    Objective,
    ObjectiveType,
    ParameterSpec,
    TrialAssignment,
)
from kubeflow_tpu.tune.suggest import make_suggester
from kubeflow_tpu.tune.controller import ExperimentController

__all__ = [
    "ExperimentSpec",
    "Objective",
    "ObjectiveType",
    "ParameterSpec",
    "TrialAssignment",
    "make_suggester",
    "ExperimentController",
]
