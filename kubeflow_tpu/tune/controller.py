"""Experiment controller: drive suggestions → parallel trials → optimum.

Reference analog: [katib] pkg/controller.v1beta1/{experiment,trial}/
(UNVERIFIED, mount empty, SURVEY.md §0, call stack §3.4): the experiment
controller asks the Suggestion service for N parameter sets, creates Trials
(each a Job/PyTorchJob from the trial template), watches metrics, tracks the
optimal trial, and completes on goal or maxTrialCount.

Two trial runners:

- ``CallableTrialRunner`` — trial = in-process function(parameters) →
  objective (the unit-test path, and the "tune a jitted train step on this
  chip" fast path: 16 trials of a small model can share one TPU).
- ``JobTrialRunner``      — trial = JAXJob through the orchestrator
  (``LocalCluster``): template → ``JobSpec`` with ``${trialParameters.x}``
  substituted, metrics scraped from worker rank-0 logs with the §5.5 regex
  scraper — the gang-scheduled path of §3.4.
"""

from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import threading
import time
from typing import Any, Callable, Mapping

from kubeflow_tpu.tune import metrics as metrics_mod
from kubeflow_tpu.tune.db import TrialDB
from kubeflow_tpu.tune.earlystop import make_early_stopper
from kubeflow_tpu.tune.spec import (
    ExperimentSpec,
    ObjectiveType,
    Trial,
    TrialAssignment,
    TrialState,
    substitute_template,
)
from kubeflow_tpu.tune.suggest import Suggester, make_suggester


class TrialRunner:
    """Runs one trial to completion, filling observations/metrics/state."""

    def run(self, trial: Trial, experiment: ExperimentSpec) -> None:
        raise NotImplementedError

    def stop(self, trial: Trial) -> None:  # early-stop hook
        pass


class CallableTrialRunner(TrialRunner):
    def __init__(
        self,
        fn: Callable[[dict], float | dict[str, float] | list[tuple[int, float]]],
    ):
        self.fn = fn

    def run(self, trial: Trial, experiment: ExperimentSpec) -> None:
        obj = experiment.objective
        try:
            result = self.fn(dict(trial.assignment.parameters))
        except Exception as e:
            trial.state = TrialState.FAILED
            trial.message = repr(e)
            return
        if isinstance(result, list):  # [(step, value), ...] curve
            trial.observations = list(result)
            trial.metrics[obj.metric] = result[-1][1]
        elif isinstance(result, Mapping):
            trial.metrics.update(result)
        else:
            trial.metrics[obj.metric] = float(result)
        if obj.metric in trial.metrics:
            trial.metrics["__objective__"] = trial.metrics[obj.metric]
        elif trial.observations:
            trial.metrics["__objective__"] = trial.observations[-1][1]
        trial.state = TrialState.SUCCEEDED


class JobTrialRunner(TrialRunner):
    """Trial = a JobSpec submitted to the orchestrator's LocalCluster."""

    def __init__(self, cluster, *, poll_s: float = 0.2, timeout_s: float = 300.0):
        self.cluster = cluster
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        self._uids: dict[str, str] = {}

    def run(self, trial: Trial, experiment: ExperimentSpec) -> None:
        from kubeflow_tpu.orchestrator.spec import JobSpec

        obj = experiment.objective
        manifest = substitute_template(
            dict(experiment.trial_template), trial.assignment.parameters
        )
        manifest["name"] = f"{experiment.name}-{trial.assignment.trial_id}"
        spec = JobSpec.from_dict(manifest)
        uid = self.cluster.submit(spec)
        self._uids[trial.assignment.trial_id] = uid
        trial.state = TrialState.RUNNING
        deadline = time.monotonic() + self.timeout_s
        terminal = None
        while time.monotonic() < deadline:
            status = self.cluster.status(uid)
            if status is not None and status.phase in ("Succeeded", "Failed"):
                terminal = status.phase
                break
            time.sleep(self.poll_s)
        log_text = self._logs(uid, spec)
        series = metrics_mod.collect_from_text(
            log_text, obj.metric, obj.additional_metrics
        )
        mine = series.get(obj.metric.lower(), [])
        trial.observations = mine
        minimize = obj.type is ObjectiveType.MINIMIZE
        val = metrics_mod.best(mine, minimize)
        if terminal == "Succeeded" and val is not None:
            trial.metrics[obj.metric] = mine[-1][1]
            trial.metrics["__objective__"] = val
            for extra in obj.additional_metrics:
                v = metrics_mod.latest(series.get(extra.lower(), []))
                if v is not None:
                    trial.metrics[extra] = v
            trial.state = TrialState.SUCCEEDED
        else:
            trial.state = TrialState.FAILED
            trial.message = (
                f"phase={terminal or 'Timeout'}, metric_found={val is not None}"
            )
            if terminal is None:  # hung job: release its gang claim
                try:
                    self.cluster.delete(uid)
                except Exception:
                    pass

    def _logs(self, uid: str, spec) -> str:
        texts = []
        for rtype in spec.replica_order():
            try:
                texts.append(self.cluster.logs(uid, rtype, 0))
            except Exception:
                pass
        return "\n".join(texts)

    def stop(self, trial: Trial) -> None:
        uid = self._uids.get(trial.assignment.trial_id)
        if uid is not None:
            try:
                self.cluster.delete(uid)
            except Exception:
                pass


@dataclasses.dataclass
class ExperimentStatus:
    trials: list[Trial]
    optimal: Trial | None
    succeeded: int
    failed: int
    early_stopped: int
    complete: bool
    reason: str


class ExperimentController:
    def __init__(
        self,
        spec: ExperimentSpec,
        runner: TrialRunner,
        *,
        suggester: Suggester | None = None,
        seed: int = 0,
        db: "TrialDB | None" = None,
        model_registry: Any | None = None,     # registry.store.ModelStore
        register_best_as: str | None = None,
        best_model_path: Callable[[Trial], "str | None"] | None = None,
    ):
        spec.validate()
        if register_best_as is not None and (
            model_registry is None or best_model_path is None
        ):
            raise ValueError(
                "register_best_as needs model_registry and best_model_path"
                " (a Trial → checkpoint-path mapping)"
            )
        self.spec = spec
        self.runner = runner
        self.model_registry = model_registry
        self.register_best_as = register_best_as
        self.best_model_path = best_model_path
        self.registered_best: Any | None = None   # ModelVersion once saved
        self.suggester = suggester or make_suggester(spec, seed)
        self.trials: list[Trial] = []
        self._lock = threading.Lock()
        self._stopper = make_early_stopper(spec.early_stopping, spec.objective)
        self.db = db
        if db is not None:
            # Resume (Katib ResumePolicy + db-manager semantics): terminal
            # trials re-enter history/lineage with their recorded metrics;
            # trials that were mid-flight when the previous controller died
            # are marked KILLED — their jobs are gone, and the budget lets
            # the suggester replace them.
            for t in db.load_trials(spec.name):
                if t.state in (TrialState.CREATED, TrialState.RUNNING):
                    t.state = TrialState.KILLED
                    t.message = "controller restarted mid-trial"
                    db.record_trial(spec.name, t)
                self.trials.append(t)

    def _persist(self, trial: Trial) -> None:
        if self.db is not None:
            self.db.record_trial(self.spec.name, trial)
            obj = self.spec.objective
            if trial.observations:
                # Append only when the stored log is an exact PREFIX of the
                # in-memory log (normalizing tuple-vs-list rows); anything
                # else — divergent values, a longer stored log from a prior
                # controller — is rewritten atomically. A blind tail-append
                # on divergence recorded wrong observations (ADVICE r2).
                want = [(int(s), float(v)) for s, v in trial.observations]
                have = self.db.observations(
                    self.spec.name, trial.assignment.trial_id, obj.metric
                )
                if have == want:
                    pass
                elif len(have) < len(want) and have == want[: len(have)]:
                    self.db.report_observations(
                        self.spec.name,
                        trial.assignment.trial_id,
                        obj.metric,
                        want[len(have):],
                    )
                else:
                    self.db.replace_observations(
                        self.spec.name,
                        trial.assignment.trial_id,
                        obj.metric,
                        want,
                    )

    # -- main loop ----------------------------------------------------------

    def run(self) -> ExperimentStatus:
        spec = self.spec
        obj = spec.objective
        reason = "max_trial_count reached"
        with cf.ThreadPoolExecutor(max_workers=spec.parallel_trial_count) as pool:
            pending: set[cf.Future] = set()
            while True:
                done_count = len(self._terminal())
                if self._failed_count() > spec.max_failed_trial_count:
                    reason = "max_failed_trial_count exceeded"
                    break
                if self._goal_reached():
                    reason = "objective goal reached"
                    break
                if done_count >= spec.max_trial_count:
                    break
                budget = spec.max_trial_count - len(self.trials)
                want = min(spec.parallel_trial_count - len(pending), budget)
                if want > 0:
                    # lineage-aware algorithms (PBT) need trial identities,
                    # not just (params, value) pairs
                    if hasattr(self.suggester, "suggest_trials"):
                        with self._lock:
                            snapshot = list(self.trials)
                        suggestions = self.suggester.suggest_trials(
                            want, snapshot
                        )
                    else:
                        suggestions = self.suggester.suggest(want, self._history())
                    if not suggestions and not pending:
                        reason = "search space exhausted"
                        break
                    for a in suggestions:
                        t = Trial(assignment=a)
                        with self._lock:
                            self.trials.append(t)
                        self._persist(t)
                        pending.add(pool.submit(self._run_one, t))
                if not pending:
                    continue
                finished, pending = cf.wait(
                    pending, return_when=cf.FIRST_COMPLETED
                )
                for f in finished:
                    f.result()  # surface runner crashes
            for f in pending:  # drain in-flight trials before reporting
                f.result()
        self._register_best()
        return self.status(complete=True, reason=reason)

    def _register_best(self) -> None:
        """Katib → model-registry handoff: the winning trial's model
        enters the registry as a new version with a ``tune_trial``
        lineage edge carrying the full assignment and objective, so
        "which hyperparameters produced the production model" stays
        answerable after the experiment object is gone."""
        if self.register_best_as is None:
            return
        best = self.optimal_trial()
        if best is None:
            return
        path = self.best_model_path(best)
        if not path:
            return
        self.registered_best = self.model_registry.register_version(
            self.register_best_as,
            path,
            source_uri="file://" + str(path),
            metadata={
                "experiment": self.spec.name,
                "trial_id": best.assignment.trial_id,
                "parameters": dict(best.assignment.parameters),
                "objective": best.metrics.get("__objective__"),
            },
            lineage=[(
                "tune_trial",
                f"{self.spec.name}/{best.assignment.trial_id}",
                {
                    "parameters": dict(best.assignment.parameters),
                    "objective": best.metrics.get("__objective__"),
                },
            )],
        )

    def _run_one(self, trial: Trial) -> None:
        trial.state = TrialState.RUNNING
        self._persist(trial)
        self.runner.run(trial, self.spec)
        if self._stopper is not None and trial.state is TrialState.SUCCEEDED:
            # retroactive medianstop: mark hopeless completed trials so the
            # suggester's history de-weights them (in-process trials finish
            # too fast to interrupt mid-flight; Job trials get stop()ed).
            with self._lock:
                others = [t for t in self.trials if t is not trial]
                if self._stopper.should_stop(trial, others):
                    trial.state = TrialState.EARLY_STOPPED
                    self.runner.stop(trial)
        self._persist(trial)

    # -- bookkeeping ---------------------------------------------------------

    def _terminal(self) -> list[Trial]:
        with self._lock:
            return [
                t
                for t in self.trials
                if t.state
                in (
                    TrialState.SUCCEEDED,
                    TrialState.FAILED,
                    TrialState.EARLY_STOPPED,
                    TrialState.KILLED,
                )
            ]

    def _failed_count(self) -> int:
        with self._lock:
            return sum(t.state is TrialState.FAILED for t in self.trials)

    def _history(self) -> list[tuple[dict, float]]:
        with self._lock:
            return [
                (dict(t.assignment.parameters), t.metrics["__objective__"])
                for t in self.trials
                if t.state is TrialState.SUCCEEDED and "__objective__" in t.metrics
            ]

    def _goal_reached(self) -> bool:
        obj = self.spec.objective
        return any(obj.reached(v) for _, v in self._history())

    def optimal_trial(self) -> Trial | None:
        obj = self.spec.objective
        best: Trial | None = None
        for t in self.trials:
            v = t.metrics.get("__objective__")
            if v is None:
                continue
            if best is None or obj.better(v, best.metrics["__objective__"]):
                best = t
        return best

    def status(self, *, complete: bool = False, reason: str = "") -> ExperimentStatus:
        with self._lock:
            trials = list(self.trials)
        return ExperimentStatus(
            trials=trials,
            optimal=self.optimal_trial(),
            succeeded=sum(t.state is TrialState.SUCCEEDED for t in trials),
            failed=sum(t.state is TrialState.FAILED for t in trials),
            early_stopped=sum(t.state is TrialState.EARLY_STOPPED for t in trials),
            complete=complete,
            reason=reason,
        )


def tune(
    fn: Callable[[dict], float],
    spec: ExperimentSpec,
    *,
    seed: int = 0,
) -> ExperimentStatus:
    """KatibClient.tune() analog: one-call hyperparameter search."""
    return ExperimentController(spec, CallableTrialRunner(fn), seed=seed).run()
