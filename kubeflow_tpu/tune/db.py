"""Trial/observation persistence — the Katib DB-manager analog.

Reference analog: [katib] cmd/db-manager + pkg/db/v1beta1/ — a gRPC facade
over MySQL storing trial observation logs, which is what lets an experiment
survive controller restarts (SURVEY.md §2.3 "DB manager + storage" row;
UNVERIFIED, mount empty — §0). Here: sqlite (available in this image) with
the same two tables — trials and observation logs — and the same
restart-resume contract, exercised by
tests/test_persistence.py::test_experiment_resumes_after_controller_restart.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time

from kubeflow_tpu.tune.spec import Trial, TrialAssignment, TrialState

_SCHEMA = """
CREATE TABLE IF NOT EXISTS trials (
    experiment TEXT NOT NULL,
    trial_id   TEXT NOT NULL,
    parameters TEXT NOT NULL,
    state      TEXT NOT NULL,
    metrics    TEXT NOT NULL DEFAULT '{}',
    message    TEXT NOT NULL DEFAULT '',
    updated    REAL NOT NULL,
    PRIMARY KEY (experiment, trial_id)
);
CREATE TABLE IF NOT EXISTS observations (
    experiment TEXT NOT NULL,
    trial_id   TEXT NOT NULL,
    metric     TEXT NOT NULL,
    step       INTEGER NOT NULL,
    value      REAL NOT NULL,
    ts         REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_obs_trial
    ON observations(experiment, trial_id, metric);
"""


class TrialDB:
    """sqlite-backed trial + observation-log store."""

    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.executescript(_SCHEMA)
        self._db.commit()
        self._lock = threading.Lock()

    # -- trials --------------------------------------------------------- #

    def record_trial(self, experiment: str, trial: Trial) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO trials"
                " (experiment, trial_id, parameters, state, metrics, message,"
                "  updated) VALUES (?,?,?,?,?,?,?)",
                (
                    experiment,
                    trial.assignment.trial_id,
                    json.dumps(trial.assignment.parameters),
                    trial.state.value,
                    json.dumps(trial.metrics),
                    trial.message,
                    time.time(),
                ),
            )
            self._db.commit()

    def experiments(self) -> list[dict]:
        """Experiment rollups for the tuner UI (Katib-UI analog)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT experiment, COUNT(*),"
                " SUM(state='Succeeded'), SUM(state='Failed'),"
                " SUM(state='Running'), MAX(updated)"
                " FROM trials GROUP BY experiment ORDER BY MAX(updated) DESC"
            ).fetchall()
        return [
            {
                "name": name,
                "trials": total,
                "succeeded": ok or 0,
                "failed": failed or 0,
                "running": running or 0,
                "updated": updated,
            }
            for name, total, ok, failed, running, updated in rows
        ]

    def load_trials(self, experiment: str) -> list[Trial]:
        with self._lock:
            rows = self._db.execute(
                "SELECT trial_id, parameters, state, metrics, message"
                " FROM trials WHERE experiment=? ORDER BY updated",
                (experiment,),
            ).fetchall()
        out = []
        for tid, params, state, metrics, message in rows:
            t = Trial(
                assignment=TrialAssignment(json.loads(params), trial_id=tid),
                state=TrialState(state),
                metrics=json.loads(metrics),
                message=message,
            )
            t.observations = self.observations(experiment, tid)
            out.append(t)
        return out

    # -- observation log (ReportObservationLog analog) ------------------ #

    def report_observation(
        self, experiment: str, trial_id: str, metric: str,
        step: int, value: float,
    ) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO observations"
                " (experiment, trial_id, metric, step, value, ts)"
                " VALUES (?,?,?,?,?,?)",
                (experiment, trial_id, metric, int(step), float(value),
                 time.time()),
            )
            self._db.commit()

    def report_observations(
        self, experiment: str, trial_id: str, metric: str,
        series: list[tuple[int, float]],
    ) -> None:
        with self._lock:
            now = time.time()
            self._db.executemany(
                "INSERT INTO observations"
                " (experiment, trial_id, metric, step, value, ts)"
                " VALUES (?,?,?,?,?,?)",
                [
                    (experiment, trial_id, metric, int(s), float(v), now)
                    for s, v in series
                ],
            )
            self._db.commit()

    def replace_observations(
        self, experiment: str, trial_id: str, metric: str,
        series: list[tuple[int, float]],
    ) -> None:
        """Atomically rewrite one trial's observation log for a metric —
        the recovery path when a stored log diverges from the in-memory
        one (restart races); plain appends would record a wrong tail."""
        with self._lock:
            now = time.time()
            self._db.execute(
                "DELETE FROM observations"
                " WHERE experiment=? AND trial_id=? AND metric=?",
                (experiment, trial_id, metric),
            )
            self._db.executemany(
                "INSERT INTO observations"
                " (experiment, trial_id, metric, step, value, ts)"
                " VALUES (?,?,?,?,?,?)",
                [
                    (experiment, trial_id, metric, int(s), float(v), now)
                    for s, v in series
                ],
            )
            self._db.commit()

    def observations(
        self, experiment: str, trial_id: str, metric: str | None = None
    ) -> list[tuple[int, float]]:
        q = (
            "SELECT step, value FROM observations"
            " WHERE experiment=? AND trial_id=?"
        )
        args: list = [experiment, trial_id]
        if metric is not None:
            q += " AND metric=?"
            args.append(metric)
        q += " ORDER BY rowid"
        with self._lock:
            return [
                (int(s), float(v))
                for s, v in self._db.execute(q, args).fetchall()
            ]

    def close(self) -> None:
        with self._lock:
            self._db.close()
