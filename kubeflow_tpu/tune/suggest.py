"""Suggestion algorithms behind one interface.

Reference analog: [katib] pkg/suggestion/v1beta1/{hyperopt,optuna,skopt,
hyperband,...}/service.py behind the ``GetSuggestions`` gRPC proto
(UNVERIFIED, mount empty, SURVEY.md §0). The image has none of those
libraries (SURVEY.md §0), so the algorithms are first-party:

- ``random``    — uniform over the (log-aware) feasible space;
- ``grid``      — cartesian grid sweep;
- ``bayesian``  — GP regression (sklearn, Matérn) + expected improvement,
                  the skopt-service analog;
- ``tpe``       — Tree-structured Parzen Estimator (hyperopt-service analog);
- ``cmaes``     — (μ/μ_w, λ) CMA-ES (optuna-cmaes analog);
- ``hyperband`` — successive-halving budget scheduler.

All optimizers work in the unit cube; ``ParameterSpec`` handles the
log/int/categorical mapping.
"""

from __future__ import annotations

import itertools
import math
import random as _random
from typing import Sequence

import numpy as np

from kubeflow_tpu.tune.spec import (
    AlgorithmSpec,
    ExperimentSpec,
    ObjectiveType,
    ParameterSpec,
    TrialAssignment,
)


class Suggester:
    """GetSuggestions interface: observations in, new assignments out."""

    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        self.spec = spec
        self.params = spec.parameters
        self.rng = _random.Random(seed)

    def suggest(
        self,
        count: int,
        history: Sequence[tuple[dict, float]],  # (parameters, objective)
    ) -> list[TrialAssignment]:
        raise NotImplementedError

    # helpers ---------------------------------------------------------------

    def _random_point(self) -> dict:
        return {p.name: p.from_unit(self.rng.random()) for p in self.params}

    def _to_unit_row(self, parameters: dict) -> list[float]:
        return [p.to_unit(parameters[p.name]) for p in self.params]

    def _from_unit_row(self, row: Sequence[float]) -> dict:
        return {p.name: p.from_unit(u) for p, u in zip(self.params, row)}

    def _sign(self) -> float:
        """Internally always minimize: flip maximize objectives."""
        return 1.0 if self.spec.objective.type is ObjectiveType.MINIMIZE else -1.0


class RandomSuggester(Suggester):
    def suggest(self, count, history):
        return [TrialAssignment(self._random_point()) for _ in range(count)]


class GridSuggester(Suggester):
    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        super().__init__(spec, seed)
        n = int(spec.algorithm.settings.get("points_per_dim", 5))
        axes = [p.grid(n) for p in self.params]
        self._points = [
            dict(zip([p.name for p in self.params], combo))
            for combo in itertools.product(*axes)
        ]
        self._cursor = 0

    def suggest(self, count, history):
        out = []
        while count > 0 and self._cursor < len(self._points):
            out.append(TrialAssignment(self._points[self._cursor]))
            self._cursor += 1
            count -= 1
        return out  # exhausted grid returns fewer (controller completes)


class BayesianSuggester(Suggester):
    """GP + expected improvement over the unit cube.

    sklearn's Matérn-5/2 GP with normalized y; EI maximized by random
    multistart (cheap and dimension-robust — no scipy optimizer state).
    """

    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        super().__init__(spec, seed)
        s = spec.algorithm.settings
        self.n_initial = int(s.get("n_initial", 5))
        self.n_candidates = int(s.get("n_candidates", 1024))
        self.xi = float(s.get("xi", 0.01))

    def suggest(self, count, history):
        if len(history) < self.n_initial:
            return [TrialAssignment(self._random_point()) for _ in range(count)]

        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import ConstantKernel, Matern

        X = np.array([self._to_unit_row(p) for p, _ in history])
        y = self._sign() * np.array([v for _, v in history])
        gp = GaussianProcessRegressor(
            kernel=ConstantKernel(1.0) * Matern(nu=2.5),
            normalize_y=True,
            alpha=1e-6,
            random_state=self.rng.randrange(2**31),
        )
        import warnings

        with warnings.catch_warnings():
            # small-sample kernel-hyperparam fits hit lbfgs iteration caps;
            # an approximate fit is fine for EI ranking
            warnings.simplefilter("ignore")
            gp.fit(X, y)
        best = y.min()

        out: list[TrialAssignment] = []
        for _ in range(count):
            cand = np.array(
                [[self.rng.random() for _ in self.params]
                 for _ in range(self.n_candidates)]
            )
            mu, sigma = gp.predict(cand, return_std=True)
            sigma = np.maximum(sigma, 1e-9)
            imp = best - mu - self.xi
            z = imp / sigma
            ei = imp * _norm_cdf(z) + sigma * _norm_pdf(z)
            # penalize points already picked this batch (batch diversity)
            for a in out:
                d = np.linalg.norm(cand - np.array(self._to_unit_row(a.parameters)), axis=1)
                ei = np.where(d < 0.05, -np.inf, ei)
            out.append(TrialAssignment(self._from_unit_row(cand[int(np.argmax(ei))])))
        return out


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z):
    from scipy.special import ndtr

    return ndtr(z)


class TPESuggester(Suggester):
    """Tree-structured Parzen Estimator: model p(x|good) / p(x|bad).

    Per-dimension 1-D Parzen windows (Gaussian KDE over unit interval),
    candidates drawn from the good-KDE, ranked by likelihood ratio l(x)/g(x)
    — the hyperopt formulation.
    """

    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        super().__init__(spec, seed)
        s = spec.algorithm.settings
        self.n_initial = int(s.get("n_initial", 5))
        self.gamma = float(s.get("gamma", 0.25))
        self.n_candidates = int(s.get("n_candidates", 64))

    def suggest(self, count, history):
        if len(history) < self.n_initial:
            return [TrialAssignment(self._random_point()) for _ in range(count)]

        X = np.array([self._to_unit_row(p) for p, _ in history])
        y = self._sign() * np.array([v for _, v in history])
        order = np.argsort(y)
        n_good = max(1, int(math.ceil(self.gamma * len(y))))
        good, bad = X[order[:n_good]], X[order[n_good:]]
        if len(bad) == 0:
            bad = X

        out = []
        rng = np.random.default_rng(self.rng.randrange(2**31))
        bw = max(0.05, 1.0 / max(1, len(good)) ** 0.5)
        for _ in range(count):
            row = []
            for d in range(len(self.params)):
                centers = good[:, d]
                cands = np.clip(
                    rng.choice(centers, self.n_candidates)
                    + rng.normal(0, bw, self.n_candidates),
                    0, 1,
                )
                lg = _parzen_logpdf(cands, centers, bw)
                lb = _parzen_logpdf(cands, bad[:, d], bw)
                row.append(float(cands[int(np.argmax(lg - lb))]))
            out.append(TrialAssignment(self._from_unit_row(row)))
        return out


def _parzen_logpdf(x: np.ndarray, centers: np.ndarray, bw: float) -> np.ndarray:
    d = (x[:, None] - centers[None, :]) / bw
    log_k = -0.5 * d * d - math.log(bw * math.sqrt(2 * math.pi))
    m = log_k.max(axis=1, keepdims=True)
    return (m + np.log(np.exp(log_k - m).sum(axis=1, keepdims=True))).ravel() - math.log(
        len(centers)
    )


class CMAESSuggester(Suggester):
    """(μ/μ_w, λ) CMA-ES in the unit cube, diagonal covariance variant."""

    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        super().__init__(spec, seed)
        d = len(self.params)
        self.mean = np.full(d, 0.5)
        self.sigma = float(spec.algorithm.settings.get("sigma0", 0.3))
        self.C = np.ones(d)  # diagonal covariance
        self._seen = 0

    def suggest(self, count, history):
        rng = np.random.default_rng(self.rng.randrange(2**31))
        # update distribution from any new completed trials
        if len(history) > self._seen and len(history) >= 4:
            X = np.array([self._to_unit_row(p) for p, _ in history])
            y = self._sign() * np.array([v for _, v in history])
            mu = max(2, len(y) // 4)
            elite = X[np.argsort(y)[:mu]]
            w = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
            w = w / w.sum()
            new_mean = (w[:, None] * elite).sum(0)
            var = (w[:, None] * (elite - self.mean) ** 2).sum(0)
            self.C = 0.8 * self.C + 0.2 * var / max(self.sigma**2, 1e-12)
            self.sigma = max(0.02, 0.9 * self.sigma)
            self.mean = new_mean
            self._seen = len(history)
        pts = rng.normal(self.mean, self.sigma * np.sqrt(self.C), (count, len(self.params)))
        return [TrialAssignment(self._from_unit_row(np.clip(r, 0, 1))) for r in pts]


class HyperbandSuggester(Suggester):
    """Successive halving: suggest() also assigns a per-trial budget.

    The budget parameter (default ``epochs``) is injected into each
    assignment; the controller runs trials at that budget and halving keeps
    the top 1/eta fraction at eta× budget.
    """

    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        super().__init__(spec, seed)
        s = spec.algorithm.settings
        self.eta = int(s.get("eta", 3))
        self.min_budget = int(s.get("min_budget", 1))
        self.max_budget = int(s.get("max_budget", 27))
        self.budget_param = s.get("budget_param", "epochs")
        self._rungs: list[list[tuple[dict, float]]] = []
        self._budget = self.min_budget

    def suggest(self, count, history):
        # promote survivors when a rung completes
        completed = [(p, v) for p, v in history if p.get(self.budget_param) == self._budget]
        rung_size = max(count, 1)
        if completed and len(completed) >= rung_size and self._budget < self.max_budget:
            sign = self._sign()
            survivors = sorted(completed, key=lambda t: sign * t[1])[
                : max(1, len(completed) // self.eta)
            ]
            self._budget = min(self.max_budget, self._budget * self.eta)
            out = []
            for p, _ in survivors[:count]:
                q = dict(p)
                q[self.budget_param] = self._budget
                out.append(TrialAssignment(q))
            while len(out) < count:
                q = self._random_point()
                q[self.budget_param] = self._budget
                out.append(TrialAssignment(q))
            return out
        out = []
        for _ in range(count):
            q = self._random_point()
            q[self.budget_param] = self._budget
            out.append(TrialAssignment(q))
        return out


class PBTSuggester(Suggester):
    """Population Based Training (the Katib PBT service analog).

    Trial-based PBT: each generation's members inherit a top performer's
    weights via the ``checkpoint_param`` trial parameter (set to the parent
    trial id — the template maps it to a checkpoint path) and explore by
    perturbing the parent's hyperparameters (numeric ×{0.8,1.2} in unit
    space, categoricals resampled with ``resample_prob``). Needs trial
    identities, so it implements ``suggest_trials``.
    """

    def __init__(self, spec: ExperimentSpec, seed: int = 0):
        super().__init__(spec, seed)
        s = spec.algorithm.settings
        self.population = int(s.get("population", spec.parallel_trial_count))
        self.quantile = float(s.get("quantile", 0.25))
        self.perturb_factors = tuple(s.get("perturb_factors", (0.8, 1.2)))
        self.resample_prob = float(s.get("resample_prob", 0.25))
        self.checkpoint_param = s.get("checkpoint_param", "parent_trial")

    def suggest_trials(self, count: int, trials) -> list[TrialAssignment]:
        from kubeflow_tpu.tune.spec import TrialState

        done = [
            t
            for t in trials
            if t.state is TrialState.SUCCEEDED and t.objective_value is not None
        ]
        out = []
        if len(done) < self.population:
            for _ in range(count):
                q = self._random_point()
                q[self.checkpoint_param] = ""  # fresh member, no parent
                out.append(TrialAssignment(q))
            return out
        sign = self._sign()
        ranked = sorted(done, key=lambda t: sign * t.objective_value)
        k = max(1, int(len(ranked) * self.quantile))
        top = ranked[:k]
        for _ in range(count):
            parent = self.rng.choice(top)
            q = self._exploit_explore(parent.assignment.parameters)
            q[self.checkpoint_param] = parent.assignment.trial_id
            out.append(TrialAssignment(q))
        return out

    def _exploit_explore(self, params: dict) -> dict:
        q = {}
        for p in self.params:
            v = params.get(p.name)
            if v is None or self.rng.random() < self.resample_prob:
                q[p.name] = p.from_unit(self.rng.random())
                continue
            if p.type.value in ("double", "int"):
                # Standard PBT perturbs the parameter VALUE, not its unit
                # coordinate — a unit-space multiply pins values at the
                # lower bound (0 × factor = 0) forever. A value of exactly
                # 0 can't move multiplicatively either, so nudge it in unit
                # space instead.
                factor = self.rng.choice(self.perturb_factors)
                if float(v) != 0.0:
                    u = p.to_unit(float(v) * factor)
                else:
                    u = p.to_unit(v) + (factor - 1.0)
                q[p.name] = p.from_unit(min(1.0, max(0.0, u)))
            else:
                q[p.name] = v
        return q

    def suggest(self, count, history):
        # history-only callers (no lineage): degrade to perturbed top points
        if not history:
            return [TrialAssignment(self._random_point()) for _ in range(count)]
        sign = self._sign()
        ranked = sorted(history, key=lambda t: sign * t[1])
        k = max(1, int(len(ranked) * self.quantile))
        return [
            TrialAssignment(
                self._exploit_explore(dict(self.rng.choice(ranked[:k])[0]))
            )
            for _ in range(count)
        ]


_REGISTRY = {
    "random": RandomSuggester,
    "grid": GridSuggester,
    "bayesian": BayesianSuggester,
    "skopt": BayesianSuggester,  # Katib algorithm-name alias
    "tpe": TPESuggester,
    "hyperopt": TPESuggester,  # alias
    "cmaes": CMAESSuggester,
    "hyperband": HyperbandSuggester,
    "pbt": PBTSuggester,
}


def make_suggester(spec: ExperimentSpec, seed: int = 0) -> Suggester:
    if spec.algorithm.name in ("darts", "enas"):
        # NAS is not a parameter suggester here: TPU-natively the whole
        # search is ONE differentiable SPMD program (no controller/service
        # split) — use kubeflow_tpu.tune.nas.DARTSSearcher in the trial.
        raise ValueError(
            f"algorithm '{spec.algorithm.name}' runs in-process: use "
            "kubeflow_tpu.tune.nas (DARTSSearcher) instead of a suggester"
        )
    try:
        cls = _REGISTRY[spec.algorithm.name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm '{spec.algorithm.name}' "
            f"(have: {sorted(_REGISTRY)})"
        ) from None
    return cls(spec, seed)
