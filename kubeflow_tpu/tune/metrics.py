"""Metrics collectors: parse training output without any SDK in user code.

Reference analog: Katib's metrics-collector sidecar ([katib]
pkg/metricscollector/v1beta1/{file-metricscollector,tfevent-metricscollector}
— UNVERIFIED, mount empty, SURVEY.md §0), injected by webhook, which tails
trial stdout with configurable regex formats or reads TFEvents files and
reports observations over gRPC. SURVEY.md §5.5 calls this "the clever bit":
user code needs zero SDK — it just prints ``metric=value``.

Our trainer's metric writer (train/metrics.py) emits exactly this format,
so trials of our own jobs scrape identically to arbitrary user scripts.
"""

from __future__ import annotations

import os
import re
from typing import Iterable

# Katib's default file-metrics format: "<name>=<float>" tokens anywhere in a
# line, e.g. "epoch 3: loss=0.42 accuracy=0.91". Also accepts "name: value".
_METRIC_RE = re.compile(
    r"([\w.|-]+)\s*[=:]\s*([+-]?\d+(?:\.\d+)?(?:[Ee][+-]?\d+)?)"
)
_STEP_KEYS = ("step", "epoch", "iteration")


def parse_lines(
    lines: Iterable[str], metric_names: set[str] | None = None
) -> list[tuple[int, str, float]]:
    """Extract (step, metric, value) observations from output lines.

    A step counter found on the same line tags the observation; otherwise
    steps are the running count of lines that produced observations.
    """
    out: list[tuple[int, str, float]] = []
    auto_step = 0
    for line in lines:
        pairs = _METRIC_RE.findall(line)
        if not pairs:
            continue
        found = {k.lower(): float(v) for k, v in pairs}
        step = None
        for sk in _STEP_KEYS:
            if sk in found:
                step = int(found[sk])
                break
        if step is None:
            step = auto_step
        got_any = False
        for name, value in found.items():
            if name in _STEP_KEYS:
                continue
            if metric_names is not None and name not in metric_names:
                continue
            out.append((step, name, value))
            got_any = True
        if got_any:
            auto_step += 1
    return out


def collect_from_text(
    text: str, objective_metric: str, additional: Iterable[str] = ()
) -> dict[str, list[tuple[int, float]]]:
    """Scrape a log blob into per-metric observation series."""
    names = {objective_metric.lower(), *[a.lower() for a in additional]}
    series: dict[str, list[tuple[int, float]]] = {n: [] for n in names}
    for step, name, value in parse_lines(text.splitlines(), names):
        series[name].append((step, value))
    return series


def collect_from_tfevents(
    logdir: str, objective_metric: str, additional: Iterable[str] = ()
) -> dict[str, list[tuple[int, float]]]:
    """TFEvents collector: read scalar series from TensorBoard event files."""
    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator,
    )

    names = {objective_metric, *additional}
    series: dict[str, list[tuple[int, float]]] = {n: [] for n in names}
    for root, _, files in os.walk(logdir):
        if not any(f.startswith("events.out.tfevents") for f in files):
            continue
        acc = EventAccumulator(root)
        acc.Reload()
        for tag in acc.Tags().get("scalars", []):
            if tag in names:
                for ev in acc.Scalars(tag):
                    series[tag].append((ev.step, ev.value))
    for k in series:
        series[k].sort()
    return series


def latest(series: list[tuple[int, float]]) -> float | None:
    return series[-1][1] if series else None


def best(series: list[tuple[int, float]], minimize: bool) -> float | None:
    if not series:
        return None
    vals = [v for _, v in series]
    return min(vals) if minimize else max(vals)
