"""Early stopping: median-stop rule.

Reference analog: [katib] pkg/earlystopping/v1beta1/medianstop/ (UNVERIFIED,
mount empty, SURVEY.md §0): a running trial is stopped when its best
objective so far is worse than the median of completed trials' objectives at
the same step.
"""

from __future__ import annotations

import statistics

from kubeflow_tpu.tune.spec import (
    EarlyStoppingSpec,
    Objective,
    ObjectiveType,
    Trial,
    TrialState,
)


class MedianStop:
    def __init__(self, spec: EarlyStoppingSpec, objective: Objective):
        self.spec = spec
        self.objective = objective

    def should_stop(self, trial: Trial, completed: list[Trial]) -> bool:
        done = [t for t in completed if t.state is TrialState.SUCCEEDED]
        if len(done) < self.spec.min_trials_required or not trial.observations:
            return False
        step = trial.observations[-1][0]
        if step < self.spec.start_step:
            return False
        minimize = self.objective.type is ObjectiveType.MINIMIZE

        def best_up_to(t: Trial) -> float | None:
            vals = [v for s, v in t.observations if s <= step]
            if not vals:
                return None
            return min(vals) if minimize else max(vals)

        peers = [v for v in (best_up_to(t) for t in done) if v is not None]
        if len(peers) < self.spec.min_trials_required:
            return False
        med = statistics.median(peers)
        mine = best_up_to(trial)
        return mine is not None and self.objective.better(med, mine)


def make_early_stopper(spec: EarlyStoppingSpec | None, objective: Objective):
    if spec is None or spec.name == "none":
        return None
    if spec.name == "medianstop":
        return MedianStop(spec, objective)
    raise ValueError(f"unknown early-stopping rule '{spec.name}'")
