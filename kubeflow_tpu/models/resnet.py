"""ResNet-50 (v1.5) — BASELINE config 2 (TFJob ResNet-50 CIFAR-10 analog).

TPU-first choices: NHWC layout (XLA:TPU's native conv layout), GroupNorm
instead of BatchNorm — stateless, so the SPMD train step needs no
cross-replica stat sync and no mutable collections (the
MultiWorkerMirrored BN-sync machinery of the reference config dissolves);
channel counts are MXU-tile multiples.

Reference analog (UNVERIFIED upstream layout, SURVEY.md §0):
[training-operator] examples/tensorflow/distribution_strategy — the model
lived in the user container; first-party here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    num_filters: int = 64
    num_classes: int = 10
    cifar_stem: bool = True   # 3x3/1 stem for 32x32 inputs (vs 7x7/2)
    groups: int = 32          # GroupNorm groups
    dtype: Any = jnp.float32


def resnet50_cifar(**kw) -> ResNetConfig:
    return ResNetConfig(**kw)


def resnet18_cifar(**kw) -> ResNetConfig:
    base = dict(stage_sizes=(2, 2, 2, 2))
    base.update(kw)
    return ResNetConfig(**base)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        norm = lambda name: nn.GroupNorm(
            num_groups=min(cfg.groups, self.filters), name=name
        )
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=cfg.dtype, name="conv1")(x)
        y = nn.relu(norm("gn1")(y))
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                    use_bias=False, dtype=cfg.dtype, name="conv2")(y)
        y = nn.relu(norm("gn2")(y))
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=cfg.dtype, name="conv3")(y)
        y = nn.GroupNorm(
            num_groups=min(cfg.groups, self.filters * 4), name="gn3"
        )(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), strides=(self.strides,) * 2,
                use_bias=False, dtype=cfg.dtype, name="proj",
            )(residual)
            residual = nn.GroupNorm(
                num_groups=min(cfg.groups, self.filters * 4), name="gn_proj"
            )(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        if cfg.cifar_stem:
            x = nn.Conv(cfg.num_filters, (3, 3), use_bias=False,
                        dtype=cfg.dtype, name="stem")(x)
        else:
            x = nn.Conv(cfg.num_filters, (7, 7), strides=(2, 2),
                        use_bias=False, dtype=cfg.dtype, name="stem")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(nn.GroupNorm(num_groups=cfg.groups, name="gn_stem")(x))

        for stage, num_blocks in enumerate(cfg.stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters=cfg.num_filters * 2**stage,
                    strides=strides,
                    cfg=cfg,
                    name=f"stage{stage}_block{block}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


def make_loss_fn(model: ResNet):
    def loss_fn(params, batch, rng):
        del rng
        images, labels = batch
        logits = model.apply({"params": params}, images)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, {"accuracy": acc}

    return loss_fn


def make_init_fn(model: ResNet, image_shape=(32, 32, 3)):
    def init_params(rng):
        return model.init(rng, jnp.zeros((1, *image_shape), jnp.float32))[
            "params"
        ]

    return init_params
