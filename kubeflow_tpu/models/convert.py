"""HF checkpoint interop: torch BERT state_dicts → this framework's params.

The reference serves ``bert-base-uncased`` through its HuggingFace runtime
(SURVEY.md §2.2, BASELINE config 5); a reference user migrating here brings
torch checkpoints. This module converts an HF ``BertModel`` /
``BertFor*`` state_dict into ``models.bert.BertEncoder`` params with
numerical agreement (same weights ⇒ same outputs), so serving and
fine-tuning continue from the exact same model. Conversion is pure
numpy — torch is only needed to ``torch.load`` a ``.bin`` file.

Name mapping (HF → ours):

    embeddings.word_embeddings.weight        embed.embedding
    embeddings.position_embeddings.weight    pos_embedding
    embeddings.token_type_embeddings.weight  type_embed.embedding
    embeddings.LayerNorm.{weight,bias}       ln_embed.{scale,bias}
    encoder.layer.N.attention.self.query     layers_N.attn.q_proj   (kernel^T)
    …key/value                               …k_proj/v_proj
    encoder.layer.N.attention.output.dense   layers_N.attn.o_proj
    encoder.layer.N.attention.output.LayerNorm  layers_N.ln1
    encoder.layer.N.intermediate.dense       layers_N.up_proj
    encoder.layer.N.output.dense             layers_N.down_proj
    encoder.layer.N.output.LayerNorm         layers_N.ln2
    pooler.dense                             pooler
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Mapping

import numpy as np

from kubeflow_tpu.models.bert import BertConfig


def bert_config_from_hf(hf: Mapping[str, Any], **overrides) -> BertConfig:
    """HF ``config.json`` dict → BertConfig."""
    base = dict(
        vocab_size=hf.get("vocab_size", 30522),
        hidden_size=hf.get("hidden_size", 768),
        num_layers=hf.get("num_hidden_layers", 12),
        num_heads=hf.get("num_attention_heads", 12),
        intermediate_size=hf.get("intermediate_size", 3072),
        max_position=hf.get("max_position_embeddings", 512),
        type_vocab_size=hf.get("type_vocab_size", 2),
        layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
    )
    base.update(overrides)
    return BertConfig(**base)


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor without importing torch
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _dense(state, hf_name):
    return {
        "kernel": _np(state[f"{hf_name}.weight"]).T,  # torch [out,in] → [in,out]
        "bias": _np(state[f"{hf_name}.bias"]),
    }


def _layernorm(state, hf_name):
    return {
        "scale": _np(state[f"{hf_name}.weight"]),
        "bias": _np(state[f"{hf_name}.bias"]),
    }


def hf_bert_state_to_params(
    state: Mapping[str, Any], cfg: BertConfig
) -> dict:
    """HF BertModel state_dict → ``BertEncoder`` params pytree.

    Accepts bare ``BertModel`` keys or ``bert.``-prefixed ones (as found
    inside ``BertForSequenceClassification``/``BertForMaskedLM`` dicts).
    """
    if any(k.startswith("bert.") for k in state):
        state = {
            k[len("bert."):]: v for k, v in state.items() if k.startswith("bert.")
        }

    params: dict[str, Any] = {
        "embed": {
            "embedding": _np(state["embeddings.word_embeddings.weight"])
        },
        "pos_embedding": _np(state["embeddings.position_embeddings.weight"]),
        "type_embed": {
            "embedding": _np(state["embeddings.token_type_embeddings.weight"])
        },
        "ln_embed": _layernorm(state, "embeddings.LayerNorm"),
    }
    for i in range(cfg.num_layers):
        p = f"encoder.layer.{i}"
        params[f"layers_{i}"] = {
            "attn": {
                "q_proj": _dense(state, f"{p}.attention.self.query"),
                "k_proj": _dense(state, f"{p}.attention.self.key"),
                "v_proj": _dense(state, f"{p}.attention.self.value"),
                "o_proj": _dense(state, f"{p}.attention.output.dense"),
            },
            "ln1": _layernorm(state, f"{p}.attention.output.LayerNorm"),
            "up_proj": _dense(state, f"{p}.intermediate.dense"),
            "down_proj": _dense(state, f"{p}.output.dense"),
            "ln2": _layernorm(state, f"{p}.output.LayerNorm"),
        }
    if "pooler.dense.weight" in state:
        params["pooler"] = _dense(state, "pooler.dense")
    return params


def hf_bert_mlm_to_params(state: Mapping[str, Any], cfg: BertConfig) -> dict:
    """HF ``BertForMaskedLM`` state_dict → ``models.bert.BertForMaskedLM``
    params (encoder nested under ``encoder``, plus the prediction head when
    present: ``cls.predictions.transform`` → mlm_transform/mlm_ln, the
    decoder (tied to word embeddings in HF) → unembed)."""
    params: dict[str, Any] = {"encoder": hf_bert_state_to_params(state, cfg)}
    if "cls.predictions.transform.dense.weight" in state:
        params["mlm_transform"] = _dense(state, "cls.predictions.transform.dense")
        params["mlm_ln"] = _layernorm(
            state, "cls.predictions.transform.LayerNorm"
        )
        bias_key = (
            "cls.predictions.decoder.bias"
            if "cls.predictions.decoder.bias" in state
            else "cls.predictions.bias"
        )
        params["unembed"] = {
            "kernel": _np(state["cls.predictions.decoder.weight"]).T,
            "bias": _np(state[bias_key]),
        }
    return params


def load_bert_dir(model_dir: str | Path, **cfg_overrides):
    """Load an HF-format model directory (``config.json`` +
    ``pytorch_model.bin``) → (BertConfig, encoder params). The directory is
    what the storage initializer materializes from a ``storage_uri``."""
    model_dir = Path(model_dir)
    cfg_path = model_dir / "config.json"
    if not cfg_path.exists():
        raise FileNotFoundError(f"no config.json under {model_dir}")
    cfg = bert_config_from_hf(json.loads(cfg_path.read_text()), **cfg_overrides)

    weights = model_dir / "pytorch_model.bin"
    if not weights.exists():
        raise FileNotFoundError(
            f"no pytorch_model.bin under {model_dir} "
            "(safetensors support: convert externally for now)"
        )
    import torch

    state = torch.load(str(weights), map_location="cpu", weights_only=True)
    return cfg, hf_bert_state_to_params(state, cfg)


def is_hf_bert_dir(model_dir: str | Path | None) -> bool:
    """True when the directory holds an HF-format BERT checkpoint (the
    layout the storage initializer materializes from a storage_uri)."""
    if not model_dir:
        return False
    p = Path(model_dir)
    return (p / "config.json").exists() and (p / "pytorch_model.bin").exists()


def load_bert_mlm_dir(model_dir: str | Path, **cfg_overrides):
    """Like ``load_bert_dir`` but shaped for ``BertForMaskedLM`` — head
    pieces are included when the checkpoint carries them (missing pieces
    are left to the caller to initialize)."""
    model_dir = Path(model_dir)
    cfg = bert_config_from_hf(
        json.loads((model_dir / "config.json").read_text()), **cfg_overrides
    )
    import torch

    state = torch.load(
        str(model_dir / "pytorch_model.bin"), map_location="cpu",
        weights_only=True,
    )
    return cfg, hf_bert_mlm_to_params(state, cfg)
