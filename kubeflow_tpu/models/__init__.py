"""Model zoo (flax linen), one family per reference benchmark config."""

from kubeflow_tpu.models.mnist_cnn import MnistCNN  # noqa: F401
