"""BERT encoder — BASELINE configs 3 (MPIJob Horovod BERT allreduce) and 5
(KServe bert-base-uncased predictor).

Faithful bert-base structure (learned positions + token-type embeddings,
post-LN blocks, GELU intermediate, pooler over [CLS]) expressed with this
framework's parallel-native pieces: attention routes through
``models.transformer.dispatch_attention`` (flash/TP/SP capable), padding is
handled with the segment-id trick (pad tokens get segment 0, valid tokens
segment 1+type), and param names match ``parallel.sharding.transformer_rules``
so FSDP/TP layouts apply unchanged.

Reference analog (UNVERIFIED upstream layout, SURVEY.md §0): [kserve]
python/huggingfaceserver (serves HF BERT on torch); the model itself was
never first-party in the reference — here it is, so the serving and
allreduce benchmarks are self-contained in a zero-egress environment.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.transformer import TransformerConfig, dispatch_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32
    attn_impl: str = "flash"
    interpret_kernels: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def attention_cfg(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=self.vocab_size,
            d_model=self.hidden_size,
            n_heads=self.num_heads,
            d_ff=self.intermediate_size,
            causal=False,
            use_rope=False,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            interpret_kernels=self.interpret_kernels,
        )


def bert_base(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def bert_tiny(**overrides) -> BertConfig:
    """4-layer test-size config (fast CI / CPU sim)."""
    base = dict(
        hidden_size=128, num_layers=4, num_heads=8, intermediate_size=256,
        vocab_size=1024,
    )
    base.update(overrides)
    return BertConfig(**base)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        cfg = self.cfg
        B, S, _ = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        dense = lambda name: nn.Dense(H * D, dtype=cfg.dtype, name=name)
        q = dense("q_proj")(x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = dense("k_proj")(x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        v = dense("v_proj")(x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        # padding via segments: pad→0, valid→1 (pads attend only to pads,
        # and their outputs are dropped downstream)
        seg = attention_mask.astype(jnp.int32)
        o = dispatch_attention(q, k, v, cfg.attention_cfg(), segment_ids=seg)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        return nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="o_proj")(o)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        cfg = self.cfg
        # post-LN, as in the original
        h = BertSelfAttention(cfg, name="attn")(x, attention_mask)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln1")(x + h)
        y = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="up_proj")(x)
        # exact (erf) GELU as in the original BERT — the tanh approximation
        # breaks bit-parity with converted HF checkpoints
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="down_proj")(y)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln2")(x + y)


class BertEncoder(nn.Module):
    """Returns (sequence_output (B,S,H), pooled_output (B,H))."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), jnp.int32)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((B, S), jnp.int32)

        embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="embed"
        )(input_ids)
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (cfg.max_position, cfg.hidden_size),
        )
        types = nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size,
            dtype=cfg.dtype, name="type_embed",
        )(token_type_ids)
        x = embed + pos[None, :S].astype(cfg.dtype) + types
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_embed")(x)

        for i in range(cfg.num_layers):
            x = BertLayer(cfg, name=f"layers_{i}")(x, attention_mask)

        pooled = nn.tanh(
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="pooler")(x[:, 0])
        )
        return x, pooled


class BertForMaskedLM(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        seq, _ = BertEncoder(self.cfg, name="encoder")(
            input_ids, attention_mask, token_type_ids
        )
        h = nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype, name="mlm_transform")(seq)
        h = nn.gelu(h, approximate=False)
        h = nn.LayerNorm(epsilon=self.cfg.layer_norm_eps, name="mlm_ln")(h)
        return nn.Dense(
            self.cfg.vocab_size, use_bias=True, dtype=jnp.float32, name="unembed"
        )(h)


class BertForSequenceClassification(nn.Module):
    cfg: BertConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        _, pooled = BertEncoder(self.cfg, name="encoder")(
            input_ids, attention_mask, token_type_ids
        )
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(
            pooled
        )


# --------------------------------------------------------------------------- #
# Trainer plumbing (BASELINE config 3: the Horovod-allreduce analog)
# --------------------------------------------------------------------------- #

MASK_TOKEN = 3  # conventionally [MASK]; synthetic data just needs an id


def make_mlm_loss_fn(model: BertForMaskedLM, mask_rate: float = 0.15):
    """(params, {"inputs"}, rng) → (loss, metrics): random-mask MLM."""
    import optax

    def loss_fn(params, batch, rng):
        tokens = batch["inputs"]
        mask = jax.random.bernoulli(rng, mask_rate, tokens.shape)
        corrupted = jnp.where(mask, MASK_TOKEN, tokens)
        logits = model.apply({"params": params}, corrupted)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, tokens)
        denom = jnp.maximum(mask.sum(), 1)
        loss = jnp.where(mask, per_tok, 0.0).sum() / denom
        acc = jnp.where(
            mask, jnp.argmax(logits, -1) == tokens, False
        ).sum() / denom
        return loss, {"masked_accuracy": acc}

    return loss_fn


def make_mlm_init_fn(model: BertForMaskedLM, seq_len: int, batch_size: int = 1):
    def init_params(rng):
        return model.init(rng, jnp.zeros((batch_size, seq_len), jnp.int32))[
            "params"
        ]

    return init_params
