"""Flagship Transformer (decoder LM / bidirectional encoder), parallel-native.

The model family behind BASELINE configs 3 and 5 (BERT-base allreduce
training; transformer serving) and the long-context story (SURVEY.md §5.7).
Design choices are TPU-first:

- bf16 compute / f32 params+softmax; matmul shapes padded to MXU-friendly
  multiples by configuration, not runtime checks;
- attention strategy per config: ``reference`` (XLA oracle), ``flash``
  (Pallas kernel), ``ring`` (context parallel over ``seq``), ``ulysses``
  (all_to_all SP) — the last two run in shard_map over the live mesh;
- activations carry sharding constraints (batch over data/fsdp, seq over
  seq) so pjit propagates layouts instead of guessing;
- optional MoE FFN every Nth layer (expert axis, ``parallel.expert``);
- param names line up with ``parallel.sharding.transformer_rules`` so
  FSDP/TP layouts are one function call.

Reference analog (UNVERIFIED upstream layout, SURVEY.md §0): the models live
in user containers (HF ``transformers`` BERT for KServe's huggingfaceserver,
Megatron-style layouts via MPIJob) — the platform never owned them; here the
model zoo is first-party so every parallel strategy is testable end-to-end.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.core.collectives import shard_map

from kubeflow_tpu.core.mesh import Axis, current_mesh
from kubeflow_tpu.ops.flash_attention import flash_attention, reference_attention
from kubeflow_tpu.ops.paged_attention import (
    dequantize_kv,
    paged_attention,
    quantize_kv,
)
from kubeflow_tpu.parallel.expert import MoEConfig, moe_ffn
from kubeflow_tpu.parallel.ring_attention import ring_attention_local
from kubeflow_tpu.parallel.ulysses import ulysses_attention_local

ATTN_IMPLS = ("reference", "flash", "ring", "ulysses")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 512
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    #: GQA: number of key/value heads (None = n_heads, i.e. plain MHA).
    #: Shrinks the KV cache — the serving memory bill — by n_heads/kv.
    n_kv_heads: int | None = None
    d_ff: int = 1024
    max_seq_len: int = 2048
    causal: bool = True              # False → bidirectional encoder (BERT)
    use_rope: bool = True            # False → learned positions (BERT)
    dtype: Any = jnp.float32         # activation/compute dtype (bf16 on TPU)
    attn_impl: str = "flash"
    #: sliding-window attention (requires causal; flash/reference impls):
    #: each position attends to the previous ``attn_window`` tokens only
    attn_window: int | None = None
    #: None → per-shape selection (ops/flash_tuning.py: measured table
    #: when a sweep has run on hardware, heuristic otherwise)
    attn_block_q: int | None = None
    attn_block_k: int | None = None
    interpret_kernels: bool = False  # Pallas interpret mode (CPU tests)
    remat: bool = False
    #: rematerialization policy when remat=True (the HBM-vs-FLOPs MFU
    #: lever): None = full remat (recompute everything — max memory
    #: saving, most recompute); "dots" = save matmul outputs, recompute
    #: only the cheap elementwise/softmax work (jax
    #: dots_with_no_batch_dims_saveable — usually the throughput sweet
    #: spot on TPU: MXU results are kept, VPU work is replayed).
    remat_policy: str | None = None
    moe_every: int = 0               # every Nth layer uses MoE FFN (0 = never)
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    dropout_rate: float = 0.0
    # "gather" = table lookup (best single-chip/serving). "onehot" = one-hot
    # matmul — the SPMD-clean form when the table is sharded P(model, fsdp):
    # a sharded-vocab gather forces the partitioner into involuntary full
    # rematerialization (replicate-then-reshard), while the one-hot
    # contraction over vocab partitions into a plain psum over the model
    # axis and rides the MXU.
    embed_impl: str = "gather"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        # explicit None check: `or` would silently turn an invalid 0 into
        # full MHA instead of letting validate() reject it
        return self.n_heads if self.n_kv_heads is None else self.n_kv_heads

    def validate(self) -> None:
        if self.attn_impl not in ATTN_IMPLS:
            raise ValueError(
                f"attn_impl {self.attn_impl!r} not in {ATTN_IMPLS}"
            )
        if self.attn_window is not None:
            if self.attn_window < 1:
                raise ValueError(
                    f"attn_window must be >= 1, got {self.attn_window}"
                )
            if not self.causal:
                raise ValueError("attn_window requires causal=True")
            if self.attn_impl not in ("flash", "reference"):
                raise ValueError(
                    "attn_window supports attn_impl 'flash'/'reference' "
                    f"(got {self.attn_impl!r}); window + context parallelism "
                    "is not implemented"
                )
        if self.remat_policy not in (None, "dots"):
            raise ValueError(
                f"remat_policy {self.remat_policy!r} not in (None, 'dots')"
            )
        if self.remat_policy is not None and not self.remat:
            # an inert policy field would read as "remat enabled"
            raise ValueError("remat_policy requires remat=True")
        if self.n_kv_heads is not None and self.n_kv_heads < 1:
            raise ValueError(f"n_kv_heads must be >= 1, got {self.n_kv_heads}")
        if self.n_heads % self.kv_heads:
            raise ValueError(
                f"n_heads {self.n_heads} must be a multiple of n_kv_heads "
                f"{self.kv_heads}"
            )
        if self.attn_impl == "ring" and not self.use_rope and self.causal:
            pass  # fine; just unusual


# --------------------------------------------------------------------------- #
# building blocks
# --------------------------------------------------------------------------- #

def _act_constraint(x: jax.Array, *, seq_dim: int = 1) -> jax.Array:
    """(batch, seq, d) activations: batch over data+fsdp, seq over seq."""
    mesh = current_mesh()
    if mesh.empty or Axis.DATA not in mesh.axis_names:
        return x
    spec = [None] * x.ndim
    spec[0] = (Axis.DATA, Axis.FSDP)
    spec[seq_dim] = Axis.SEQ
    return jax.lax.with_sharding_constraint(x, P(*spec))


class Embedding(nn.Module):
    """Token embedding with a choice of lookup implementation.

    Param path matches ``nn.Embed`` ("embedding", same default init), so
    checkpoints and sharding rules are interchangeable. ``impl="onehot"``
    trades a gather for an MXU one-hot contraction — required for clean
    SPMD partitioning when the table is sharded P(model, fsdp); see
    ``TransformerConfig.embed_impl``.
    """

    vocab_size: int
    features: int
    dtype: Any = jnp.float32
    impl: str = "gather"

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        table = self.param(
            "embedding",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal", out_axis=0),
            (self.vocab_size, self.features),
        )
        if self.impl == "onehot":
            oh = jax.nn.one_hot(tokens, self.vocab_size, dtype=self.dtype)
            return oh @ table.astype(self.dtype)
        return jnp.take(table, tokens, axis=0).astype(self.dtype)


def rope(x: jax.Array, positions: jax.Array, *, base: float = 10_000.0) -> jax.Array:
    """Rotary embeddings; x: (B, H, S, D), positions: (B, S)."""
    d = x.shape[-1]
    half = d // 2
    freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None, :, None].astype(jnp.float32) * freq  # (B,1,S,half)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + self.eps)
        return (y * scale).astype(x.dtype)


def _grouped_cache_attention(q, K, V, mask, groups):
    """Cache-side attention in grouped (GQA) form: q (B, H, S, D) against
    an Hkv-head cache view K/V (B, Hkv, T, D) with mask (B, S, T). q is
    reshaped (B, Hkv, g, S, D) so the repeated n_heads view of the whole
    cache is never materialized (it would be a 2x-of-the-cache transient
    on EVERY decode step)."""
    B, H, S, D = q.shape
    Hkv = K.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.float32(D))
    qg = q.reshape(B, Hkv, groups, S, D)
    scores = (
        jnp.einsum(
            "bhgsd,bhtd->bhgst",
            qg.astype(jnp.float32),
            K.astype(jnp.float32),
        )
        * scale
    )
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(V.dtype)
    o = jnp.einsum("bhgst,bhtd->bhgsd", probs, V)
    return o.reshape(B, H, S, D)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x,
        positions,
        segment_ids=None,
        layer_cache=None,
        cache_index=None,
        kv_mask=None,
        page_table=None,
        page_size=None,
        page_write_ok=None,
        paged_attn_impl="gather",
        kv_quant="none",
    ):
        cfg = self.cfg
        B, S, _ = x.shape
        H, D = cfg.n_heads, cfg.head_dim
        Hkv = cfg.kv_heads
        groups = H // Hkv
        dense = lambda name, nh: nn.Dense(
            nh * D, use_bias=False, dtype=cfg.dtype, name=name
        )
        q = dense("q_proj", H)(x).reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = dense("k_proj", Hkv)(x).reshape(B, S, Hkv, D).transpose(0, 2, 1, 3)
        v = dense("v_proj", Hkv)(x).reshape(B, S, Hkv, D).transpose(0, 2, 1, 3)
        if cfg.use_rope:
            q, k = rope(q, positions), rope(k, positions)
        # GQA: the CACHE and projections hold Hkv heads (the memory bill);
        # attention itself sees the repeated view
        expand = (
            (lambda t: jnp.repeat(t, groups, axis=1)) if groups > 1
            else (lambda t: t)
        )

        new_cache = None
        if page_table is not None:
            # PAGED decode/prefill (serve/paging.py): the cache is one flat
            # token axis per layer — (Hkv, pool_tokens, D) — and row b's
            # logical token j lives at table[b, j//P]*P + j%P. Because a
            # row's token space is CONTIGUOUS (no quantized gen gap), the
            # causal + sliding-window mask is just arithmetic on positions;
            # no kv_mask operand exists in this mode.
            P = page_size
            n_pages_w = page_table.shape[1]
            W = n_pages_w * P
            # scatter this call's keys/values into the pool. Pad positions
            # and dead rows route to the scratch page (0) via page_write_ok.
            wpage = jnp.take_along_axis(page_table, positions // P, axis=1)
            flat_w = wpage * P + positions % P                    # (B, S)
            if page_write_ok is not None:
                # scratch slots: distinct per (b,s) within the page where
                # possible, but collisions are harmless — never read
                scratch = (
                    jnp.arange(B * S, dtype=flat_w.dtype).reshape(B, S) % P
                )
                flat_w = jnp.where(page_write_ok, flat_w, scratch)
            idx = flat_w.reshape(-1)
            if kv_quant == "int8":
                # quantize-on-write: per-token-per-head symmetric int8
                # codes + f32 scales ride the same scatter indices (see
                # ops/paged_attention.py for why NOT per-page scales)
                kq, ks = quantize_kv(k)                # codes (B,Hkv,S,D)
                vq, vs = quantize_kv(v)                # scales (B,Hkv,S)
                K = layer_cache["k"].at[:, idx, :].set(
                    kq.transpose(1, 0, 2, 3).reshape(Hkv, B * S, D)
                )
                V = layer_cache["v"].at[:, idx, :].set(
                    vq.transpose(1, 0, 2, 3).reshape(Hkv, B * S, D)
                )
                Ks = layer_cache["k_scale"].at[:, idx].set(
                    ks.transpose(1, 0, 2).reshape(Hkv, B * S)
                )
                Vs = layer_cache["v_scale"].at[:, idx].set(
                    vs.transpose(1, 0, 2).reshape(Hkv, B * S)
                )
                new_cache = {"k": K, "v": V, "k_scale": Ks, "v_scale": Vs}
                # quantization-error telemetry: a no-op (XLA-dead) unless
                # the caller requests mutable=["quant_stats"] — the engine
                # does so only in its suffix-prefill program
                kf, vf = k.astype(jnp.float32), v.astype(jnp.float32)
                err = (
                    jnp.sum(jnp.abs(dequantize_kv(kq, ks) - kf))
                    + jnp.sum(jnp.abs(dequantize_kv(vq, vs) - vf))
                )
                den = jnp.sum(jnp.abs(kf)) + jnp.sum(jnp.abs(vf))
                self.sow("quant_stats", "kv_quant_err", jnp.stack([err, den]))
            elif kv_quant == "none":
                K = layer_cache["k"].at[:, idx, :].set(
                    k.astype(layer_cache["k"].dtype)
                    .transpose(1, 0, 2, 3).reshape(Hkv, B * S, D)
                )
                V = layer_cache["v"].at[:, idx, :].set(
                    v.astype(layer_cache["v"].dtype)
                    .transpose(1, 0, 2, 3).reshape(Hkv, B * S, D)
                )
                new_cache = {"k": K, "v": V}
            else:
                raise ValueError(f"unknown kv_quant {kv_quant!r}")
            if paged_attn_impl == "kernel":
                # Pallas kernel read: the block table rides the grid as a
                # scalar-prefetch operand and the pallas_call pipeline
                # stages pages HBM→VMEM. Assumes contiguous span
                # positions (positions[b] == positions[b, 0] + arange(S)),
                # which holds for every engine caller — decode steps, the
                # speculative verify span, and chunked-prefill pieces.
                o = paged_attention(
                    q,
                    new_cache["k"],
                    new_cache["v"],
                    page_table,
                    positions[:, 0],
                    page_size=P,
                    window=cfg.attn_window,
                    k_scale=new_cache.get("k_scale"),
                    v_scale=new_cache.get("v_scale"),
                    interpret=cfg.interpret_kernels,
                )
            elif paged_attn_impl == "gather":
                # gather each row's first W logical tokens back out
                j = jnp.arange(W)
                flat_r = (
                    page_table[:, j // P] * P + (j % P)[None, :]
                ).reshape(-1)                                      # (B*W,)
                Kg = K[:, flat_r, :].reshape(Hkv, B, W, D).transpose(1, 0, 2, 3)
                Vg = V[:, flat_r, :].reshape(Hkv, B, W, D).transpose(1, 0, 2, 3)
                if kv_quant == "int8":
                    # dequantize with the SAME broadcast multiply the
                    # kernel uses, so gather/kernel parity holds
                    Ksg = Ks[:, flat_r].reshape(Hkv, B, W).transpose(1, 0, 2)
                    Vsg = Vs[:, flat_r].reshape(Hkv, B, W).transpose(1, 0, 2)
                    Kg = dequantize_kv(Kg, Ksg)
                    Vg = dequantize_kv(Vg, Vsg)
                mask = j[None, None, :] <= positions[:, :, None]   # (B,S,W)
                if cfg.attn_window is not None:
                    mask &= j[None, None, :] > (
                        positions[:, :, None] - cfg.attn_window
                    )
                o = _grouped_cache_attention(q, Kg, Vg, mask, groups)
            else:
                raise ValueError(
                    f"unknown paged_attn_impl {paged_attn_impl!r}"
                )
        elif layer_cache is not None:
            # Autoregressive decode path (SURVEY.md §2.2 "vLLM backend"
            # analog): keys/values accumulate in an explicit functional
            # cache — (B, H, max_len, D) — threaded through apply(), never
            # flax mutable state. Already-roped keys are cached, so decode
            # steps pay one GEMV against the cache, not a re-prefill.
            if getattr(cache_index, "ndim", 0) == 1:
                # PER-ROW slots (B,): continuous batching writes each row at
                # its own progress point (rows admitted at different times)
                upd = lambda c, new, i: jax.lax.dynamic_update_slice(
                    c, new, (0, i, 0)
                )
                K = jax.vmap(upd)(
                    layer_cache["k"], k.astype(layer_cache["k"].dtype),
                    cache_index,
                )
                V = jax.vmap(upd)(
                    layer_cache["v"], v.astype(layer_cache["v"].dtype),
                    cache_index,
                )
            else:
                K = jax.lax.dynamic_update_slice(
                    layer_cache["k"], k.astype(layer_cache["k"].dtype),
                    (0, 0, cache_index, 0),
                )
                V = jax.lax.dynamic_update_slice(
                    layer_cache["v"], v.astype(layer_cache["v"].dtype),
                    (0, 0, cache_index, 0),
                )
            new_cache = {"k": K, "v": V}
            T = K.shape[2]
            kpos = jnp.arange(T)
            if kv_mask is None:
                # default: causal over absolute slots (prefill) — here slot
                # index == token position, so the sliding window (if any)
                # applies directly: key slot must be within the last
                # attn_window positions of the query
                if getattr(cache_index, "ndim", 0) == 1:
                    qpos = cache_index[:, None] + jnp.arange(S)[None, :]
                    mask = kpos[None, None, :] <= qpos[:, :, None]  # (B,S,T)
                    if cfg.attn_window is not None:
                        mask &= kpos[None, None, :] > (
                            qpos[:, :, None] - cfg.attn_window
                        )
                else:
                    qpos = cache_index + jnp.arange(S)
                    mask = kpos[None, :] <= qpos[:, None]
                    if cfg.attn_window is not None:
                        mask &= kpos[None, :] > qpos[:, None] - cfg.attn_window
                    mask = jnp.broadcast_to(mask[None, :, :], (B, S, T))
            else:
                # caller-supplied slot mask: slot index need NOT equal token
                # position (continuous-batching gen regions start at a
                # quantized slot), so the window can only be applied by the
                # caller, who owns the slot→position mapping. generate.py
                # and serve/engine.py both do; anything else must too.
                # (B, T) masks every query position the same way (classic
                # one-token decode); (B, S, T) gives each query its own
                # slot bound — the speculative multi-token verify step,
                # where query j must not see the span's future draft keys
                # (decode_span_kv_mask).
                kvm = kv_mask if kv_mask.ndim == 3 else kv_mask[:, None, :]
                mask = jnp.broadcast_to(kvm, (B, S, T))
            o = _grouped_cache_attention(q, K, V, mask, groups)
        else:
            o = dispatch_attention(
                q, expand(k), expand(v), cfg, segment_ids=segment_ids
            )

        o = o.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        out = nn.Dense(
            cfg.d_model, use_bias=False, dtype=cfg.dtype, name="o_proj"
        )(o)
        if layer_cache is not None:
            return out, new_cache
        return out


def dispatch_attention(q, k, v, cfg: TransformerConfig, *, segment_ids=None):
    """Route to the configured attention strategy. q/k/v: (B, H, S, D)."""
    mesh = current_mesh()
    kw = dict(
        causal=cfg.causal,
        block_q=cfg.attn_block_q,
        block_k=cfg.attn_block_k,
        interpret=cfg.interpret_kernels,
    )
    if cfg.attn_impl == "reference" or (
        cfg.attn_impl == "flash" and mesh.empty
    ):
        if cfg.attn_impl == "reference":
            return reference_attention(
                q, k, v, causal=cfg.causal, window=cfg.attn_window,
                q_segment_ids=segment_ids, kv_segment_ids=segment_ids,
            )
        return flash_attention(
            q, k, v, q_segment_ids=segment_ids, kv_segment_ids=segment_ids,
            window=cfg.attn_window, **kw,
        )
    if mesh.empty:
        raise ValueError(
            f"attn_impl {cfg.attn_impl!r} needs a mesh context (jax.set_mesh)"
        )

    spec = P((Axis.DATA, Axis.FSDP), Axis.MODEL, Axis.SEQ, None)
    seg_spec = P((Axis.DATA, Axis.FSDP), Axis.SEQ)
    # unpacked batches must not pay the seg machinery (per-tile mask loads,
    # an extra ring ppermute per hop, the ulysses all_gather): the dummy
    # zeros below exist only to give shard_map a concrete operand
    has_seg = segment_ids is not None

    if cfg.attn_impl == "flash":
        def local(q, k, v, seg):
            seg = seg if has_seg else None
            return flash_attention(
                q, k, v, window=cfg.attn_window,
                q_segment_ids=seg, kv_segment_ids=seg, **kw,
            )
    elif cfg.attn_impl == "ring":
        def local(q, k, v, seg):
            return ring_attention_local(
                q, k, v, axis_name=Axis.SEQ, causal=cfg.causal,
                segment_ids=seg if has_seg else None,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
                interpret=cfg.interpret_kernels,
            )
    else:  # ulysses
        def local(q, k, v, seg):
            return ulysses_attention_local(
                q, k, v, axis_name=Axis.SEQ, causal=cfg.causal,
                segment_ids=seg if has_seg else None,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
                interpret=cfg.interpret_kernels,
            )

    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:1] + q.shape[2:3], jnp.int32)
    if cfg.attn_impl == "flash" and mesh.shape.get(Axis.SEQ, 1) > 1:
        raise ValueError(
            "attn_impl='flash' cannot shard the seq axis; use 'ring' or "
            "'ulysses' for sequence parallelism"
        )
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, segment_ids)


class Mlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        up = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="up_proj")(x)
        gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="gate_proj")(x)
        return nn.Dense(
            cfg.d_model, use_bias=False, dtype=cfg.dtype, name="down_proj"
        )(nn.silu(gate) * up)


class Experts(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x2d):
        cfg, moe = self.cfg, self.cfg.moe
        router = self.param(
            "router_kernel",
            nn.initializers.lecun_normal(),
            (cfg.d_model, moe.num_experts),
        )
        up = self.param(
            "up_kernel",
            nn.initializers.lecun_normal(),
            (moe.num_experts, cfg.d_model, moe.expert_dim),
        )
        down = self.param(
            "down_kernel",
            nn.initializers.lecun_normal(),
            (moe.num_experts, moe.expert_dim, cfg.d_model),
        )
        out, aux, stats = moe_ffn(x2d, router, up, down, moe)
        self.sow("losses", "moe_aux", aux)
        return out


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(
        self,
        x,
        positions,
        segment_ids=None,
        layer_cache=None,
        cache_index=None,
        kv_mask=None,
        page_table=None,
        page_size=None,
        page_write_ok=None,
        paged_attn_impl="gather",
        kv_quant="none",
    ):
        cfg = self.cfg
        new_cache = None
        attn_in = RMSNorm(name="ln1")(x)
        if layer_cache is not None:
            h, new_cache = Attention(cfg, name="attn")(
                attn_in, positions, segment_ids,
                layer_cache=layer_cache, cache_index=cache_index,
                kv_mask=kv_mask, page_table=page_table,
                page_size=page_size, page_write_ok=page_write_ok,
                paged_attn_impl=paged_attn_impl, kv_quant=kv_quant,
            )
        else:
            h = Attention(cfg, name="attn")(attn_in, positions, segment_ids)
        x = _act_constraint(x + h)
        y = RMSNorm(name="ln2")(x)
        if self.use_moe:
            B, S, d = y.shape
            out = Experts(cfg, name="experts")(y.reshape(B * S, d))
            y = out.reshape(B, S, d)
        else:
            y = Mlp(cfg, name="mlp")(y)
        out = _act_constraint(x + y)
        if layer_cache is not None:
            return out, new_cache
        return out


class TransformerLM(nn.Module):
    """Decoder LM (causal=True) or encoder (causal=False)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        tokens,
        *,
        segment_ids=None,
        positions=None,
        cache=None,
        cache_index=None,
        kv_mask=None,
        page_table=None,
        page_size=None,
        page_write_ok=None,
        paged_attn_impl="gather",
        kv_quant="none",
    ):
        """Training/scoring: ``(tokens) -> logits``. Autoregressive serving:
        pass ``cache`` (from :func:`init_kv_cache`) + ``cache_index`` →
        ``(logits, new_cache)``; prefill writes slots [idx, idx+S), decode
        steps pass S=1. ``kv_mask`` (B, max_len) marks which cache slots a
        query may attend (ragged-prompt batches exclude padding slots).
        Paged serving (serve/paging.py) instead passes a pooled cache from
        :func:`init_paged_kv_cache` + ``page_table``/``page_size``/
        ``page_write_ok`` and explicit ``positions``; masking is derived
        from positions in-branch (kv_mask unused)."""
        cfg = self.cfg
        cfg.validate()
        B, S = tokens.shape
        if positions is None:
            start = 0 if cache_index is None else cache_index
            positions = jnp.broadcast_to(start + jnp.arange(S), (B, S))
        x = Embedding(
            cfg.vocab_size, cfg.d_model,
            dtype=cfg.dtype, impl=cfg.embed_impl, name="embed",
        )(tokens)
        if not cfg.use_rope:
            pos_emb = self.param(
                "pos_embedding",
                nn.initializers.normal(0.02),
                (cfg.max_seq_len, cfg.d_model),
            )
            x = x + jnp.take(pos_emb, positions, axis=0).astype(cfg.dtype)
        x = _act_constraint(x)

        policy = (
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            if cfg.remat_policy == "dots" else None
        )
        BlockCls = nn.remat(Block, policy=policy) if cfg.remat else Block
        new_cache = {} if cache is not None else None
        for i in range(cfg.n_layers):
            use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
            block = BlockCls(cfg, use_moe=use_moe, name=f"layers_{i}")
            if cache is not None:
                x, new_cache[f"layers_{i}"] = block(
                    x, positions, segment_ids,
                    layer_cache=cache[f"layers_{i}"],
                    cache_index=cache_index,
                    kv_mask=kv_mask,
                    page_table=page_table,
                    page_size=page_size,
                    page_write_ok=page_write_ok,
                    paged_attn_impl=paged_attn_impl,
                    kv_quant=kv_quant,
                )
            else:
                x = block(x, positions, segment_ids)
        x = RMSNorm(name="ln_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32, name="unembed"
        )(x)
        if cache is not None:
            return logits, new_cache
        return logits


def init_kv_cache(
    cfg: TransformerConfig, batch: int, max_len: int, dtype: Any | None = None
) -> dict:
    """Zeroed decode cache: one (B, kv_heads, max_len, head_dim) K and V per
    layer — GQA configs pay for kv_heads, not n_heads."""
    dtype = dtype or cfg.dtype
    shape = (batch, cfg.kv_heads, max_len, cfg.head_dim)
    return {
        f"layers_{i}": {
            "k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)
        }
        for i in range(cfg.n_layers)
    }


def init_paged_kv_cache(
    cfg: TransformerConfig,
    pool_tokens: int,
    dtype: Any | None = None,
    kv_quant: str = "none",
) -> dict:
    """Zeroed PAGED decode cache: one flat (kv_heads, pool_tokens,
    head_dim) K and V per layer, shared by every row through a block table
    (serve/paging.py). HBM is billed per resident TOKEN, not per
    (row × max_seq) rectangle. ``kv_quant="int8"`` stores int8 codes plus
    per-(kv_head, token) f32 ``k_scale``/``v_scale`` side arrays — the
    pool arrays themselves cost a quarter of f32 (half of bf16), scales
    add ~1/head_dim on top."""
    dtype = dtype or cfg.dtype
    shape = (cfg.kv_heads, pool_tokens, cfg.head_dim)
    if kv_quant == "int8":
        return {
            f"layers_{i}": {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:2], jnp.float32),
                "v_scale": jnp.zeros(shape[:2], jnp.float32),
            }
            for i in range(cfg.n_layers)
        }
    if kv_quant != "none":
        raise ValueError(f"unknown kv_quant {kv_quant!r}")
    return {
        f"layers_{i}": {
            "k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)
        }
        for i in range(cfg.n_layers)
    }


# --------------------------------------------------------------------------- #
# Trainer plumbing
# --------------------------------------------------------------------------- #

def make_init_fn(model: TransformerLM, seq_len: int, batch_size: int = 1):
    """``batch_size`` must be divisible by the mesh's batch partitions when
    the model's attention runs in shard_map (pass
    ``MeshSpec.batch_partitions``)."""

    def init_params(rng):
        dummy = jnp.zeros((batch_size, seq_len), jnp.int32)
        return model.init(rng, dummy)["params"]

    return init_params


def make_loss_fn(model: TransformerLM):
    """(params, {"inputs","targets"}, rng) → (loss, metrics). Includes MoE
    aux losses sown by Experts blocks."""
    import optax

    def loss_fn(params, batch, rng):
        del rng
        logits, vars_out = model.apply(
            {"params": params}, batch["inputs"], mutable=["losses"]
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["targets"]
        ).mean()
        metrics = {"lm_loss": loss}
        aux_tree = vars_out.get("losses", {})
        aux = sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(aux_tree))
        if aux_tree:
            loss = loss + aux
            metrics["moe_aux"] = aux
        acc = (jnp.argmax(logits, -1) == batch["targets"]).mean()
        metrics["accuracy"] = acc
        return loss, metrics

    return loss_fn
