"""MNIST CNN — the BASELINE config-1 model.

Architecture mirrors the reference example's small CNN (upstream analog
[training-operator] examples/pytorch/mnist/mnist.py: two conv blocks + two
dense — UNVERIFIED, mount empty, SURVEY.md §0), expressed as flax linen with
TPU-friendly defaults (NHWC, bf16-able, channel sizes that tile onto the
MXU/VPU lanes).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def make_loss_fn(model: MnistCNN):
    """(params, (images, labels), rng) → (loss, {accuracy})."""

    def loss_fn(params, batch, rng):
        del rng
        images, labels = batch
        logits = model.apply({"params": params}, images)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, {"accuracy": acc}

    return loss_fn


def make_init_fn(model: MnistCNN, image_shape=(28, 28, 1)):
    def init_params(rng):
        dummy = jnp.zeros((1, *image_shape), jnp.float32)
        return model.init(rng, dummy)["params"]

    return init_params
