"""``jax.distributed`` bootstrap from the orchestrator's env contract.

TPU-native replacement for the reference's rank-rendezvous wiring
(SURVEY.md §2.7): where the PyTorchJob controller sets
``MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE`` (c10d TCPStore rendezvous) and the
TFJob controller builds ``TF_CONFIG`` JSON, our JAXJob controller sets three
env vars and worker processes call :func:`initialize_from_env` exactly once
before touching any device.

Env contract (written by ``kubeflow_tpu.orchestrator.envwire``):

- ``JAX_COORDINATOR_ADDRESS``  — host:port of process 0 (the "master" headless
  service analog).
- ``JAX_NUM_PROCESSES``        — world size.
- ``JAX_PROCESS_ID``           — this pod's completion-index / rank.
- ``JAX_LOCAL_DEVICE_IDS``     — optional, comma-separated; used by CPU
  simulation so each process claims distinct virtual devices.

Reference analog (UNVERIFIED upstream layout, mount empty — SURVEY.md §0):
[training-operator] pkg/controller.v1/pytorch/envvar.go (setPodEnv),
pkg/controller.v1/tensorflow/tfjob_controller.go (TF_CONFIG builder).
"""

from __future__ import annotations

import dataclasses
import logging
import os

logger = logging.getLogger(__name__)

ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_LOCAL_DEVICE_IDS = "JAX_LOCAL_DEVICE_IDS"

# GKE TPU provisioning surface the orchestrator models (SURVEY.md §5.8).
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Resolved multi-process rendezvous parameters."""

    coordinator_address: str | None = None
    num_processes: int = 1
    process_id: int = 0
    local_device_ids: tuple[int, ...] | None = None

    @property
    def is_multiprocess(self) -> bool:
        return self.num_processes > 1

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "DistributedConfig":
        """Resolve from the JAXJob env contract, with TPU-pod fallbacks.

        Precedence: explicit ``JAX_*`` contract > GKE ``TPU_WORKER_*`` vars
        (a bare TPU pod slice without our orchestrator) > single-process.
        """
        e = os.environ if env is None else env
        if ENV_NUM_PROCESSES in e:
            num = int(e[ENV_NUM_PROCESSES])
            cfg = cls(
                coordinator_address=e.get(ENV_COORDINATOR_ADDRESS),
                num_processes=num,
                process_id=int(e.get(ENV_PROCESS_ID, "0")),
                local_device_ids=_parse_device_ids(e.get(ENV_LOCAL_DEVICE_IDS)),
            )
        elif ENV_TPU_WORKER_HOSTNAMES in e:
            hosts = [h for h in e[ENV_TPU_WORKER_HOSTNAMES].split(",") if h]
            if len(hosts) > 1 and ENV_TPU_WORKER_ID not in e:
                raise ValueError(
                    f"{ENV_TPU_WORKER_HOSTNAMES} lists {len(hosts)} workers "
                    f"but {ENV_TPU_WORKER_ID} is unset; every worker would "
                    "claim rank 0"
                )
            cfg = cls(
                coordinator_address=f"{hosts[0]}:8476" if hosts else None,
                num_processes=max(len(hosts), 1),
                process_id=int(e.get(ENV_TPU_WORKER_ID, "0")),
            )
        else:
            cfg = cls()
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.num_processes < 1:
            raise ValueError(f"num_processes must be >=1, got {self.num_processes}")
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id {self.process_id} out of range "
                f"[0, {self.num_processes})"
            )
        if self.is_multiprocess and not self.coordinator_address:
            raise ValueError(
                f"{ENV_COORDINATOR_ADDRESS} required when "
                f"{ENV_NUM_PROCESSES} > 1"
            )


def initialize(cfg: DistributedConfig) -> None:
    """Idempotently bring up the ``jax.distributed`` coordinator/clients.

    The coordinator service (gRPC, C++ inside jaxlib) is the c10d-TCPStore /
    MPI-rendezvous equivalent; it also provides the peer-failure detection the
    supervisor relies on (SURVEY.md §5.3).
    """
    global _initialized
    if _initialized:
        logger.debug("jax.distributed already initialized; skipping")
        return
    if not cfg.is_multiprocess:
        # Don't latch: a later *multiprocess* init (e.g. the launcher's env
        # landing after an early library call) must still go through.
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
        local_device_ids=cfg.local_device_ids,
    )
    _initialized = True
    logger.info(
        "jax.distributed up: process %d/%d via %s",
        cfg.process_id,
        cfg.num_processes,
        cfg.coordinator_address,
    )


def initialize_from_env() -> DistributedConfig:
    """Bootstrap entrypoint every JAXJob worker calls first."""
    cfg = DistributedConfig.from_env()
    initialize(cfg)
    return cfg


def _parse_device_ids(raw: str | None) -> tuple[int, ...] | None:
    if not raw:
        return None
    return tuple(int(x) for x in raw.split(",") if x.strip())
