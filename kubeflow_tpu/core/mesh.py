"""Device mesh construction over ICI/DCN topology.

This is the TPU-native replacement for the reference platform's entire L1
"communication plane" wiring (SURVEY.md §1 L1, §2.7): where the reference's
controllers wire NCCL/gloo/MPI via ``MASTER_ADDR``/``TF_CONFIG``/hostfiles and
the frameworks build process groups, on TPU all collectives are emitted by XLA
against a single ``jax.sharding.Mesh``. The only "backend" decisions are:

1. which *named logical axes* exist (data / fsdp / model / expert / seq / pipe),
2. how they map onto the *physical* ICI torus (and a leading DCN axis for
   multislice), so collectives ride ICI neighbor links rather than hopping.

Reference analog (UNVERIFIED upstream layout, mount empty — SURVEY.md §0):
[training-operator] pkg/controller.v1/pytorch/envvar.go builds the rendezvous
env; process-group *factorization* (DPxTPxPP) lives in user containers
(Megatron/DeepSpeed configs). Here both collapse into ``MeshSpec``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


class Axis:
    """Canonical logical mesh-axis names.

    Every parallelism strategy in SURVEY.md §2.6 is one named axis:

    - ``DATA``:   pure data parallel (gradient psum; NCCL-allreduce analog).
    - ``FSDP``:   data parallel with param/grad/opt-state sharding
                  (ZeRO-3/FSDP analog; XLA inserts all-gather/reduce-scatter).
    - ``MODEL``:  tensor parallel (Megatron column/row sharding analog).
    - ``EXPERT``: expert parallel for MoE (all_to_all token dispatch).
    - ``SEQ``:    sequence/context parallel (Ulysses all_to_all or ring
                  attention ppermute).
    - ``PIPE``:   pipeline-stage axis (GPipe/1F1B microbatching).
    """

    DATA = "data"
    FSDP = "fsdp"
    MODEL = "model"
    EXPERT = "expert"
    SEQ = "seq"
    PIPE = "pipe"

    #: Order matters: outermost (slowest-varying, largest communication
    #: granularity, most DCN-tolerant) first. PIPE and DATA tolerate slow
    #: links (activations/gradients once per step); MODEL/SEQ need the
    #: fastest links (per-layer collectives), so they sit innermost where
    #: `mesh_utils.create_device_mesh` assigns ICI-adjacent devices.
    ALL = (PIPE, DATA, FSDP, EXPERT, SEQ, MODEL)

    #: Axes over which the *batch* is split — used to compute per-device
    #: batch sizes and to build data shardings.
    BATCH = (DATA, FSDP)


#: Known single-slice ICI torus shapes for TPU v5e (chips per slice → 2D
#: physical topology) — SURVEY.md §2.7 "ICI" row. v5e slices are 2D tori.
V5E_TOPOLOGIES: Mapping[int, tuple[int, ...]] = {
    1: (1, 1),
    2: (1, 2),
    4: (2, 2),
    8: (2, 4),
    16: (4, 4),
    32: (4, 8),
    64: (8, 8),
    128: (8, 16),
    256: (16, 16),
}


def slice_topology(num_devices: int, generation: str = "v5e") -> tuple[int, ...]:
    """Physical ICI topology for a slice of ``num_devices`` chips.

    Falls back to a near-square 2D factorization for sizes not in the table
    (e.g. CPU simulation meshes).
    """
    del generation  # only v5e shipped in this environment; table is v5e's
    if num_devices in V5E_TOPOLOGIES:
        return V5E_TOPOLOGIES[num_devices]
    # Near-square factorization keeps ring axes short for simulated meshes.
    a = int(math.sqrt(num_devices))
    while a > 1 and num_devices % a != 0:
        a -= 1
    return (a, num_devices // a)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative logical mesh: named axis sizes plus an optional DCN axis.

    A ``MeshSpec`` is the single source of truth for how a job is
    parallelized. The orchestrator stores it in the JobSpec; the train loop
    builds the ``jax.sharding.Mesh`` from it; sharding rules reference its
    axis names.

    ``dcn_data`` is the leading cross-slice axis for multislice jobs
    (SURVEY.md §2.7 "DCN" row): data/pipeline parallelism across slices,
    everything else within a slice.
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    expert: int = 1
    seq: int = 1
    pipe: int = 1
    dcn_data: int = 1

    # ------------------------------------------------------------------ #

    @property
    def ici_axis_sizes(self) -> dict[str, int]:
        return {
            Axis.PIPE: self.pipe,
            Axis.DATA: self.data,
            Axis.FSDP: self.fsdp,
            Axis.EXPERT: self.expert,
            Axis.SEQ: self.seq,
            Axis.MODEL: self.model,
        }

    @property
    def axis_names(self) -> tuple[str, ...]:
        return Axis.ALL

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        """ICI-only logical shape; ``build_mesh`` folds ``dcn_data`` in."""
        return tuple(self.ici_axis_sizes[name] for name in Axis.ALL)

    @property
    def ici_devices(self) -> int:
        return int(np.prod(self.axis_sizes))

    @property
    def total_devices(self) -> int:
        return self.ici_devices * self.dcn_data

    @property
    def batch_partitions(self) -> int:
        """How many ways the global batch is split (data-like axes x DCN)."""
        return self.data * self.fsdp * self.dcn_data

    def validate(self, num_devices: int | None = None) -> None:
        for name, size in self.ici_axis_sizes.items():
            if size < 1:
                raise ValueError(f"mesh axis {name!r} must be >=1, got {size}")
        if self.dcn_data < 1:
            raise ValueError(f"dcn_data must be >=1, got {self.dcn_data}")
        if num_devices is not None and self.total_devices != num_devices:
            raise ValueError(
                f"MeshSpec wants {self.total_devices} devices "
                f"({dict(self.ici_axis_sizes)} x dcn_data={self.dcn_data}) "
                f"but {num_devices} are available"
            )

    # ------------------------------------------------------------------ #

    @classmethod
    def data_parallel(cls, num_devices: int) -> "MeshSpec":
        """Pure DP over every device — the DDP/MultiWorkerMirrored analog."""
        return cls(data=num_devices)

    @classmethod
    def fsdp_parallel(cls, num_devices: int) -> "MeshSpec":
        return cls(fsdp=num_devices)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "MeshSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown MeshSpec fields: {sorted(unknown)}")
        return cls(**{k: int(v) for k, v in d.items()})

    def to_dict(self) -> dict[str, int]:
        return dataclasses.asdict(self)


def build_mesh(
    spec: MeshSpec,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Materialize a ``jax.sharding.Mesh`` laying logical axes onto hardware.

    Uses ``mesh_utils.create_device_mesh`` so that, on real TPU slices, the
    innermost logical axes (MODEL, SEQ — the chatty ones) map to physically
    adjacent chips on the ICI torus, and ``create_hybrid_device_mesh`` when a
    DCN axis is present so cross-slice traffic is confined to the leading
    (data) axis. This is the topology-awareness that replaces everything the
    reference delegated to ``NCCL_*`` env tuning (SURVEY.md §5.8).
    """
    if devices is None:
        devices = jax.devices()
    spec.validate(len(devices))

    if spec.dcn_data > 1:
        # Leading DCN axis: replicate the ICI mesh across slices, folding the
        # DCN factor into the DATA axis position.
        data_pos = Axis.ALL.index(Axis.DATA)
        # Only take the hybrid path when the visible devices really span
        # `dcn_data` DISTINCT slices. Merely having a `slice_index` attribute
        # is not enough: a multi-process CPU-simulation world (and a
        # single-slice world standing in for many) reports slice_index=0 on
        # every device, and `create_hybrid_device_mesh` then rejects the
        # dcn_mesh_shape (VERDICT r2/r3 weak #1).
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        if None not in slice_ids and len(slice_ids) > 1 and len(slice_ids) != spec.dcn_data:
            # Genuinely multi-slice hardware that doesn't match the spec is a
            # misconfiguration — falling back would lay "ICI" axes across DCN
            # links and silently train an order of magnitude slower.
            raise ValueError(
                f"devices span {len(slice_ids)} distinct slices but "
                f"MeshSpec.dcn_data={spec.dcn_data}"
            )
        if None not in slice_ids and len(slice_ids) == spec.dcn_data:
            ici_shape = list(spec.axis_sizes)
            dcn_shape = [1] * len(ici_shape)
            dcn_shape[data_pos] = spec.dcn_data
            device_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape,
                dcn_shape,
                devices=devices,
                allow_split_physical_axes=True,
            )
            return Mesh(device_array, Axis.ALL)
        # Fallback: partition devices into `dcn_data` virtual slices. Group
        # by process when the process count matches (each jax.distributed
        # process stands in for one slice — the CPU-sim contract used by
        # tests/test_multislice.py), contiguous id-ordered blocks otherwise.
        devs = sorted(devices, key=lambda d: (d.process_index, d.id))
        per = spec.ici_devices
        by_proc: dict[int, list] = {}
        for d in devs:
            by_proc.setdefault(d.process_index, []).append(d)
        if len(by_proc) == spec.dcn_data and all(
            len(b) == per for b in by_proc.values()
        ):
            blocks = [by_proc[k] for k in sorted(by_proc)]
        else:
            blocks = [devs[i * per : (i + 1) * per] for i in range(spec.dcn_data)]
        per_block = [np.asarray(b).reshape(spec.axis_sizes) for b in blocks]
        device_array = np.concatenate(per_block, axis=data_pos)
        return Mesh(device_array, Axis.ALL)

    device_array = mesh_utils.create_device_mesh(
        spec.axis_sizes, devices=devices, allow_split_physical_axes=True
    )
    return Mesh(device_array, Axis.ALL)


def single_device_mesh() -> Mesh:
    """A trivial mesh on the first local device (serving / smoke tests)."""
    return build_mesh(MeshSpec(), devices=jax.devices()[:1])


def mesh_context(mesh: Mesh):
    """``jax.set_mesh(mesh)`` where it exists (jax ≥ 0.5); on older jax
    the ``with mesh:`` physical-mesh context is the same ambient-mesh
    mechanism (it is what :func:`current_mesh` reads back)."""
    setter = getattr(jax, "set_mesh", None)
    if setter is not None:
        return setter(mesh)
    return mesh


def current_mesh():
    """The ambient mesh (``.empty`` when none): the public
    ``jax.sharding.get_abstract_mesh`` on new jax; on jax < 0.5 — where
    that API doesn't exist — the ``with mesh:`` context's physical mesh."""
    getter = getattr(jax.sharding, "get_abstract_mesh", None)
    if getter is not None:
        return getter()
    from jax._src import mesh as _mesh_src

    return _mesh_src.thread_resources.env.physical_mesh


def per_device_batch(global_batch: int, spec: MeshSpec) -> int:
    """Per-batch-shard size; validates divisibility like DDP samplers do."""
    parts = spec.batch_partitions
    if global_batch % parts != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"batch partitions {parts} (data={spec.data} x fsdp={spec.fsdp} "
            f"x dcn={spec.dcn_data})"
        )
    return global_batch // parts
