"""Subprocess accelerator liveness probe.

The chip in this environment is reached through a tunnel; when the tunnel
wedges, EVERY in-process device touch — including ``jax.default_backend()``
during backend init — blocks forever. Anything that must not hang (the
bench driver, the chip test suite) probes from a SUBPROCESS with a hard
timeout before touching jax devices in-process. One shared implementation
so "unreachable" means the same thing everywhere.
"""

from __future__ import annotations

import subprocess
import sys

#: prints the backend AND runs one op — a wedged tunnel hangs either the
#: backend init or the execute; both are caught by the subprocess timeout.
_PROBE = (
    "import jax, numpy as np;"
    "print('backend:' + jax.default_backend(), flush=True);"
    "x = jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8));"
    "print('value:' + str(float(np.asarray(x)[0, 0])))"
)

UNREACHABLE = "unreachable"


def probe_backend(timeout_s: float = 120.0) -> str:
    """Returns the backend name ("cpu", "tpu", …) or ``UNREACHABLE``."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE], timeout=timeout_s,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return UNREACHABLE
    if out.returncode != 0 or "value:" not in out.stdout:
        return UNREACHABLE
    for line in out.stdout.splitlines():
        if line.startswith("backend:"):
            return line.split(":", 1)[1]
    return UNREACHABLE
