"""Persistent XLA compilation cache — the cold-start lever.

Reference analog: none to port — upstream serving pays full compile (or
torch load) on every pod start; BASELINE config 5 measures that cost as
``cold_start_s``. XLA compiles are pure functions of (HLO, flags,
backend), so JAX's persistent compilation cache turns every process
start after the first into a disk read: measured on the v5e serving
config this is the difference between ~60s and a few seconds of cold
start. Every long-lived entrypoint (ModelServer, LMEngine, Trainer, the
CLI, bench) calls :func:`enable_compilation_cache` at construction; it
is idempotent, respects an operator-chosen directory, and can be opted
out of with ``KFT_NO_COMPILATION_CACHE=1`` (e.g. hermetic CI).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_DEFAULT_DIR = "~/.cache/kubeflow_tpu/xla"


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a durable directory.

    Returns the active cache dir, or None when disabled (opt-out env var
    set, or the directory cannot be created — a read-only rootfs must
    degrade to in-memory compiles, never crash serving).

    Resolution order: explicit argument > ``KFT_COMPILATION_CACHE_DIR`` >
    ``~/.cache/kubeflow_tpu/xla``. Idempotent: a dir already configured
    (by us or by the user via ``JAX_COMPILATION_CACHE_DIR``) is kept.
    """
    if os.environ.get("KFT_NO_COMPILATION_CACHE"):
        return None
    import jax

    # serving buckets are small programs that still take seconds of XLA
    # time on TPU; the default 1s floor would skip exactly the programs a
    # cold start pays for. Lowered even when the dir was configured
    # outside this function (JAX_COMPILATION_CACHE_DIR) — an "enabled"
    # cache that never persists the serving programs would be a lie.
    floor = jax.config.jax_persistent_cache_min_compile_time_secs
    if floor is None or floor > 0.2:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)

    current = jax.config.jax_compilation_cache_dir
    if current:
        return current
    cache_dir = os.path.expanduser(
        cache_dir
        or os.environ.get("KFT_COMPILATION_CACHE_DIR")
        or _DEFAULT_DIR
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        # unique probe name: concurrent starters sharing the dir must not
        # race each other's os.remove into a spurious "not writable"
        probe = os.path.join(cache_dir, f".kft-writable-{os.getpid()}")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        logger.warning(
            "compilation cache disabled: %s not writable (%s)", cache_dir, e
        )
        return None
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    return cache_dir
