"""Runtime + communication foundation: mesh, distributed bootstrap, collectives."""

from kubeflow_tpu.core.mesh import (  # noqa: F401
    Axis,
    MeshSpec,
    build_mesh,
    slice_topology,
)
from kubeflow_tpu.core.distributed import (  # noqa: F401
    DistributedConfig,
    initialize_from_env,
)
