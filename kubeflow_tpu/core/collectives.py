"""Collective primitives over the mesh + micro-benchmarks.

The reference stack's collective layer is NCCL/Gloo/Horovod/MPI linked into
user containers (SURVEY.md §2.7); the platform never calls it, only wires it.
On TPU the collectives are XLA-emitted onto ICI, so this module is thin:
named-axis wrappers usable inside ``shard_map``/``pjit``-sharded code, ring
helpers for pipeline/context parallelism, and the psum/all_gather/ppermute/
all_to_all micro-benchmarks SURVEY.md §7 step 1 calls for (the
``hvd.allreduce`` → ``lax.psum`` mapping of BASELINE config 3).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    shard_map = jax.shard_map
except AttributeError:  # jax < 0.5: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, check_vma: bool = True, **kwargs):
        return _shard_map_exp(f, check_rep=check_vma, **kwargs)

try:
    axis_size = lax.axis_size
except AttributeError:  # jax < 0.5: psum of a unit constant folds to the
    # axis size as a concrete int at trace time
    def axis_size(axis_name):
        return lax.psum(1, axis_name)


# --------------------------------------------------------------------------- #
# Named-axis wrappers. Inside shard_map/pjit these lower to single ICI
# collectives; they exist so call sites read like the strategy table in
# SURVEY.md §2.6 rather than raw lax.
# --------------------------------------------------------------------------- #

def grad_allreduce(grads, axis: str):
    """Mean-allreduce of gradients over a data axis — the DDP bucketed
    allreduce / ``hvd.allreduce`` analog, as one fused psum."""
    return jax.tree_util.tree_map(lambda g: lax.pmean(g, axis), grads)


def psum(x, axis: str):
    return lax.psum(x, axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    """FSDP param gather (ZeRO all-gather analog)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    """FSDP gradient reduce-scatter (ZeRO reduce-scatter analog)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ring_shift(x, axis: str, *, shift: int = 1):
    """Rotate shards around the axis ring with ``ppermute`` — the building
    block of ring attention (KV rotation) and pipeline stage handoff.
    ICI tori make each hop a physical-neighbor transfer."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """Re-shard between two tensor dimensions over ``axis`` — Ulysses
    sequence<->heads swap, MoE token dispatch."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


# --------------------------------------------------------------------------- #
# Micro-benchmarks (SURVEY.md §7 step 1; BASELINE config 3's allreduce path).
# --------------------------------------------------------------------------- #

def _timed(fn: Callable[[], jax.Array], iters: int, warmup: int) -> float:
    for _ in range(warmup):
        fn().block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters


def benchmark_collective(
    mesh: Mesh,
    axis: str,
    kind: str = "psum",
    *,
    mb_per_shard: float = 4.0,
    dtype=jnp.float32,
    iters: int = 10,
    warmup: int = 3,
) -> dict:
    """Time one collective over ``axis``; returns sec/op and algo bandwidth.

    ``kind``: psum | all_gather | reduce_scatter | ppermute | all_to_all.
    Algo-bandwidth convention matches nccl-tests so numbers are comparable
    with the reference stack's NCCL/Horovod benchmarking practice.
    """
    n = mesh.shape[axis]
    elem = jnp.dtype(dtype).itemsize
    rows = max(int(mb_per_shard * 1e6 / (128 * elem)), n)
    rows -= rows % n  # all_to_all needs divisibility
    shard_shape = (rows, 128)
    nbytes = rows * 128 * elem

    ops = {
        "psum": lambda x: lax.psum(x, axis),
        "all_gather": lambda x: lax.all_gather(x, axis, axis=0, tiled=True),
        "reduce_scatter": lambda x: lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True),
        "ppermute": lambda x: ring_shift(x, axis),
        "all_to_all": lambda x: lax.all_to_all(x, axis, split_axis=0, concat_axis=1, tiled=True),
    }
    if kind not in ops:
        raise ValueError(f"unknown collective {kind!r}; options {sorted(ops)}")
    op = ops[kind]

    spec = P(axis)  # shard rows over the axis
    @partial(
        jax.jit,
        in_shardings=NamedSharding(mesh, spec),
        out_shardings=NamedSharding(mesh, _out_spec(kind, axis)),
    )
    def step(x):
        # check_vma=False: all_gather output is replicated over `axis`, which
        # the static varying-manifest check can't always infer.
        return shard_map(
            op, mesh=mesh, in_specs=spec, out_specs=_out_spec(kind, axis),
            check_vma=False,
        )(x)

    global_shape = (shard_shape[0] * n, shard_shape[1])
    x = jax.device_put(
        jnp.ones(global_shape, dtype), NamedSharding(mesh, spec)
    )
    sec = _timed(lambda: step(x), iters, warmup)

    # Algorithmic bytes moved per device (nccl-tests convention).
    factor = {
        "psum": 2 * (n - 1) / n,
        "all_gather": (n - 1) / n,
        "reduce_scatter": (n - 1) / n,
        "ppermute": 1.0,
        "all_to_all": (n - 1) / n,
    }[kind]
    busbw = nbytes * n * factor / sec if sec > 0 else float("inf")
    return {
        "kind": kind,
        "axis": axis,
        "axis_size": n,
        "bytes_per_shard": nbytes,
        "sec_per_op": sec,
        "bus_gbps": busbw / 1e9,
    }


def _out_spec(kind: str, axis: str) -> P:
    # all_gather returns replicated-along-axis output; everything else keeps
    # the input sharding layout.
    if kind == "all_gather":
        return P(None)
    return P(axis)


def benchmark_suite(mesh: Mesh, axis: str, **kw) -> list[dict]:
    return [
        benchmark_collective(mesh, axis, kind, **kw)
        for kind in ("psum", "all_gather", "reduce_scatter", "ppermute", "all_to_all")
    ]
