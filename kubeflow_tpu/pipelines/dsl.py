"""Pipeline DSL: `@component`, `@pipeline`, Input/Output artifact markers.

Reference analog (SURVEY.md §2.4 row 1): KFP's `@dsl.component` turns a
python function into a containerized component; `@dsl.pipeline` traces a
function whose body calls components, producing tasks wired by data
edges; `ContainerOp.set_gpu_limit()` / node selectors are the GPU
resource surface ([pipelines] sdk/python/kfp/dsl/ — UNVERIFIED,
SURVEY.md §0). Here `.set_tpu_request()` is that surface re-targeted to
TPU chips + topology, and tracing happens at compile time via
placeholder `TaskOutput` objects instead of container command lines.
"""

from __future__ import annotations

import dataclasses
import inspect
import textwrap
import typing
from typing import Any, Callable, Generic, TypeVar

from kubeflow_tpu.pipelines.artifacts import Artifact
from kubeflow_tpu.pipelines.ir import (
    ComponentIR,
    InputRef,
    OutputSpec,
    ResourceSpec,
)

T = TypeVar("T")


class Input(Generic[T]):
    """Annotation marker: `x: Input[Dataset]` — artifact consumed by value."""


class Output(Generic[T]):
    """Annotation marker: `x: Output[Model]` — artifact the fn writes to."""


def _annotation_kind(ann: Any) -> tuple[str, str]:
    """→ ("parameter"|"input_artifact"|"output_artifact", artifact TYPE)."""
    origin = typing.get_origin(ann)
    if origin in (Input, Output):
        (atype,) = typing.get_args(ann)
        if not (isinstance(atype, type) and issubclass(atype, Artifact)):
            raise TypeError(f"Input/Output arg must be an Artifact type, got {atype}")
        kind = "input_artifact" if origin is Input else "output_artifact"
        return kind, atype.TYPE
    return "parameter", ""


@dataclasses.dataclass(frozen=True)
class TaskOutput:
    """Placeholder for one task output while tracing a pipeline body."""

    task: "Task"
    name: str

    def ref(self) -> InputRef:
        return InputRef(task_output=(self.task.name, self.name))


class Task:
    """A component invocation recorded during pipeline tracing —
    the ContainerOp analog (mutable: resource/caching setters chain)."""

    def __init__(self, component: "Component", name: str,
                 inputs: dict[str, Any]):
        self.component = component
        self.name = name
        self.inputs = inputs           # name → constant | TaskOutput | PipelineParam
        self.resources = ResourceSpec()
        self.cache_enabled = True
        self.retries = 0
        self._after: list[str] = []

    # --- chained setters (ContainerOp surface) ------------------------ #

    def set_tpu_request(self, chips: int, topology: str = "",
                        num_workers: int = 1) -> "Task":
        """`set_gpu_limit` / `add_node_selector_constraint('gke-accelerator')`
        analog: ask for TPU chips (+ optional topology, multi-worker gang)."""
        self.resources = dataclasses.replace(
            self.resources, tpu_chips=chips, topology=topology,
            num_workers=num_workers,
        )
        return self

    def set_cpu_request(self, millis: int) -> "Task":
        self.resources = dataclasses.replace(self.resources, cpu_millis=millis)
        return self

    def set_memory_request(self, mb: int) -> "Task":
        self.resources = dataclasses.replace(self.resources, memory_mb=mb)
        return self

    def set_caching_options(self, enabled: bool) -> "Task":
        self.cache_enabled = enabled
        return self

    def set_retry(self, retries: int) -> "Task":
        self.retries = retries
        return self

    def after(self, *tasks: "Task") -> "Task":
        self._after.extend(t.name for t in tasks)
        return self

    # --- output access ------------------------------------------------ #

    @property
    def output(self) -> TaskOutput:
        outs = self.component.ir.outputs
        if len(outs) != 1:
            raise ValueError(
                f"task {self.name!r} has {len(outs)} outputs; use .outputs[name]"
            )
        return TaskOutput(self, outs[0].name)

    @property
    def outputs(self) -> dict[str, TaskOutput]:
        return {o.name: TaskOutput(self, o.name) for o in self.component.ir.outputs}


@dataclasses.dataclass(frozen=True)
class PipelineParam:
    """Placeholder for a pipeline-level parameter during tracing."""

    name: str

    def ref(self) -> InputRef:
        return InputRef(parameter=self.name)


class _TraceContext:
    current: "_TraceContext | None" = None

    def __init__(self) -> None:
        self.tasks: list[Task] = []
        self.components: dict[str, ComponentIR] = {}
        self._names: dict[str, int] = {}

    def unique(self, base: str) -> str:
        n = self._names.get(base, 0)
        self._names[base] = n + 1
        return base if n == 0 else f"{base}-{n + 1}"

    def record(self, task: Task) -> None:
        self.tasks.append(task)
        prior = self.components.get(task.component.ir.name)
        if prior is not None and prior != task.component.ir:
            raise ValueError(
                f"two different components both named "
                f"{task.component.ir.name!r} used in one pipeline — "
                "give one an explicit @component(name=...)"
            )
        self.components[task.component.ir.name] = task.component.ir


class Component:
    """A `@component`-decorated function: callable directly (plain python)
    or inside a `@pipeline` body (records a Task)."""

    def __init__(self, fn: Callable, name: str | None = None,
                 env: dict[str, str] | None = None):
        self.fn = fn
        hints = typing.get_type_hints(fn, include_extras=True)
        sig = inspect.signature(fn)
        inputs, input_kinds, outputs = [], [], []
        for pname in sig.parameters:
            ann = hints.get(pname, str)
            kind, atype = _annotation_kind(ann)
            if kind == "output_artifact":
                outputs.append(OutputSpec(pname, kind=atype))
            else:
                inputs.append(pname)
                input_kinds.append((pname, atype or "parameter"))
        ret = hints.get("return")
        if ret is not None and ret is not type(None):  # noqa: E721
            outputs.append(OutputSpec("Output", kind="parameter"))
        try:
            source = textwrap.dedent(inspect.getsource(fn))
        except OSError:  # interactively-defined fn; executor will refuse jobs
            source = ""
        # strip decorator lines (possibly multi-line calls) so the
        # serialized source starts at the def and is re-executable
        lines = source.splitlines()
        while lines and not lines[0].startswith(("def ", "async def ")):
            lines.pop(0)
        self.ir = ComponentIR(
            name=name or fn.__name__.replace("_", "-"),
            source="\n".join(lines),
            fn_name=fn.__name__,
            inputs=tuple(inputs),
            input_kinds=tuple(input_kinds),
            outputs=tuple(outputs),
            base_env=tuple(sorted((env or {}).items())),
        )

    def __call__(self, *args, **kwargs):
        ctx = _TraceContext.current
        if ctx is None:
            return self.fn(*args, **kwargs)   # plain python call
        bound: dict[str, Any] = {}
        names = list(self.ir.inputs)
        if args:
            if len(args) > len(names):
                raise TypeError(f"{self.ir.name}: too many positional args")
            bound.update(zip(names, args))
        for k, v in kwargs.items():
            if k not in names:
                raise TypeError(f"{self.ir.name}: unexpected argument {k!r}")
            if k in bound:
                raise TypeError(f"{self.ir.name}: duplicate argument {k!r}")
            bound[k] = v
        task = Task(self, ctx.unique(self.ir.name), bound)
        ctx.record(task)
        return task


def component(fn: Callable | None = None, *, name: str | None = None,
              env: dict[str, str] | None = None):
    if fn is None:
        return lambda f: Component(f, name=name, env=env)
    return Component(fn, name=name, env=env)


# JSON-safe sentinel for "parameter has no default" — distinct from a
# legitimate default of None
REQUIRED = "__kft_required__"


class Pipeline:
    def __init__(self, fn: Callable, name: str | None = None,
                 description: str = ""):
        self.fn = fn
        self.name = name or fn.__name__.replace("_", "-")
        self.description = description
        sig = inspect.signature(fn)
        self.parameters: list[tuple[str, Any]] = []
        for pname, p in sig.parameters.items():
            default = (REQUIRED if p.default is inspect.Parameter.empty
                       else p.default)
            self.parameters.append((pname, default))


def pipeline(fn: Callable | None = None, *, name: str | None = None,
             description: str = ""):
    if fn is None:
        return lambda f: Pipeline(f, name=name, description=description)
    return Pipeline(fn, name=name, description=description)
