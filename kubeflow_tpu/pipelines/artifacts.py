"""Artifact types and store.

Reference analog (SURVEY.md §2.4): KFP artifacts (`dsl.Dataset`,
`dsl.Model`, `dsl.Metrics`) stored in MinIO under
`<bucket>/<pipeline>/<run>/<task>/<output>`; the launcher downloads
inputs and uploads outputs ([pipelines] backend/src/v2/component/
launcher_v2.go — UNVERIFIED, SURVEY.md §0).

Here artifacts are directories/files under a local root with the same
run-scoped layout, addressed by `uri`. A `file://` uri maps straight to
the path; other schemes resolve through `kubeflow_tpu.serve.storage`
fetchers so `gs://` stubs plug in uniformly.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

from kubeflow_tpu.serve import storage as _storage


@dataclasses.dataclass
class Artifact:
    """A named, typed blob with metadata — the MLMD artifact analog."""

    name: str = ""
    uri: str = ""
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)

    TYPE = "system.Artifact"

    @property
    def path(self) -> str:
        """Local filesystem path for reading/writing the payload."""
        if self.uri.startswith("file://"):
            return self.uri[len("file://"):]
        if "://" not in self.uri:
            return self.uri
        raise ValueError(
            f"artifact {self.name!r} uri {self.uri!r} is not local; "
            "call ArtifactStore.localize() first"
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "uri": self.uri,
            "type": self.TYPE,
            "metadata": dict(self.metadata),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Artifact":
        klass = _TYPE_REGISTRY.get(d.get("type", cls.TYPE), Artifact)
        return klass(
            name=d.get("name", ""),
            uri=d.get("uri", ""),
            metadata=dict(d.get("metadata", {})),
        )


class Dataset(Artifact):
    TYPE = "system.Dataset"


class Model(Artifact):
    TYPE = "system.Model"


class Metrics(Artifact):
    TYPE = "system.Metrics"

    def log_metric(self, key: str, value: float) -> None:
        self.metadata[key] = float(value)


_TYPE_REGISTRY = {
    k.TYPE: k for k in (Artifact, Dataset, Model, Metrics)
}


class ArtifactStore:
    """Run-scoped artifact root: ``<root>/<pipeline>/<run_id>/<task>/<name>``."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def uri_for(self, pipeline: str, run_id: str, task: str, name: str) -> str:
        path = os.path.join(self.root, pipeline, run_id, task, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        return "file://" + path

    def localize(self, artifact: Artifact, dest_dir: str) -> str:
        """Materialize a (possibly remote) artifact locally; returns path."""
        if artifact.uri.startswith("file://") or "://" not in artifact.uri:
            return artifact.path
        return _storage.download(artifact.uri, dest_dir)

    # -- parameter (small JSON value) storage ------------------------- #

    def put_value(self, pipeline: str, run_id: str, task: str,
                  name: str, value: Any) -> str:
        uri = self.uri_for(pipeline, run_id, task, name + ".json")
        with open(uri[len("file://"):], "w") as f:
            json.dump(value, f)
        return uri

    def get_value(self, uri: str) -> Any:
        with open(uri[len("file://"):]) as f:
            return json.load(f)
