"""Component executor: the KFP launcher analog.

Reference analog (SURVEY.md §2.4 "v2 driver & launcher"): the launcher
pod wraps the user container — downloads input artifacts, execs the
component, uploads outputs, records to MLMD ([pipelines]
backend/src/v2/component/launcher_v2.go — UNVERIFIED, SURVEY.md §0).

Here the runner writes ``task.json`` into a workdir, then either calls
:func:`execute` in-process (fast path) or launches
``python -m kubeflow_tpu.pipelines.executor --workdir D`` as a JAXJob
through the orchestrator (TPU/multi-worker steps, §3.5 mapping). The
executor re-execs the serialized component source, wires parameters and
artifacts, and writes ``outputs.json``; lineage is recorded by the
runner, which owns the stores.

task.json = {component: ComponentIR dict, inputs: {name: value | artifact
dict}, output_uris: {name: uri}, parameters_uri: uri}
"""

from __future__ import annotations

import argparse
import json
import os
import traceback
from typing import Any

from kubeflow_tpu.pipelines.artifacts import Artifact, _TYPE_REGISTRY
from kubeflow_tpu.pipelines.ir import ComponentIR


def _load_fn(component: ComponentIR):
    if not component.source:
        raise RuntimeError(
            f"component {component.name!r} has no serializable source "
            "(defined interactively?) — run it in-process instead"
        )
    ns: dict[str, Any] = {}
    exec(compile(component.source, f"<component:{component.name}>", "exec"), ns)
    fn = ns.get(component.fn_name)
    if fn is None:
        raise RuntimeError(
            f"component {component.name!r}: {component.fn_name!r} not found "
            "after exec of serialized source"
        )
    return fn


def execute(task: dict) -> dict:
    """Run one component invocation; returns the outputs dict
    {name: {"value": v} | artifact dict}."""
    component = ComponentIR.from_dict(task["component"])
    kinds = dict(component.input_kinds)
    kwargs: dict[str, Any] = {}
    input_artifacts: list[Artifact] = []
    for name in component.inputs:
        raw = task["inputs"][name]
        if kinds.get(name, "parameter") != "parameter":
            art = Artifact.from_dict(raw)
            kwargs[name] = art
            input_artifacts.append(art)
        else:
            kwargs[name] = raw

    output_artifacts: dict[str, Artifact] = {}
    for out in component.outputs:
        if out.kind == "parameter":
            continue
        klass = _TYPE_REGISTRY.get(out.kind, Artifact)
        art = klass(name=out.name, uri=task["output_uris"][out.name])
        kwargs[out.name] = art
        output_artifacts[out.name] = art

    fn = _load_fn(component)
    ret = fn(**kwargs)

    outputs: dict[str, Any] = {}
    for out in component.outputs:
        if out.kind == "parameter":
            outputs[out.name] = {"value": ret}
        else:
            art = output_artifacts[out.name]
            if art.TYPE == "system.Model":
                _stamp_model_digest(art)
            outputs[out.name] = art.to_dict()
    return outputs


def _stamp_model_digest(art: Artifact) -> None:
    """Record the written payload's sha256 in the artifact metadata (the
    launcher-side half of model governance): the registry can verify its
    ingest against the hash computed where the bytes were produced, and
    a serving fetch can pin it. Single-file payloads only — directory
    digests are manifest-shaped and belong to the registry."""
    try:
        path = art.path
    except ValueError:
        return  # non-local uri: the producing side cannot hash it
    if os.path.isfile(path):
        import hashlib

        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        art.metadata.setdefault("sha256", h.hexdigest())


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="kft-executor")
    ap.add_argument("--workdir", required=True)
    ns = ap.parse_args(argv)
    with open(os.path.join(ns.workdir, "task.json")) as f:
        task = json.load(f)
    # component-declared env applies to this process only (the in-process
    # fast path must not mutate the runner's environment)
    component = ComponentIR.from_dict(task["component"])
    for k, v in dict(component.base_env).items():
        os.environ.setdefault(k, v)
    # Multi-worker gangs: every rank executes the fn (SPMD steps need all
    # participants for collectives), but only rank 0 publishes
    # outputs.json — the others would race the same workdir. Components
    # writing artifact files from a gang must follow the same
    # rank-0-writes convention.
    rank = int(os.environ.get("JAX_PROCESS_ID", "0"))
    try:
        outputs = execute(task)
    except Exception:
        suffix = "" if rank == 0 else f"-{rank}"
        with open(os.path.join(ns.workdir, f"error{suffix}.txt"), "w") as f:
            f.write(traceback.format_exc())
        return 1
    if rank == 0:
        tmp = os.path.join(ns.workdir, "outputs.json.tmp")
        with open(tmp, "w") as f:
            json.dump(outputs, f, default=str)
        os.replace(tmp, os.path.join(ns.workdir, "outputs.json"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
