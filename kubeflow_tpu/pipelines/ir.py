"""Pipeline IR: the PipelineSpec analog.

Reference analog (SURVEY.md §2.4): KFP compiles the DSL to a
PipelineSpec protobuf ([pipelines] api/v2alpha1/pipeline_spec.proto —
UNVERIFIED, SURVEY.md §0) serialized as YAML; golden-file tests diff
compiled IR (§4 "Compiler golden tests").

This IR is plain dataclasses with a canonical, deterministic
``to_dict()`` (sorted keys, stable task ordering) so golden tests can
diff JSON. Input references use the KFP-style discriminated union:
a constant, a pipeline parameter, or an upstream task output.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Mapping


@dataclasses.dataclass(frozen=True)
class InputRef:
    """Where a task input comes from: exactly one of the fields is set."""

    constant: Any = None
    parameter: str | None = None        # pipeline-level parameter name
    task_output: tuple[str, str] | None = None  # (task_name, output_name)

    def to_dict(self) -> dict:
        if self.task_output is not None:
            return {"taskOutput": {"task": self.task_output[0],
                                   "output": self.task_output[1]}}
        if self.parameter is not None:
            return {"parameter": self.parameter}
        return {"constant": self.constant}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "InputRef":
        if "taskOutput" in d:
            t = d["taskOutput"]
            return cls(task_output=(t["task"], t["output"]))
        if "parameter" in d:
            return cls(parameter=d["parameter"])
        return cls(constant=d.get("constant"))


@dataclasses.dataclass(frozen=True)
class OutputSpec:
    name: str
    kind: str = "parameter"          # "parameter" | artifact TYPE string

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind}


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """Accelerator request — the `set_gpu_limit`/node-selector surface
    re-targeted to TPU (SURVEY.md §2.4 row 1)."""

    tpu_chips: int = 0
    topology: str = ""               # e.g. "2x4"
    num_workers: int = 1
    cpu_millis: int = 0
    memory_mb: int = 0

    @property
    def wants_job(self) -> bool:
        return self.tpu_chips > 0 or self.num_workers > 1

    def to_dict(self) -> dict:
        return {
            "tpuChips": self.tpu_chips,
            "topology": self.topology,
            "numWorkers": self.num_workers,
            "cpuMillis": self.cpu_millis,
            "memoryMb": self.memory_mb,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ResourceSpec":
        return cls(
            tpu_chips=d.get("tpuChips", 0),
            topology=d.get("topology", ""),
            num_workers=d.get("numWorkers", 1),
            cpu_millis=d.get("cpuMillis", 0),
            memory_mb=d.get("memoryMb", 0),
        )


@dataclasses.dataclass(frozen=True)
class ComponentIR:
    """Reusable component definition: the executable contract."""

    name: str
    source: str                      # python source of the user function
    fn_name: str
    inputs: tuple[str, ...] = ()
    input_kinds: tuple[tuple[str, str], ...] = ()  # name → "parameter"|artifact TYPE
    outputs: tuple[OutputSpec, ...] = ()
    base_env: tuple[tuple[str, str], ...] = ()

    def fingerprint(self) -> str:
        """Stable digest of the executable contract — the cache key half
        that the KFP cache server computes from the component spec."""
        payload = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "fnName": self.fn_name,
            "source": self.source,
            "inputs": list(self.inputs),
            "inputKinds": {k: v for k, v in self.input_kinds},
            "outputs": [o.to_dict() for o in self.outputs],
            "env": {k: v for k, v in self.base_env},
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ComponentIR":
        return cls(
            name=d["name"],
            source=d["source"],
            fn_name=d["fnName"],
            inputs=tuple(d.get("inputs", ())),
            input_kinds=tuple(sorted(d.get("inputKinds", {}).items())),
            outputs=tuple(
                OutputSpec(o["name"], o.get("kind", "parameter"))
                for o in d.get("outputs", ())
            ),
            base_env=tuple(sorted(d.get("env", {}).items())),
        )


@dataclasses.dataclass(frozen=True)
class TaskIR:
    """One DAG node: a component invocation with wired inputs."""

    name: str
    component: str                   # ComponentIR name
    inputs: tuple[tuple[str, InputRef], ...] = ()
    after: tuple[str, ...] = ()      # explicit ordering deps (dsl .after())
    resources: ResourceSpec = ResourceSpec()
    cache_enabled: bool = True
    retries: int = 0

    def deps(self) -> set[str]:
        data = {
            ref.task_output[0]
            for _, ref in self.inputs
            if ref.task_output is not None
        }
        return data | set(self.after)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "component": self.component,
            "inputs": {k: ref.to_dict() for k, ref in self.inputs},
            "after": sorted(self.after),
            "resources": self.resources.to_dict(),
            "cacheEnabled": self.cache_enabled,
            "retries": self.retries,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "TaskIR":
        return cls(
            name=d["name"],
            component=d["component"],
            inputs=tuple(
                (k, InputRef.from_dict(v))
                for k, v in sorted(d.get("inputs", {}).items())
            ),
            after=tuple(d.get("after", ())),
            resources=ResourceSpec.from_dict(d.get("resources", {})),
            cache_enabled=d.get("cacheEnabled", True),
            retries=d.get("retries", 0),
        )


@dataclasses.dataclass(frozen=True)
class PipelineIR:
    name: str
    components: tuple[ComponentIR, ...]
    tasks: tuple[TaskIR, ...]
    parameters: tuple[tuple[str, Any], ...] = ()   # name → default
    description: str = ""

    def component(self, name: str) -> ComponentIR:
        for c in self.components:
            if c.name == name:
                return c
        raise KeyError(f"component {name!r} not in pipeline {self.name!r}")

    def task(self, name: str) -> TaskIR:
        for t in self.tasks:
            if t.name == name:
                return t
        raise KeyError(f"task {name!r} not in pipeline {self.name!r}")

    def topological_order(self) -> list[list[str]]:
        """Kahn's algorithm into ready-waves; raises on cycles."""
        deps = {t.name: set(t.deps()) for t in self.tasks}
        known = set(deps)
        for t, ds in deps.items():
            missing = ds - known
            if missing:
                raise ValueError(f"task {t!r} depends on unknown {missing}")
        waves: list[list[str]] = []
        done: set[str] = set()
        while len(done) < len(deps):
            ready = sorted(
                t for t, ds in deps.items() if t not in done and ds <= done
            )
            if not ready:
                rest = sorted(set(deps) - done)
                raise ValueError(f"cycle among tasks {rest}")
            waves.append(ready)
            done.update(ready)
        return waves

    def to_dict(self) -> dict:
        return {
            "schemaVersion": "kft/v1",
            "name": self.name,
            "description": self.description,
            "parameters": {k: v for k, v in self.parameters},
            "components": [
                c.to_dict() for c in sorted(self.components, key=lambda c: c.name)
            ],
            "tasks": [t.to_dict() for t in self.tasks],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, indent=2)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "PipelineIR":
        return cls(
            name=d["name"],
            description=d.get("description", ""),
            parameters=tuple(sorted(d.get("parameters", {}).items())),
            components=tuple(
                ComponentIR.from_dict(c) for c in d.get("components", ())
            ),
            tasks=tuple(TaskIR.from_dict(t) for t in d.get("tasks", ())),
        )

    @classmethod
    def from_json(cls, s: str) -> "PipelineIR":
        return cls.from_dict(json.loads(s))
