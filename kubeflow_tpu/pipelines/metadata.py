"""Lineage store: the MLMD equivalent.

Reference analog (SURVEY.md §2.4 "Metadata (MLMD)"): ml-metadata (C++
gRPC service over MySQL) records executions, artifacts, and events so
runs are queryable by lineage. Per SURVEY.md §2.8, C++ is not
perf-critical here — this is a sqlite-backed store with the same data
model: executions ←events→ artifacts, contexts (runs) grouping both.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Any

_SCHEMA = """
CREATE TABLE IF NOT EXISTS executions (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id TEXT NOT NULL,
    task TEXT NOT NULL,
    component TEXT NOT NULL,
    state TEXT NOT NULL,
    cache_hit INTEGER NOT NULL DEFAULT 0,
    started REAL NOT NULL,
    finished REAL,
    error TEXT
);
CREATE TABLE IF NOT EXISTS artifacts (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    uri TEXT NOT NULL,
    type TEXT NOT NULL,
    name TEXT NOT NULL,
    metadata TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS events (
    execution_id INTEGER NOT NULL REFERENCES executions(id),
    artifact_id INTEGER NOT NULL REFERENCES artifacts(id),
    direction TEXT NOT NULL CHECK (direction IN ('input','output'))
);
CREATE INDEX IF NOT EXISTS idx_exec_run ON executions(run_id);
CREATE INDEX IF NOT EXISTS idx_art_uri ON artifacts(uri);
"""


class LineageStore:
    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.executescript(_SCHEMA)
        self._lock = threading.Lock()

    # -- write path ---------------------------------------------------- #

    def begin_execution(self, run_id: str, task: str, component: str) -> int:
        with self._lock:
            cur = self._db.execute(
                "INSERT INTO executions (run_id, task, component, state, started)"
                " VALUES (?,?,?,?,?)",
                (run_id, task, component, "RUNNING", time.time()),
            )
            self._db.commit()
            return cur.lastrowid

    def finish_execution(self, exec_id: int, *, state: str,
                         cache_hit: bool = False, error: str = "") -> None:
        with self._lock:
            self._db.execute(
                "UPDATE executions SET state=?, cache_hit=?, finished=?, error=?"
                " WHERE id=?",
                (state, int(cache_hit), time.time(), error or None, exec_id),
            )
            self._db.commit()

    def record_artifact(self, exec_id: int, *, uri: str, type_: str,
                        name: str, direction: str,
                        metadata: dict[str, Any] | None = None) -> int:
        with self._lock:
            row = self._db.execute(
                "SELECT id FROM artifacts WHERE uri=? AND name=?", (uri, name)
            ).fetchone()
            if row:
                art_id = row[0]
            else:
                art_id = self._db.execute(
                    "INSERT INTO artifacts (uri, type, name, metadata)"
                    " VALUES (?,?,?,?)",
                    (uri, type_, name, json.dumps(metadata or {})),
                ).lastrowid
            self._db.execute(
                "INSERT INTO events (execution_id, artifact_id, direction)"
                " VALUES (?,?,?)",
                (exec_id, art_id, direction),
            )
            self._db.commit()
            return art_id

    # -- query path ---------------------------------------------------- #

    def runs(self) -> list[dict]:
        """All pipeline runs with task-state rollups (the frontend's run
        list — SURVEY.md §2.4 Frontend row)."""
        with self._lock:
            rows = self._db.execute(
                # case-insensitive: the runner writes 'SUCCEEDED'/'FAILED'
                "SELECT run_id, COUNT(*),"
                " SUM(UPPER(state)='SUCCEEDED'), SUM(UPPER(state)='FAILED'),"
                " SUM(cache_hit), MIN(started), MAX(finished)"
                " FROM executions GROUP BY run_id ORDER BY MIN(started) DESC"
            ).fetchall()
        out = []
        for run_id, total, ok, failed, cached, started, finished in rows:
            state = (
                "Failed" if failed else
                "Succeeded" if ok == total else "Running"
            )
            out.append(
                {
                    "run_id": run_id,
                    "state": state,
                    "tasks": total,
                    "succeeded": ok or 0,
                    "failed": failed or 0,
                    "cache_hits": cached or 0,
                    "started": started,
                    "finished": finished,
                }
            )
        return out

    def executions(self, run_id: str) -> list[dict]:
        with self._lock:
            rows = self._db.execute(
                "SELECT id, task, component, state, cache_hit, started,"
                " finished, error FROM executions WHERE run_id=? ORDER BY id",
                (run_id,),
            ).fetchall()
        keys = ("id", "task", "component", "state", "cache_hit", "started",
                "finished", "error")
        return [dict(zip(keys, r)) for r in rows]

    def artifacts_of(self, exec_id: int, direction: str | None = None) -> list[dict]:
        q = ("SELECT a.id, a.uri, a.type, a.name, a.metadata, e.direction"
             " FROM artifacts a JOIN events e ON a.id = e.artifact_id"
             " WHERE e.execution_id=?")
        args: tuple = (exec_id,)
        if direction:
            q += " AND e.direction=?"
            args = (exec_id, direction)
        with self._lock:
            rows = self._db.execute(q, args).fetchall()
        return [
            {"id": r[0], "uri": r[1], "type": r[2], "name": r[3],
             "metadata": json.loads(r[4]), "direction": r[5]}
            for r in rows
        ]

    def lineage(self, uri: str) -> list[dict]:
        """All executions that produced or consumed an artifact uri."""
        with self._lock:
            rows = self._db.execute(
                "SELECT DISTINCT x.id, x.run_id, x.task, x.component,"
                " x.state, e.direction"
                " FROM executions x JOIN events e ON x.id = e.execution_id"
                " JOIN artifacts a ON a.id = e.artifact_id WHERE a.uri=?"
                " ORDER BY x.id",
                (uri,),
            ).fetchall()
        keys = ("id", "run_id", "task", "component", "state", "direction")
        return [dict(zip(keys, r)) for r in rows]

    def close(self) -> None:
        self._db.close()
