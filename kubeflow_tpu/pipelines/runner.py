"""Pipeline DAG runner: the Argo-workflow + driver analog.

Reference analog (SURVEY.md §2.4, §3.5): the API server turns
PipelineSpec into an Argo Workflow; per node a driver pod resolves
inputs/parameters and checks the MLMD cache, then a launcher executes
the component ([pipelines] backend/src/apiserver/, backend/src/v2/driver/
— UNVERIFIED, SURVEY.md §0).

Here one in-process scheduler plays Argo: tasks are submitted to a
thread pool the moment their dependencies complete (no wave barriers).
The driver role (resolve → cache check → lineage) runs inline; the
launcher role is either in-process `executor.execute` or — when a task
requests TPU chips / multiple workers — a JAXJob through the
orchestrator, per the §3.5 "step creates a JAXJob" mapping.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import sys
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from kubeflow_tpu.pipelines.artifacts import Artifact, ArtifactStore
from kubeflow_tpu.pipelines.cache import StepCache, cache_key
from kubeflow_tpu.pipelines import executor as _executor
from kubeflow_tpu.pipelines.ir import PipelineIR, TaskIR
from kubeflow_tpu.pipelines.metadata import LineageStore

logger = logging.getLogger(__name__)

SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
SKIPPED = "SKIPPED"          # upstream failed
RUNNING = "RUNNING"
PENDING = "PENDING"


@dataclasses.dataclass
class TaskResult:
    state: str = PENDING
    outputs: dict[str, Any] = dataclasses.field(default_factory=dict)
    cache_hit: bool = False
    error: str = ""
    attempts: int = 0


@dataclasses.dataclass
class RunResult:
    run_id: str
    pipeline: str
    state: str
    tasks: dict[str, TaskResult]
    wall_s: float

    def output(self, task: str, name: str | None = None) -> Any:
        tr = self.tasks[task]
        if name is None:
            if len(tr.outputs) != 1:
                raise ValueError(f"task {task!r} has {len(tr.outputs)} outputs")
            name = next(iter(tr.outputs))
        raw = tr.outputs[name]
        if isinstance(raw, dict) and "value" in raw and "uri" not in raw:
            return raw["value"]
        return Artifact.from_dict(raw)


def resolve_parameters(
    ir: PipelineIR, parameters: dict[str, Any] | None
) -> dict[str, Any]:
    """Merge caller parameters over pipeline defaults, failing fast on
    unknown names and on REQUIRED parameters left unset. Shared by the
    in-process runner and the REST API so a bad request is rejected at
    submit time, not inside the run thread."""
    from kubeflow_tpu.pipelines.dsl import REQUIRED

    params = {name: default for name, default in ir.parameters}
    for k, v in (parameters or {}).items():
        if k not in params:
            raise KeyError(f"unknown pipeline parameter {k!r}")
        params[k] = v
    missing = [k for k, v in params.items()
               if isinstance(v, str) and v == REQUIRED]
    if missing:
        raise ValueError(f"pipeline parameters without values: {missing}")
    return params


class PipelineRunner:
    def __init__(
        self,
        *,
        artifact_store: ArtifactStore,
        cache: StepCache | None = None,
        lineage: LineageStore | None = None,
        cluster: Any | None = None,       # orchestrator LocalCluster, for TPU steps
        model_registry: Any | None = None,  # registry.store.ModelStore
        max_parallel: int = 8,
        job_timeout_s: float = 600.0,
    ):
        self.store = artifact_store
        self.cache = cache
        self.lineage = lineage or LineageStore()
        self.cluster = cluster
        self.model_registry = model_registry
        self.max_parallel = max_parallel
        self.job_timeout_s = job_timeout_s

    # ------------------------------------------------------------------ #

    def run(self, ir: PipelineIR, parameters: dict[str, Any] | None = None,
            *, run_id: str | None = None,
            live_tasks: dict[str, TaskResult] | None = None) -> RunResult:
        """``live_tasks`` (optional): filled with the per-task TaskResult
        objects AS THE RUN STARTS and mutated in place while it executes —
        the REST API's GET /runs/{id} reads task states from it mid-run."""
        t0 = time.monotonic()
        run_id = run_id or uuid.uuid4().hex[:12]
        params = resolve_parameters(ir, parameters)

        ir.topological_order()            # validate DAG up front
        results = {t.name: TaskResult() for t in ir.tasks}
        if live_tasks is not None:
            live_tasks.update(results)
        remaining = {t.name: set(t.deps()) for t in ir.tasks}
        dependents: dict[str, list[str]] = {t.name: [] for t in ir.tasks}
        for t in ir.tasks:
            for d in t.deps():
                dependents[d].append(t.name)

        lock = threading.Lock()
        done_cv = threading.Condition(lock)
        scheduled: set[str] = set()

        def finish(name: str, pool: ThreadPoolExecutor) -> None:
            newly_ready: list[str] = []
            with lock:
                res = results[name]
                for dep_name in dependents[name]:
                    if res.state != SUCCEEDED:
                        if results[dep_name].state == PENDING:
                            results[dep_name].state = SKIPPED
                            results[dep_name].error = f"upstream {name!r} {res.state}"
                            newly_ready.append(dep_name)   # propagate skip
                        continue
                    remaining[dep_name].discard(name)
                    if (not remaining[dep_name]
                            and results[dep_name].state == PENDING
                            and dep_name not in scheduled):
                        scheduled.add(dep_name)
                        newly_ready.append(dep_name)
                done_cv.notify_all()
            for dep_name in newly_ready:
                submit(dep_name, pool)

        def submit(name: str, pool: ThreadPoolExecutor) -> None:
            with lock:
                res = results[name]
                if res.state == SKIPPED:
                    # terminal already; recurse only to propagate the skip
                    pass
                elif res.state != PENDING:
                    return
                else:
                    res.state = RUNNING
            if results[name].state == RUNNING:
                pool.submit(self._run_task_safely, ir, ir.task(name), params,
                            results, run_id, lambda: finish(name, pool))
            else:
                finish(name, pool)

        roots = [t.name for t in ir.tasks if not t.deps()]
        with ThreadPoolExecutor(max_workers=self.max_parallel) as pool:
            for r in roots:
                submit(r, pool)
            with lock:
                while any(r.state in (PENDING, RUNNING)
                          for r in results.values()):
                    done_cv.wait(timeout=0.5)

        state = (SUCCEEDED if all(r.state == SUCCEEDED
                                  for r in results.values()) else FAILED)
        return RunResult(run_id=run_id, pipeline=ir.name, state=state,
                         tasks=results, wall_s=time.monotonic() - t0)

    # ------------------------------------------------------------------ #

    def _run_task_safely(self, ir, task, params, results, run_id, done_cb):
        try:
            self._run_task(ir, task, params, results, run_id)
        except Exception as e:       # driver-level failure
            logger.exception("task %s driver error", task.name)
            results[task.name].state = FAILED
            results[task.name].error = f"{type(e).__name__}: {e}"
        finally:
            done_cb()

    def _run_task(self, ir: PipelineIR, task: TaskIR,
                  params: dict[str, Any], results: dict[str, TaskResult],
                  run_id: str) -> None:
        component = ir.component(task.component)
        res = results[task.name]

        # -- driver: resolve inputs ------------------------------------ #
        kinds = dict(component.input_kinds)
        inputs: dict[str, Any] = {}
        for name, ref in task.inputs:
            if ref.task_output is not None:
                up_task, up_out = ref.task_output
                raw = results[up_task].outputs[up_out]
                inputs[name] = (raw["value"]
                                if isinstance(raw, dict) and "value" in raw
                                and "uri" not in raw else raw)
            elif ref.parameter is not None:
                inputs[name] = params[ref.parameter]
            else:
                inputs[name] = ref.constant
        for name in component.inputs:
            if name not in inputs:
                raise ValueError(
                    f"task {task.name!r}: input {name!r} not wired")

        # -- driver: cache check --------------------------------------- #
        key = cache_key(component, inputs)
        exec_id = self.lineage.begin_execution(run_id, task.name, component.name)
        if task.cache_enabled and self.cache is not None:
            cached = self.cache.lookup(key)
            if cached is not None:
                res.outputs = cached
                res.cache_hit = True
                res.state = SUCCEEDED
                self._record_artifacts(exec_id, kinds, inputs, cached)
                self._register_model_outputs(ir, task, run_id, cached,
                                             cache_hit=True)
                self.lineage.finish_execution(exec_id, state=SUCCEEDED,
                                              cache_hit=True)
                return

        # -- launcher -------------------------------------------------- #
        output_uris = {
            o.name: self.store.uri_for(ir.name, run_id, task.name, o.name)
            for o in component.outputs if o.kind != "parameter"
        }
        payload = {
            "component": component.to_dict(),
            "inputs": inputs,
            "output_uris": output_uris,
        }
        last_err = ""
        for attempt in range(task.retries + 1):
            res.attempts = attempt + 1
            try:
                if task.resources.wants_job and self.cluster is not None:
                    outputs = self._execute_as_job(ir, task, payload, run_id,
                                                   attempt)
                else:
                    outputs = _executor.execute(payload)
                res.outputs = outputs
                res.state = SUCCEEDED
                if task.cache_enabled and self.cache is not None:
                    self.cache.record(key, outputs)
                self._record_artifacts(exec_id, kinds, inputs, outputs)
                self._register_model_outputs(ir, task, run_id, outputs,
                                             cache_hit=False)
                self.lineage.finish_execution(exec_id, state=SUCCEEDED)
                return
            except Exception as e:
                last_err = f"{type(e).__name__}: {e}"
                logger.warning("task %s attempt %d failed: %s",
                               task.name, attempt + 1, last_err)
        res.state = FAILED
        res.error = last_err
        self.lineage.finish_execution(exec_id, state=FAILED, error=last_err)

    def _execute_as_job(self, ir: PipelineIR, task: TaskIR, payload: dict,
                        run_id: str, attempt: int) -> dict:
        """§3.5 mapping: a TPU/multi-worker step becomes a JAXJob gang."""
        from kubeflow_tpu.orchestrator.spec import (
            JobSpec, ReplicaSpec, RunPolicy, TPURequest,
        )
        workdir = os.path.join(self.store.root, ir.name, run_id,
                               task.name, f".exec-{attempt}")
        os.makedirs(workdir, exist_ok=True)
        with open(os.path.join(workdir, "task.json"), "w") as f:
            json.dump(payload, f, default=str)
        r = task.resources
        # the executor module must be importable from the job's workdir
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        pypath = os.environ.get("PYTHONPATH", "")
        env = {"PYTHONPATH": (pkg_root + os.pathsep + pypath).rstrip(os.pathsep)}
        spec = JobSpec(
            name=f"pipe-{run_id}-{task.name}"[:60],
            labels={"pipeline": ir.name, "run": run_id, "task": task.name},
            replicas={
                "worker": ReplicaSpec(
                    replicas=r.num_workers,
                    command=(sys.executable, "-m",
                             "kubeflow_tpu.pipelines.executor",
                             "--workdir", workdir),
                    env=env,
                    tpu=TPURequest(chips=r.tpu_chips,
                                   topology=r.topology or None),
                )
            },
            run_policy=RunPolicy(backoff_limit=0),
        )
        uid = self.cluster.submit(spec)
        status = self.cluster.wait(uid, timeout=self.job_timeout_s)
        if not status.finished or status.phase != "Succeeded":
            err_path = os.path.join(workdir, "error.txt")
            detail = ""
            if os.path.exists(err_path):
                with open(err_path) as f:
                    detail = f.read()[-2000:]
            raise RuntimeError(
                f"step job {spec.name} phase={status.phase}: {detail}")
        with open(os.path.join(workdir, "outputs.json")) as f:
            return json.load(f)

    def _register_model_outputs(self, ir: PipelineIR, task: TaskIR,
                                run_id: str, outputs: dict,
                                *, cache_hit: bool) -> None:
        """Auto-register declared ``system.Model`` outputs into the model
        registry with run lineage (the KFP → model-registry handoff).
        Components pick the registered name with
        ``model.metadata["register_as"]``; the default is
        ``<pipeline>/<output-name>``. Registration is bookkeeping — a
        registry failure logs, it does not fail the run."""
        if self.model_registry is None:
            return
        for name, v in outputs.items():
            if not (isinstance(v, dict) and v.get("type") == "system.Model"
                    and v.get("uri")):
                continue
            uri = v["uri"]
            local = uri[len("file://"):] if uri.startswith("file://") else uri
            if "://" in local or not os.path.exists(local):
                continue  # remote or never-written output — nothing to ingest
            meta = dict(v.get("metadata") or {})
            reg_name = meta.pop("register_as", None) or f"{ir.name}/{name}"
            try:
                self.model_registry.register_version(
                    reg_name,
                    local,
                    source_uri=uri,
                    metadata={**meta, "pipeline": ir.name, "task": task.name,
                              "cache_hit": cache_hit},
                    lineage=[(
                        "pipeline_run",
                        run_id,
                        {"pipeline": ir.name, "task": task.name,
                         "output": name, "cache_hit": cache_hit},
                    )],
                )
            except Exception:
                logger.exception(
                    "registry: failed to register %s output %s of run %s",
                    task.name, name, run_id,
                )

    def _record_artifacts(self, exec_id: int, kinds: dict,
                          inputs: dict, outputs: dict) -> None:
        for name, v in inputs.items():
            if kinds.get(name, "parameter") != "parameter" and isinstance(v, dict):
                self.lineage.record_artifact(
                    exec_id, uri=v.get("uri", ""), type_=v.get("type", ""),
                    name=name, direction="input",
                    metadata=v.get("metadata", {}))
        for name, v in outputs.items():
            if isinstance(v, dict) and "uri" in v:
                self.lineage.record_artifact(
                    exec_id, uri=v["uri"], type_=v.get("type", ""),
                    name=name, direction="output",
                    metadata=v.get("metadata", {}))
