"""Recurring runs: the ScheduledWorkflow controller analog.

Reference analog (SURVEY.md §2.4 "ScheduledWorkflow controller"):
a CRD controller that fires pipeline runs on a cron/interval schedule
([pipelines] backend/src/crd/controller/scheduledworkflow/ —
UNVERIFIED, SURVEY.md §0). Semantics kept: interval trigger, max
concurrency 1 per schedule (no overlapping runs), pause/resume,
run-history cap, catch-up disabled (missed ticks collapse into one).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import uuid
from typing import Any, Callable

from kubeflow_tpu.pipelines.ir import PipelineIR
from kubeflow_tpu.pipelines.runner import PipelineRunner, RunResult

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RecurringRun:
    pipeline: PipelineIR
    interval_s: float
    parameters: dict[str, Any] = dataclasses.field(default_factory=dict)
    max_runs: int | None = None          # stop after N fires (None = forever)
    name: str = ""
    uid: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:8])
    paused: bool = False
    # status
    fired: int = 0
    history: list[RunResult] = dataclasses.field(default_factory=list)
    next_at: float = 0.0
    running: bool = False      # overlap guard (maxConcurrency 1 per schedule)

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.name = self.name or f"{self.pipeline.name}-recurring"


class RunScheduler:
    """One background thread watches the clock; each due schedule fires
    on its own worker thread, so a slow run never starves other
    schedules. Overlapping fires of the SAME schedule are suppressed
    (the reference default `maxConcurrency: 1`)."""

    def __init__(self, runner: PipelineRunner,
                 on_result: Callable[[RecurringRun, RunResult], None] | None = None,
                 history_cap: int = 20):
        self.runner = runner
        self.on_result = on_result
        self.history_cap = history_cap
        self._schedules: dict[str, RecurringRun] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------ #

    def add(self, rr: RecurringRun) -> str:
        with self._lock:
            rr.next_at = time.monotonic() + rr.interval_s
            self._schedules[rr.uid] = rr
        self._wake.set()
        return rr.uid

    def pause(self, uid: str) -> None:
        with self._lock:
            self._schedules[uid].paused = True

    def resume(self, uid: str) -> None:
        with self._lock:
            rr = self._schedules[uid]
            rr.paused = False
            # missed ticks collapse: next fire is one interval from now
            rr.next_at = time.monotonic() + rr.interval_s
        self._wake.set()

    def remove(self, uid: str) -> None:
        with self._lock:
            self._schedules.pop(uid, None)

    def get(self, uid: str) -> RecurringRun:
        with self._lock:
            return self._schedules[uid]

    def list(self) -> list[RecurringRun]:
        with self._lock:
            return list(self._schedules.values())

    # ------------------------------------------------------------------ #

    def start(self) -> "RunScheduler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="kft-run-scheduler")
        self._thread.start()
        return self

    def shutdown(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout)

    def __enter__(self) -> "RunScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def _loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            due: list[RecurringRun] = []
            with self._lock:
                wait = 3600.0
                for rr in self._schedules.values():
                    if rr.paused or rr.running or (
                            rr.max_runs is not None
                            and rr.fired >= rr.max_runs):
                        continue
                    if rr.next_at <= now:
                        rr.running = True          # claim before spawning
                        rr.fired += 1
                        rr.next_at = now + rr.interval_s   # no catch-up
                        due.append(rr)
                    else:
                        wait = min(wait, rr.next_at - now)
            for rr in due:
                threading.Thread(target=self._fire, args=(rr,), daemon=True,
                                 name=f"kft-fire-{rr.name}").start()
            if not due:
                self._wake.wait(timeout=wait)
                self._wake.clear()

    def _fire(self, rr: RecurringRun) -> None:
        try:
            result = self.runner.run(rr.pipeline, rr.parameters)
        except Exception:
            logger.exception("recurring run %s fire %d crashed", rr.name, rr.fired)
            return
        finally:
            with self._lock:
                rr.running = False
            self._wake.set()    # re-evaluate: next fire may already be due
        rr.history.append(result)
        del rr.history[:-self.history_cap]
        if self.on_result:
            self.on_result(rr, result)
