"""Pipelines plane: the KFP equivalent, TPU/local-native.

Reference analog (SURVEY.md §2.4, [pipelines] repo — UNVERIFIED, mount
empty §0): `@dsl.component` / `@dsl.pipeline` → PipelineSpec IR → Argo
Workflow execution with driver/launcher pods, MLMD lineage, MinIO
artifacts, cache server, ScheduledWorkflow controller.

Here: decorators trace a Python pipeline function into a deterministic
DAG IR; a DAG executor runs components either in-process or as JAXJobs
through the orchestrator (the §3.5 "step creates a JAXJob" mapping);
artifacts live in a local content-addressed store; the step cache and
lineage store replace the cache server and MLMD.
"""

from kubeflow_tpu.pipelines.artifacts import (
    Artifact,
    ArtifactStore,
    Dataset,
    Metrics,
    Model,
)
from kubeflow_tpu.pipelines.api import PipelineAPIServer
from kubeflow_tpu.pipelines.cache import StepCache
from kubeflow_tpu.pipelines.compiler import compile_pipeline
from kubeflow_tpu.pipelines.dsl import Input, Output, component, pipeline
from kubeflow_tpu.pipelines.ir import ComponentIR, PipelineIR, TaskIR
from kubeflow_tpu.pipelines.metadata import LineageStore
from kubeflow_tpu.pipelines.runner import PipelineRunner, RunResult
from kubeflow_tpu.pipelines.scheduler import RecurringRun, RunScheduler

__all__ = [
    "Artifact",
    "ArtifactStore",
    "ComponentIR",
    "Dataset",
    "Input",
    "LineageStore",
    "Metrics",
    "Model",
    "Output",
    "PipelineAPIServer",
    "PipelineIR",
    "PipelineRunner",
    "RecurringRun",
    "RunResult",
    "RunScheduler",
    "StepCache",
    "TaskIR",
    "component",
    "compile_pipeline",
    "pipeline",
]
