"""DSL → IR compiler.

Reference analog (SURVEY.md §2.4 row 1): `Compiler.compile()` traces the
pipeline function and emits PipelineSpec YAML ([pipelines]
sdk/python/kfp/compiler/compiler.py — UNVERIFIED, SURVEY.md §0). Golden
tests diff the emitted IR (§4).
"""

from __future__ import annotations

from typing import Any

from kubeflow_tpu.pipelines.dsl import (
    Pipeline,
    PipelineParam,
    Task,
    TaskOutput,
    _TraceContext,
)
from kubeflow_tpu.pipelines.ir import InputRef, PipelineIR, TaskIR


def _to_ref(value: Any) -> InputRef:
    if isinstance(value, TaskOutput):
        return value.ref()
    if isinstance(value, PipelineParam):
        return value.ref()
    if isinstance(value, Task):
        raise TypeError(
            f"task {value.name!r} passed as an input — pass `.output` "
            "or `.outputs[name]` instead"
        )
    return InputRef(constant=value)


def compile_pipeline(p: Pipeline) -> PipelineIR:
    if not isinstance(p, Pipeline):
        raise TypeError("compile_pipeline() takes a @pipeline-decorated object")
    ctx = _TraceContext()
    prev, _TraceContext.current = _TraceContext.current, ctx
    try:
        p.fn(*[PipelineParam(name) for name, _ in p.parameters])
    finally:
        _TraceContext.current = prev

    tasks = tuple(
        TaskIR(
            name=t.name,
            component=t.component.ir.name,
            inputs=tuple(sorted(
                (k, _to_ref(v)) for k, v in t.inputs.items()
            )),
            after=tuple(sorted(set(t._after))),
            resources=t.resources,
            cache_enabled=t.cache_enabled,
            retries=t.retries,
        )
        for t in ctx.tasks
    )
    ir = PipelineIR(
        name=p.name,
        description=p.description,
        parameters=tuple(p.parameters),
        components=tuple(ctx.components.values()),
        tasks=tasks,
    )
    ir.topological_order()   # validate: unknown deps / cycles fail at compile
    return ir
