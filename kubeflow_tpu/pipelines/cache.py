"""Step cache: skip a task when the same (component, inputs) ran before.

Reference analog (SURVEY.md §2.4 "Cache server", §5.4): KFP's cache
webhook matches the component spec + resolved inputs fingerprint against
MLMD and short-circuits execution, reusing recorded outputs
([pipelines] backend/src/cache/ — UNVERIFIED, SURVEY.md §0).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any

from kubeflow_tpu.pipelines.ir import ComponentIR


def cache_key(component: ComponentIR, resolved_inputs: dict[str, Any]) -> str:
    """Digest of the executable contract + the concrete input values.

    Artifact inputs contribute their uri + metadata (content identity is
    run-scoped uris, so a re-produced artifact at a new uri is a miss —
    same conservative behavior as the reference's fingerprinting).
    """
    payload = json.dumps(
        {"component": component.fingerprint(), "inputs": resolved_inputs},
        sort_keys=True, default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


class StepCache:
    """File-backed key → recorded outputs map (one JSON per entry)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ".json")

    def lookup(self, key: str) -> dict | None:
        with self._lock:
            path = self._path(key)
            if not os.path.exists(path):
                self.misses += 1
                return None
            with open(path) as f:
                entry = json.load(f)
            # stale entry: a recorded file:// output was GC'd
            for uri in entry.get("artifact_uris", []):
                if uri.startswith("file://") and not os.path.exists(uri[7:]):
                    self.misses += 1
                    return None
            self.hits += 1
            return entry["outputs"]

    def record(self, key: str, outputs: dict) -> None:
        # stale-check only artifacts that were actually materialized —
        # metadata-only artifacts (e.g. Metrics) have no file to GC
        uris = [
            o["uri"] for o in outputs.values()
            if isinstance(o, dict) and "uri" in o
            and o["uri"].startswith("file://")
            and os.path.exists(o["uri"][7:])
        ]
        with self._lock:
            tmp = self._path(key) + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"outputs": outputs, "artifact_uris": uris}, f)
            os.replace(tmp, self._path(key))

    def clear(self) -> None:
        with self._lock:
            for name in os.listdir(self.root):
                if name.endswith(".json"):
                    os.unlink(os.path.join(self.root, name))
            self.hits = self.misses = 0
