"""Pipelines REST API: upload pipelines, create/watch runs, recurring CRUD.

Reference analog (SURVEY.md §2.4 "API server / resource manager" row): the
KFP API server's REST surface — UploadPipeline, CreateRun, GetRun,
ListRuns, recurring-run CRUD ([pipelines] backend/src/apiserver/ —
UNVERIFIED, mount empty, SURVEY.md §0). The reference fronts a MySQL
resource manager and compiles to Argo; here the resource manager IS the
in-process ``PipelineRunner`` + ``RunScheduler``, and the wire format is
the canonical ``PipelineIR`` JSON the compiler emits (``kft pipeline
compile``), so upload → create-run → poll → artifact lineage all ride one
spec format end to end.

Route shapes follow the KFP v2beta1 naming so a reference user's muscle
memory transfers: ``/apis/v2beta1/pipelines``, ``/apis/v2beta1/runs``,
``/apis/v2beta1/recurringruns``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from kubeflow_tpu.obs.webhost import ThreadedAiohttpServer
from kubeflow_tpu.pipelines.ir import PipelineIR
from kubeflow_tpu.pipelines.runner import (
    FAILED,
    PENDING,
    RUNNING,
    PipelineRunner,
    RunResult,
    TaskResult,
    resolve_parameters,
)
from kubeflow_tpu.pipelines.scheduler import RecurringRun, RunScheduler


@dataclasses.dataclass
class _RunRecord:
    run_id: str
    pipeline: str
    state: str = PENDING
    created_at: float = dataclasses.field(default_factory=time.time)
    parameters: dict[str, Any] = dataclasses.field(default_factory=dict)
    #: shared with the runner (mutated in place while the run executes)
    tasks: dict[str, TaskResult] = dataclasses.field(default_factory=dict)
    #: the run's DAG structure, captured at submit (inline specs have no
    #: registry entry to consult later): [{name, component, deps}]
    dag: list[dict] = dataclasses.field(default_factory=list)
    result: RunResult | None = None
    error: str = ""

    def to_dict(self, *, detail: bool = True) -> dict:
        d = {
            "run_id": self.run_id,
            "pipeline": self.pipeline,
            "state": self.state,
            "created_at": self.created_at,
            "error": self.error,
        }
        if self.result is not None:
            d["wall_s"] = round(self.result.wall_s, 4)
        if detail:
            d["parameters"] = self.parameters
            d["tasks"] = {
                name: {
                    "state": tr.state,
                    "cache_hit": tr.cache_hit,
                    "attempts": tr.attempts,
                    "error": tr.error,
                }
                for name, tr in self.tasks.items()
            }
        return d


class PipelineAPIServer(ThreadedAiohttpServer):
    """The write path for pipelines: everything the dashboard's read-only
    ``/api/pipelines`` view cannot do. Runs execute on a bounded worker
    pool; GET /runs/{id} observes live per-task state via the runner's
    ``live_tasks`` handoff."""

    thread_name = "kft-pipeline-api"

    def __init__(
        self,
        runner: PipelineRunner,
        *,
        scheduler: RunScheduler | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_parallel_runs: int = 4,
    ):
        super().__init__(host=host, port=port)
        self.runner = runner
        self._owns_scheduler = scheduler is None
        self.scheduler = scheduler or RunScheduler(runner).start()
        self._pipelines: dict[str, PipelineIR] = {}
        self._runs: dict[str, _RunRecord] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max_parallel_runs, thread_name_prefix="kft-api-run"
        )

    # -- pipeline registry -------------------------------------------------- #

    def upload(self, ir: PipelineIR) -> None:
        ir.topological_order()  # reject cyclic/broken specs at upload
        with self._lock:
            self._pipelines[ir.name] = ir

    def _get_pipeline(self, name: str) -> PipelineIR:
        with self._lock:
            if name not in self._pipelines:
                raise KeyError(f"pipeline {name!r} not uploaded")
            return self._pipelines[name]

    def _resolve_spec(self, body: dict) -> PipelineIR:
        """A run/recurring request names an uploaded pipeline OR inlines a
        spec (the `kft pipeline run -f` one-shot path)."""
        if "spec" in body:
            ir = PipelineIR.from_dict(body["spec"])
            # same fail-fast-at-submit contract as upload: a cyclic inline
            # spec must 400 here, not FAIL asynchronously in the run thread
            ir.topological_order()
            return ir
        if "pipeline" not in body:
            raise ValueError("request needs 'pipeline' (name) or 'spec'")
        return self._get_pipeline(body["pipeline"])

    # -- runs ---------------------------------------------------------------- #

    def create_run(self, ir: PipelineIR, parameters: dict[str, Any]) -> str:
        resolve_parameters(ir, parameters)  # fail fast at submit time
        rid = uuid.uuid4().hex[:12]
        rec = _RunRecord(
            run_id=rid, pipeline=ir.name, parameters=parameters,
            dag=[
                {
                    "name": t.name,
                    "component": t.component,
                    "deps": sorted(t.deps()),
                }
                for t in ir.tasks
            ],
        )
        with self._lock:
            self._runs[rid] = rec

        def work() -> None:
            rec.state = RUNNING
            try:
                res = self.runner.run(
                    ir, parameters, run_id=rid, live_tasks=rec.tasks
                )
                rec.result = res
                rec.state = res.state
            except Exception as e:  # noqa: BLE001 — surfaced via GET /runs
                rec.state = FAILED
                rec.error = f"{type(e).__name__}: {e}"

        self._pool.submit(work)
        return rid

    def get_run(self, run_id: str) -> _RunRecord:
        with self._lock:
            if run_id not in self._runs:
                raise KeyError(f"run {run_id!r} not found")
            return self._runs[run_id]

    def run_dag(self, run_id: str) -> dict:
        """DAG structure + live task states — the dashboard's pipeline
        graph view (SURVEY.md §2.4 frontend row)."""
        rec = self.get_run(run_id)
        return {
            "run_id": rec.run_id,
            "pipeline": rec.pipeline,
            "state": rec.state,
            "tasks": [
                {
                    **node,
                    "state": (
                        rec.tasks[node["name"]].state
                        if node["name"] in rec.tasks else "PENDING"
                    ),
                    "cache_hit": (
                        rec.tasks[node["name"]].cache_hit
                        if node["name"] in rec.tasks else False
                    ),
                }
                for node in rec.dag
            ],
        }

    # -- HTTP surface -------------------------------------------------------- #

    def _make_app(self):
        from aiohttp import web

        def fail(status: int, msg: str):
            return web.json_response({"error": msg}, status=status)

        def guard(fn):
            """JSON handler with the API's error contract: KeyError → 404,
            ValueError/TypeError (bad spec/params) → 400."""

            async def h(request):
                try:
                    return web.json_response(await fn(request))
                except KeyError as e:
                    return fail(404, str(e))
                except (ValueError, TypeError) as e:
                    return fail(400, f"{type(e).__name__}: {e}")

            return h

        async def upload_pipeline(request):
            body = await request.json()
            spec = body.get("spec", body)  # bare IR JSON accepted too
            ir = PipelineIR.from_dict(spec)
            self.upload(ir)
            return {
                "name": ir.name,
                "parameters": [list(p) for p in ir.parameters],
                "tasks": len(ir.tasks),
            }

        async def list_pipelines(_request):
            with self._lock:
                items = list(self._pipelines.values())
            return {
                "pipelines": [
                    {
                        "name": ir.name,
                        "description": ir.description,
                        "parameters": [list(p) for p in ir.parameters],
                        "tasks": len(ir.tasks),
                    }
                    for ir in items
                ]
            }

        async def get_pipeline(request):
            ir = self._get_pipeline(request.match_info["name"])
            return {"name": ir.name, "spec": ir.to_dict()}

        async def delete_pipeline(request):
            name = request.match_info["name"]
            with self._lock:
                if name not in self._pipelines:
                    raise KeyError(f"pipeline {name!r} not uploaded")
                del self._pipelines[name]
            return {"deleted": name}

        async def create_run(request):
            body = await request.json()
            ir = self._resolve_spec(body)
            rid = self.create_run(ir, dict(body.get("parameters") or {}))
            return {"run_id": rid, "pipeline": ir.name, "state": PENDING}

        async def list_runs(_request):
            with self._lock:
                recs = list(self._runs.values())
            recs.sort(key=lambda r: r.created_at, reverse=True)
            return {"runs": [r.to_dict(detail=False) for r in recs]}

        async def get_run(request):
            return self.get_run(request.match_info["run_id"]).to_dict()

        async def get_run_dag(request):
            return self.run_dag(request.match_info["run_id"])

        async def create_recurring(request):
            body = await request.json()
            ir = self._resolve_spec(body)
            params = dict(body.get("parameters") or {})
            resolve_parameters(ir, params)
            if "interval_s" not in body:
                raise ValueError("recurring run needs 'interval_s'")
            rr = RecurringRun(
                pipeline=ir,
                interval_s=float(body["interval_s"]),
                parameters=params,
                max_runs=body.get("max_runs"),
                name=body.get("name", ""),
            )
            uid = self.scheduler.add(rr)
            return {"uid": uid, "name": rr.name}

        def _rr_dict(rr: RecurringRun) -> dict:
            return {
                "uid": rr.uid,
                "name": rr.name,
                "pipeline": rr.pipeline.name,
                "interval_s": rr.interval_s,
                "paused": rr.paused,
                "fired": rr.fired,
                "max_runs": rr.max_runs,
                "history": [
                    {"run_id": h.run_id, "state": h.state,
                     "wall_s": round(h.wall_s, 4)}
                    for h in rr.history
                ],
            }

        async def list_recurring(_request):
            return {
                "recurring_runs": [
                    _rr_dict(rr) for rr in self.scheduler.list()
                ]
            }

        async def get_recurring(request):
            return _rr_dict(self.scheduler.get(request.match_info["uid"]))

        async def pause_recurring(request):
            self.scheduler.pause(request.match_info["uid"])
            return {"paused": True}

        async def resume_recurring(request):
            self.scheduler.resume(request.match_info["uid"])
            return {"paused": False}

        async def delete_recurring(request):
            uid = request.match_info["uid"]
            self.scheduler.get(uid)  # 404 if unknown
            self.scheduler.remove(uid)
            return {"deleted": uid}

        async def healthz(_request):
            return web.json_response({"ok": True})

        app = web.Application()
        pfx = "/apis/v2beta1"
        app.router.add_get("/healthz", healthz)
        app.router.add_post(f"{pfx}/pipelines", guard(upload_pipeline))
        app.router.add_get(f"{pfx}/pipelines", guard(list_pipelines))
        app.router.add_get(f"{pfx}/pipelines/{{name}}", guard(get_pipeline))
        app.router.add_delete(
            f"{pfx}/pipelines/{{name}}", guard(delete_pipeline)
        )
        app.router.add_post(f"{pfx}/runs", guard(create_run))
        app.router.add_get(f"{pfx}/runs", guard(list_runs))
        app.router.add_get(f"{pfx}/runs/{{run_id}}", guard(get_run))
        app.router.add_get(f"{pfx}/runs/{{run_id}}/dag", guard(get_run_dag))
        app.router.add_post(f"{pfx}/recurringruns", guard(create_recurring))
        app.router.add_get(f"{pfx}/recurringruns", guard(list_recurring))
        app.router.add_get(
            f"{pfx}/recurringruns/{{uid}}", guard(get_recurring)
        )
        app.router.add_post(
            f"{pfx}/recurringruns/{{uid}}:pause", guard(pause_recurring)
        )
        app.router.add_post(
            f"{pfx}/recurringruns/{{uid}}:resume", guard(resume_recurring)
        )
        app.router.add_delete(
            f"{pfx}/recurringruns/{{uid}}", guard(delete_recurring)
        )
        return app

    def stop(self) -> None:
        super().stop()
        if self._owns_scheduler:
            self.scheduler.shutdown()
        self._pool.shutdown(wait=False)
