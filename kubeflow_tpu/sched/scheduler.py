"""QuotaScheduler: multi-tenant quota admission over the gang scheduler.

The Kueue admission loop, TPU-form. ``GangScheduler`` gave each queue
strict priority + FIFO over raw fleet capacity; this subclass makes the
queue name a **LocalQueue** and admits against its **ClusterQueue**'s chip
quota instead of raw capacity:

1. **Nominal admission** — a workload whose ClusterQueue usage + demand
   fits nominal quota admits first (per-queue priority+FIFO preserved; a
   blocked head still holds its queue's line so large gangs never starve).
2. **Borrowing** — a workload over nominal may borrow unused nominal quota
   of other queues in the same ``cohort``, capped by ``borrowing_limit``.
   Across queues, borrow-needing heads are served in dominant-resource-
   share order (least-loaded queue first), so one tenant cannot starve a
   cohort.
3. **Preemption** — a workload that fits *nominal* quota but finds the
   chips physically held by cohort borrowers or lower-priority own-queue
   workloads selects victims (``sched.preemption``) and records intents;
   the reconciler drives each victim through the graceful preemption path
   (SIGTERM → forced checkpoint → exit 143 → gang requeued, no backoff
   burned) and the preemptor admits once the claims free.

Everything still rides ``Fleet.claim_gang`` — quota says *may* a workload
run, topology-aware claims say *where*; admission requires both.
"""

from __future__ import annotations

import logging
import time

from kubeflow_tpu.obs import names, prom
from kubeflow_tpu.orchestrator.gang import GangScheduler, PodGroup
from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.sched.preemption import plan_preemption
from kubeflow_tpu.sched.queues import ClusterQueue, QueueConfig
from kubeflow_tpu.sched.workload import Workload, group_chips_by_generation

logger = logging.getLogger(__name__)

QUEUE_NOMINAL = prom.REGISTRY.gauge(
    names.QUEUE_NOMINAL_CHIPS,
    "nominal chip quota per ClusterQueue and accelerator generation",
    labels=("queue", "generation"),
)
QUEUE_BORROWED = prom.REGISTRY.gauge(
    names.QUEUE_BORROWED_CHIPS,
    "chips each ClusterQueue currently holds beyond nominal (cohort-borrowed)",
    labels=("queue", "generation"),
)
QUEUE_PENDING = prom.REGISTRY.gauge(
    names.QUEUE_PENDING_WORKLOADS,
    "workloads waiting for quota admission per ClusterQueue",
    labels=("queue",),
)
PREEMPTIONS = prom.REGISTRY.counter(
    names.PREEMPTIONS_TOTAL,
    "workloads preempted by the quota scheduler",
    labels=("reason",),
)
QUEUE_WAIT = prom.REGISTRY.histogram(
    names.QUEUE_WAIT_SECONDS,
    "enqueue-to-admission wait per ClusterQueue",
    labels=("queue",),
)

#: per-queue wait samples kept for exact p50/p95 in `kft queues show`
_WAIT_SAMPLE_CAP = 512


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, round(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


class QuotaScheduler(GangScheduler):
    """Quota-aware admission in front of the gang scheduler."""

    def __init__(
        self,
        fleet: Fleet,
        config: QueueConfig,
        *,
        preemption_grace_seconds: float = 5.0,
    ):
        super().__init__(fleet)
        config.validate()
        self.config = config
        #: SIGTERM-to-SIGKILL budget the reconciler gives a victim to take
        #: its forced checkpoint before the hard kill.
        self.preemption_grace_seconds = preemption_grace_seconds
        #: job_uid → Workload for every pending or held gang.
        self._workloads: dict[str, Workload] = {}
        #: victim job_uid → preemptor job_uid (intents the reconciler drives)
        self._preempting: dict[str, str] = {}
        #: ClusterQueue name → recent enqueue→admission waits (seconds)
        self._waits: dict[str, list[float]] = {}
        # scrape-time gauge refresh (the client_golang Collector idiom)
        prom.REGISTRY.add_collector(self._refresh_gauges, key=self)

    def close(self) -> None:
        prom.REGISTRY.remove_collector(self)

    # -- queue lookups --------------------------------------------------- #

    def knows_queue(self, local_queue: str) -> bool:
        return local_queue in self.config.local_queues

    def known_queues(self) -> list[str]:
        return sorted(self.config.local_queues)

    def preemption_requested(self, job_uid: str) -> bool:
        with self._lock:
            return job_uid in self._preempting

    # -- bookkeeping overrides ------------------------------------------- #

    def _wrap(self, group: PodGroup) -> Workload:
        return Workload(
            group=group,
            cluster_queue=self.config.resolve(group.queue),
            chips_by_gen=group_chips_by_generation(group),
        )

    def enqueue(self, group: PodGroup) -> None:
        with self._lock:
            if group.job_uid in self._pending or group.job_uid in self._held:
                return
            self._pending[group.job_uid] = group
            w = self._wrap(group)
            self._workloads[group.job_uid] = w
            if w.cluster_queue is None:
                logger.warning(
                    "job %s submitted to unknown LocalQueue %r — it will "
                    "never admit (known: %s)",
                    group.job_uid, group.queue, self.known_queues(),
                )

    def cancel(self, job_uid: str) -> None:
        with self._lock:
            self._pending.pop(job_uid, None)
            group = self._held.pop(job_uid, None)
            self._workloads.pop(job_uid, None)
            # a cancelled victim's intent is fulfilled (or moot); a
            # cancelled preemptor must not keep evicting for capacity it
            # will never use
            self._preempting.pop(job_uid, None)
            for victim, preemptor in list(self._preempting.items()):
                if preemptor == job_uid:
                    del self._preempting[victim]
        if group and group.claims:
            self.fleet.release(list(group.claims.values()))

    def timed_out(self) -> list[PodGroup]:
        out = super().timed_out()
        if out:
            with self._lock:
                for g in out:
                    self._workloads.pop(g.job_uid, None)
        return out

    # -- quota accounting (lock held) ------------------------------------ #

    def _usage_locked(self) -> dict[str, dict[str, int]]:
        """ClusterQueue name → generation → chips held by admitted gangs."""
        usage: dict[str, dict[str, int]] = {}
        for uid in self._held:
            w = self._workloads.get(uid)
            if w is None or w.cluster_queue is None:
                continue
            q = usage.setdefault(w.cluster_queue.name, {})
            for gen, chips in w.chips_by_gen.items():
                q[gen] = q.get(gen, 0) + chips
        return usage

    def _fits_quota_locked(
        self,
        w: Workload,
        usage: dict[str, dict[str, int]],
        *,
        borrow: bool,
    ) -> bool:
        cq = w.cluster_queue
        if cq is None:
            return False
        used = usage.get(cq.name, {})
        for gen, chips in w.chips_by_gen.items():
            new = used.get(gen, 0) + chips
            nominal = cq.nominal(gen)
            if new <= nominal:
                continue
            if not borrow or cq.cohort is None:
                return False
            if (
                cq.borrowing_limit is not None
                and new - nominal > cq.borrowing_limit
            ):
                return False
            members = self.config.cohort_members(cq.cohort)
            cohort_nominal = sum(m.nominal(gen) for m in members)
            cohort_used = sum(
                usage.get(m.name, {}).get(gen, 0) for m in members
            )
            if cohort_used + chips > cohort_nominal:
                return False
        return True

    def _dominant_share_locked(
        self, cq: ClusterQueue, usage: dict[str, dict[str, int]]
    ) -> float:
        """Max over generations of usage/nominal — the DRF ordering key for
        cohort borrowing (zero-nominal generations with any usage count as
        fully saturated)."""
        used = usage.get(cq.name, {})
        share = 0.0
        for gen, chips in used.items():
            nominal = cq.nominal(gen)
            if nominal > 0:
                share = max(share, chips / nominal)
            elif chips > 0:
                share = max(share, float("inf"))
        return share

    # -- admission -------------------------------------------------------- #

    def try_schedule(self) -> list[PodGroup]:
        """One quota-admission pass; returns newly admitted groups."""
        admitted: list[PodGroup] = []
        now = time.time()
        with self._lock:
            usage = self._usage_locked()
            blocked: set[str] = set()
            progress = True
            while progress:
                progress = False
                for w in self._heads_locked(usage, blocked):
                    uid = w.uid
                    cq = w.cluster_queue
                    if uid in set(self._preempting.values()):
                        # victims are still draining for this workload;
                        # hold its queue's line until the claims free
                        blocked.add(cq.name)
                        continue
                    fits_nominal = self._fits_quota_locked(
                        w, usage, borrow=False
                    )
                    fits = fits_nominal or self._fits_quota_locked(
                        w, usage, borrow=True
                    )
                    if fits and self._admit_locked(w.group):
                        self._charge_locked(w, usage, now)
                        admitted.append(w.group)
                        progress = True
                        continue
                    if fits_nominal:
                        # quota says yes, capacity says no: the chips are
                        # physically held by borrowers or lower-priority
                        # workloads — reclaim them
                        self._plan_preemption_locked(w, usage)
                    blocked.add(cq.name)  # head-of-line holds the queue
        return admitted

    def _heads_locked(
        self, usage: dict[str, dict[str, int]], blocked: set[str]
    ) -> list[Workload]:
        """Head workload of each unblocked ClusterQueue, ordered: nominal-
        fitting heads first (FIFO among them), then borrow-needing heads by
        dominant share (fair sharing across the cohort)."""
        by_cq: dict[str, list[Workload]] = {}
        for uid in self._pending:
            w = self._workloads.get(uid)
            if w is None or w.cluster_queue is None:
                continue
            if w.cluster_queue.name in blocked:
                continue
            by_cq.setdefault(w.cluster_queue.name, []).append(w)
        heads = []
        for workloads in by_cq.values():
            workloads.sort(
                key=lambda w: (-w.priority, w.group.enqueued_at)
            )
            heads.append(workloads[0])
        heads.sort(
            key=lambda w: (
                0 if self._fits_quota_locked(w, usage, borrow=False) else 1,
                self._dominant_share_locked(w.cluster_queue, usage),
                w.group.enqueued_at,
            )
        )
        return heads

    def _charge_locked(
        self,
        w: Workload,
        usage: dict[str, dict[str, int]],
        now: float,
    ) -> None:
        """Record an admission: update usage, split nominal vs borrowed,
        and observe the queue wait."""
        cq = w.cluster_queue
        used = usage.setdefault(cq.name, {})
        borrowed: dict[str, int] = {}
        for gen, chips in w.chips_by_gen.items():
            before = used.get(gen, 0)
            after = before + chips
            nominal = cq.nominal(gen)
            over = max(0, after - nominal) - max(0, before - nominal)
            if over:
                borrowed[gen] = over
            used[gen] = after
        w.borrowed = borrowed
        w.admitted_at = now
        wait = max(0.0, now - w.group.enqueued_at)
        QUEUE_WAIT.labels(queue=cq.name).observe(wait)
        samples = self._waits.setdefault(cq.name, [])
        samples.append(wait)
        if len(samples) > _WAIT_SAMPLE_CAP:
            del samples[: len(samples) - _WAIT_SAMPLE_CAP]

    def _plan_preemption_locked(
        self, w: Workload, usage: dict[str, dict[str, int]]
    ) -> None:
        held = [
            self._workloads[uid]
            for uid in self._held
            if uid in self._workloads
            # a gang already marked for eviction is spoken for
            and uid not in self._preempting
        ]
        victims = plan_preemption(w, held, usage, self.fleet)
        if not victims:
            return
        for v in victims:
            self._preempting[v.uid] = w.uid
            reason = "borrowed" if v.borrowed_total > 0 else "priority"
            PREEMPTIONS.labels(reason=reason).inc()
            logger.warning(
                "preempting %s (queue %s, %s) so %s reclaims nominal quota",
                v.uid, v.group.queue, reason, w.uid,
            )

    # -- observability ---------------------------------------------------- #

    def _refresh_gauges(self) -> None:
        with self._lock:
            usage = self._usage_locked()
            borrowed: dict[str, dict[str, int]] = {}
            pending: dict[str, int] = {}
            for uid, w in self._workloads.items():
                if w.cluster_queue is None:
                    continue
                name = w.cluster_queue.name
                if uid in self._held:
                    b = borrowed.setdefault(name, {})
                    for gen, chips in w.borrowed.items():
                        b[gen] = b.get(gen, 0) + chips
                elif uid in self._pending:
                    pending[name] = pending.get(name, 0) + 1
        for cq in self.config.cluster_queues.values():
            gens = set(cq.quota) | set(usage.get(cq.name, {}))
            for gen in gens:
                QUEUE_NOMINAL.labels(
                    queue=cq.name, generation=gen
                ).set(cq.nominal(gen))
                QUEUE_BORROWED.labels(queue=cq.name, generation=gen).set(
                    borrowed.get(cq.name, {}).get(gen, 0)
                )
            QUEUE_PENDING.labels(queue=cq.name).set(
                pending.get(cq.name, 0)
            )

    def queues_view(self) -> list[dict]:
        """Dashboard/CLI rows: per-ClusterQueue quota, live usage, borrow
        split, pending depth, and enqueue→admission wait percentiles."""
        with self._lock:
            usage = self._usage_locked()
            rows = []
            for cq in self.config.cluster_queues.values():
                borrowed: dict[str, int] = {}
                admitted = pending = 0
                for uid, w in self._workloads.items():
                    if w.cluster_queue is not cq:
                        continue
                    if uid in self._held:
                        admitted += 1
                        for gen, chips in w.borrowed.items():
                            borrowed[gen] = borrowed.get(gen, 0) + chips
                    elif uid in self._pending:
                        pending += 1
                waits = sorted(self._waits.get(cq.name, []))
                rows.append(
                    {
                        "name": cq.name,
                        "cohort": cq.cohort,
                        "nominal": dict(cq.quota),
                        "usage": dict(usage.get(cq.name, {})),
                        "borrowed": borrowed,
                        "borrowing_limit": cq.borrowing_limit,
                        "preemption": cq.preemption.to_dict(),
                        "local_queues": self.config.local_queues_of(cq.name),
                        "admitted": admitted,
                        "pending": pending,
                        "wait_p50_s": _percentile(waits, 0.50),
                        "wait_p95_s": _percentile(waits, 0.95),
                    }
                )
        return rows
