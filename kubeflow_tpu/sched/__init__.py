"""Multi-tenant quota scheduling — the Kueue analog.

- ``queues``     — ClusterQueue (chip quota per generation, cohort,
                   borrowing limit, preemption policy) + LocalQueue
                   (tenant → ClusterQueue binding) + QueueConfig.
- ``workload``   — the per-gang quota ledger entry (charged vs borrowed).
- ``scheduler``  — QuotaScheduler: nominal admission, cohort borrowing with
                   dominant-share fairness, preemption intents.
- ``preemption`` — victim selection (borrowed-first, lowest-priority,
                   newest-first) with quota+topology feasibility simulation.

The eviction half runs in ``orchestrator.reconciler``: a victim is driven
through the graceful preemption path built in the chaos work — SIGTERM →
forced checkpoint → exit 143 → gang requeued ``Queued`` with claims
released, ``reason=Preempted``, no backoff burned — and resumes at the
exact next step when capacity returns.
"""

from kubeflow_tpu.sched.queues import (  # noqa: F401
    ClusterQueue,
    LocalQueue,
    PreemptionPolicy,
    QueueConfig,
)
from kubeflow_tpu.sched.scheduler import QuotaScheduler  # noqa: F401
from kubeflow_tpu.sched.workload import Workload  # noqa: F401
