"""Queue objects: the Kueue ClusterQueue / LocalQueue analog.

Kueue (the Kubeflow ecosystem's quota-admission layer, itself a descendant
of Borg's quota-and-preemption scheduling) splits multi-tenant admission
into two objects: a ``ClusterQueue`` owns capacity — nominal quota per
resource flavor, cohort membership for borrowing, a preemption policy —
and a ``LocalQueue`` is the namespaced tenant handle that binds job
submissions to a ClusterQueue. This module is that data model, TPU-form:
quota is **chips per accelerator generation** (the resource flavors of a
TPU fleet), and both objects are declarable as YAML manifests alongside
job specs (``platform.manifests.parse`` knows the kinds) or as plain
dicts/dataclasses in code.

Semantics implemented by ``sched.scheduler.QuotaScheduler``:

- a workload is charged against its ClusterQueue's nominal quota;
- queues in the same ``cohort`` may *borrow* each other's unused nominal
  quota, up to ``borrowing_limit`` chips beyond their own nominal;
- a workload that fits its **nominal** quota may *preempt* — reclaim
  capacity held by cohort borrowers and, policy permitting, by
  lower-priority workloads of its own ClusterQueue.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Mapping

#: ``reclaim_within_cohort`` values (who a nominal-quota workload may evict
#: among cohort borrowers) and ``within_cluster_queue`` values (whether it
#: may evict lower-priority workloads of its own queue).
RECLAIM_POLICIES = ("Never", "LowerPriority", "Any")
WITHIN_POLICIES = ("Never", "LowerPriority")


@dataclasses.dataclass(frozen=True)
class PreemptionPolicy:
    """Who this queue's workloads may evict to reclaim nominal quota
    (the Kueue ``ClusterQueue.spec.preemption`` analog)."""

    #: cohort borrowers: Never | LowerPriority (only borrowers of lower
    #: priority) | Any (any borrower — reclaiming nominal quota outranks
    #: a borrower's priority, the Kueue ``reclaimWithinCohort: Any`` mode).
    reclaim_within_cohort: str = "Any"
    #: own queue: Never | LowerPriority.
    within_cluster_queue: str = "LowerPriority"

    def __post_init__(self) -> None:
        if self.reclaim_within_cohort not in RECLAIM_POLICIES:
            raise ValueError(
                f"reclaim_within_cohort {self.reclaim_within_cohort!r} "
                f"not in {RECLAIM_POLICIES}"
            )
        if self.within_cluster_queue not in WITHIN_POLICIES:
            raise ValueError(
                f"within_cluster_queue {self.within_cluster_queue!r} "
                f"not in {WITHIN_POLICIES}"
            )

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "PreemptionPolicy":
        return cls(
            reclaim_within_cohort=d.get(
                "reclaim_within_cohort", d.get("reclaimWithinCohort", "Any")
            ),
            within_cluster_queue=d.get(
                "within_cluster_queue",
                d.get("withinClusterQueue", "LowerPriority"),
            ),
        )

    def to_dict(self) -> dict:
        return {
            "reclaim_within_cohort": self.reclaim_within_cohort,
            "within_cluster_queue": self.within_cluster_queue,
        }


@dataclasses.dataclass(frozen=True)
class ClusterQueue:
    """Capacity owner: chip quota per accelerator generation.

    ``quota`` maps generation → nominal chips ("v5e" → 16). ``cohort``
    names the borrowing pool; None opts out of borrowing entirely.
    ``borrowing_limit`` caps how many chips beyond nominal this queue may
    hold per generation (None = unbounded within cohort headroom).
    """

    name: str
    quota: Mapping[str, int] = dataclasses.field(default_factory=dict)
    cohort: str | None = None
    borrowing_limit: int | None = None
    preemption: PreemptionPolicy = dataclasses.field(
        default_factory=PreemptionPolicy
    )

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("ClusterQueue needs a name")
        for gen, chips in self.quota.items():
            if int(chips) < 0:
                raise ValueError(
                    f"ClusterQueue {self.name}: negative quota for {gen!r}"
                )
        if self.borrowing_limit is not None and self.borrowing_limit < 0:
            raise ValueError(
                f"ClusterQueue {self.name}: negative borrowing_limit"
            )
        if self.borrowing_limit and self.cohort is None:
            raise ValueError(
                f"ClusterQueue {self.name}: borrowing_limit without a "
                "cohort can never be used — set cohort or drop the limit"
            )

    def nominal(self, generation: str) -> int:
        return int(self.quota.get(generation, 0))

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ClusterQueue":
        return cls(
            name=d["name"],
            quota={k: int(v) for k, v in dict(d.get("quota", {})).items()},
            cohort=d.get("cohort"),
            borrowing_limit=(
                int(d["borrowing_limit"])
                if d.get("borrowing_limit") is not None
                else None
            ),
            preemption=PreemptionPolicy.from_dict(d.get("preemption", {})),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "quota": dict(self.quota),
            "cohort": self.cohort,
            "borrowing_limit": self.borrowing_limit,
            "preemption": self.preemption.to_dict(),
        }


@dataclasses.dataclass(frozen=True)
class LocalQueue:
    """Tenant handle: the name jobs submit to (``SchedulingPolicy.queue``),
    bound to the ClusterQueue whose quota admits them."""

    name: str
    cluster_queue: str
    namespace: str = "default"

    def __post_init__(self) -> None:
        if not self.name or not self.cluster_queue:
            raise ValueError("LocalQueue needs name and cluster_queue")

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "LocalQueue":
        return cls(
            name=d["name"],
            cluster_queue=d.get("cluster_queue", d.get("clusterQueue", "")),
            namespace=d.get("namespace", "default"),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "cluster_queue": self.cluster_queue,
            "namespace": self.namespace,
        }


class QueueConfig:
    """Validated set of ClusterQueues + LocalQueues the scheduler runs on."""

    def __init__(
        self,
        cluster_queues: Iterable[ClusterQueue] = (),
        local_queues: Iterable[LocalQueue] = (),
    ):
        self.cluster_queues: dict[str, ClusterQueue] = {}
        self.local_queues: dict[str, LocalQueue] = {}
        for cq in cluster_queues:
            self.add(cq)
        for lq in local_queues:
            self.add(lq)
        self.validate()

    def add(self, obj: ClusterQueue | LocalQueue) -> None:
        if isinstance(obj, ClusterQueue):
            if obj.name in self.cluster_queues:
                raise ValueError(f"duplicate ClusterQueue {obj.name!r}")
            self.cluster_queues[obj.name] = obj
        elif isinstance(obj, LocalQueue):
            if obj.name in self.local_queues:
                raise ValueError(f"duplicate LocalQueue {obj.name!r}")
            self.local_queues[obj.name] = obj
        else:
            raise TypeError(f"not a queue object: {obj!r}")

    def validate(self) -> None:
        for lq in self.local_queues.values():
            if lq.cluster_queue not in self.cluster_queues:
                raise ValueError(
                    f"LocalQueue {lq.name!r} binds unknown ClusterQueue "
                    f"{lq.cluster_queue!r} (known: "
                    f"{sorted(self.cluster_queues)})"
                )

    def resolve(self, local_queue: str) -> ClusterQueue | None:
        """LocalQueue name → its ClusterQueue; None when unknown."""
        lq = self.local_queues.get(local_queue)
        if lq is None:
            return None
        return self.cluster_queues.get(lq.cluster_queue)

    def cohort_members(self, cohort: str) -> list[ClusterQueue]:
        return [
            cq for cq in self.cluster_queues.values() if cq.cohort == cohort
        ]

    def local_queues_of(self, cq_name: str) -> list[str]:
        return sorted(
            lq.name
            for lq in self.local_queues.values()
            if lq.cluster_queue == cq_name
        )

    @classmethod
    def from_specs(cls, specs: Iterable[Any]) -> "QueueConfig":
        """Build from a mixed iterable of queue dataclasses and/or manifest
        dicts (the shapes ``from_manifest`` accepts)."""
        cqs: list[ClusterQueue] = []
        lqs: list[LocalQueue] = []
        for s in specs:
            if isinstance(s, Mapping):
                s = from_manifest(s)
            if isinstance(s, ClusterQueue):
                cqs.append(s)
            elif isinstance(s, LocalQueue):
                lqs.append(s)
            else:
                raise TypeError(f"not a queue spec: {s!r}")
        return cls(cqs, lqs)


def from_manifest(manifest: Mapping[str, Any]) -> ClusterQueue | LocalQueue:
    """Parse a ClusterQueue/LocalQueue manifest (the Kueue CRD shapes,
    TPU-form: ``spec.quota`` maps generation → chips)::

        kind: ClusterQueue
        metadata: {name: tenant-a}
        spec:
          cohort: shared
          quota: {v5e: 8}
          borrowingLimit: 4
          preemption: {reclaimWithinCohort: Any,
                       withinClusterQueue: LowerPriority}

        kind: LocalQueue
        metadata: {name: team-a, namespace: default}
        spec: {clusterQueue: tenant-a}
    """
    kind = manifest.get("kind")
    meta = manifest.get("metadata", {})
    spec = manifest.get("spec", {}) or {}
    if kind == "ClusterQueue":
        return ClusterQueue.from_dict(
            {
                "name": meta.get("name", ""),
                "quota": spec.get("quota", {}),
                "cohort": spec.get("cohort"),
                "borrowing_limit": spec.get(
                    "borrowingLimit", spec.get("borrowing_limit")
                ),
                "preemption": spec.get("preemption", {}),
            }
        )
    if kind == "LocalQueue":
        return LocalQueue.from_dict(
            {
                "name": meta.get("name", ""),
                "cluster_queue": spec.get(
                    "clusterQueue", spec.get("cluster_queue", "")
                ),
                "namespace": meta.get("namespace", "default"),
            }
        )
    raise ValueError(f"not a queue manifest kind: {kind!r}")
