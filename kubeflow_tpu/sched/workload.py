"""Workload: one gang's quota ledger entry.

The Kueue ``Workload`` analog: when a job's PodGroup enters the quota
scheduler it is wrapped in a ``Workload`` that resolves the submission's
LocalQueue to its ClusterQueue and aggregates the gang's chip demand per
accelerator generation. While admitted, the workload records how many of
those chips were charged *within* the ClusterQueue's nominal quota and how
many were **borrowed** from the cohort — the split preemption keys off
(borrowers are first in line to be reclaimed).
"""

from __future__ import annotations

import dataclasses

from kubeflow_tpu.orchestrator.gang import PodGroup
from kubeflow_tpu.orchestrator.resources import topology_chips
from kubeflow_tpu.sched.queues import ClusterQueue


def group_chips_by_generation(group: PodGroup) -> dict[str, int]:
    """Aggregate a gang's chip demand per generation; whole-slice topology
    requests charge the full slice."""
    out: dict[str, int] = {}
    for _, chips, topo, gen in group.requests:
        need = topology_chips(topo) if topo is not None else chips
        out[gen] = out.get(gen, 0) + need
    return out


@dataclasses.dataclass
class Workload:
    """One gang under quota management (pending or admitted)."""

    group: PodGroup
    #: the ClusterQueue whose quota admits this workload; None when the
    #: submission named an unknown LocalQueue (never admitted — the
    #: admission webhook normally rejects this before it gets here).
    cluster_queue: ClusterQueue | None
    #: generation → chips the whole gang occupies.
    chips_by_gen: dict[str, int] = dataclasses.field(default_factory=dict)
    #: generation → chips charged beyond nominal quota at admission time
    #: (cohort-borrowed); empty while pending or when fully nominal.
    borrowed: dict[str, int] = dataclasses.field(default_factory=dict)
    admitted_at: float | None = None

    @property
    def uid(self) -> str:
        return self.group.job_uid

    @property
    def priority(self) -> int:
        return self.group.priority

    @property
    def borrowed_total(self) -> int:
        return sum(self.borrowed.values())

    def to_dict(self) -> dict:
        return {
            "uid": self.uid,
            "queue": self.group.queue,
            "cluster_queue": (
                self.cluster_queue.name if self.cluster_queue else None
            ),
            "priority": self.priority,
            "chips": dict(self.chips_by_gen),
            "borrowed": dict(self.borrowed),
            "admitted": self.group.admitted,
        }
