"""Victim selection: which admitted gangs a blocked workload may evict.

A workload earns the right to preempt only when it is asking for capacity
its ClusterQueue *owns* — it fits nominal quota once the victims are gone —
and the capacity is currently held by cohort **borrowers** or (policy
permitting) **lower-priority** workloads of its own queue. Eviction order
is the Kueue/Borg convention: borrowed-first, then lowest-priority,
newest-first — a borrower is living on someone else's quota, a newer
workload has wasted the least work.

Selection is a greedy simulation: walk candidates in eviction order,
virtually release each victim's slice claims and quota charge, and stop at
the first prefix that makes the preemptor feasible **both** ways — quota
(nominal fits) and topology (``Fleet.fits_gang`` with the victims' chips
returned). No feasible prefix ⇒ no preemption (never evict work that
cannot actually be replaced by the preemptor).
"""

from __future__ import annotations

import logging

from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.sched.workload import Workload

logger = logging.getLogger(__name__)


def _fits_nominal(
    w: Workload, usage: dict[str, dict[str, int]]
) -> bool:
    cq = w.cluster_queue
    if cq is None:
        return False
    used = usage.get(cq.name, {})
    return all(
        used.get(gen, 0) + chips <= cq.nominal(gen)
        for gen, chips in w.chips_by_gen.items()
    )


def eviction_candidates(
    preemptor: Workload, held: list[Workload]
) -> list[Workload]:
    """Admitted workloads the preemptor's policy allows it to evict, in
    eviction order (borrowed-first, then lowest-priority, newest-first)."""
    cq = preemptor.cluster_queue
    if cq is None:
        return []
    policy = cq.preemption
    ranked: list[tuple[int, Workload]] = []
    for v in held:
        if v.uid == preemptor.uid or v.cluster_queue is None:
            continue
        vcq = v.cluster_queue
        same_queue = vcq.name == cq.name
        same_cohort = (
            cq.cohort is not None and vcq.cohort == cq.cohort
        )
        if not same_queue and same_cohort and v.borrowed_total > 0:
            # a cohort borrower holding quota the preemptor owns
            if policy.reclaim_within_cohort == "Never":
                continue
            if (
                policy.reclaim_within_cohort == "LowerPriority"
                and v.priority >= preemptor.priority
            ):
                continue
            ranked.append((0, v))
        elif same_queue:
            if policy.within_cluster_queue == "Never":
                continue
            if v.priority >= preemptor.priority:
                continue
            ranked.append((1, v))
    ranked.sort(
        key=lambda t: (
            t[0],                       # borrowers before own-queue victims
            t[1].priority,              # lowest priority first
            -(t[1].admitted_at or 0.0), # newest first
        )
    )
    return [v for _, v in ranked]


def plan_preemption(
    preemptor: Workload,
    held: list[Workload],
    usage: dict[str, dict[str, int]],
    fleet: Fleet,
) -> list[Workload] | None:
    """Minimal eviction-ordered victim prefix that makes ``preemptor``
    feasible within its nominal quota, or None."""
    candidates = eviction_candidates(preemptor, held)
    if not candidates:
        return None
    requests = [
        (chips, topo, gen)
        for _, chips, topo, gen in preemptor.group.requests
    ]
    sim_usage = {q: dict(g) for q, g in usage.items()}
    extra_free: dict[str, int] = {}
    victims: list[Workload] = []
    for v in candidates:
        victims.append(v)
        for claim in (v.group.claims or {}).values():
            extra_free[claim.slice_id] = (
                extra_free.get(claim.slice_id, 0) + claim.chips
            )
        vq = sim_usage.setdefault(v.cluster_queue.name, {})
        for gen, chips in v.chips_by_gen.items():
            vq[gen] = vq.get(gen, 0) - chips
        if _fits_nominal(preemptor, sim_usage) and fleet.fits_gang(
            requests, extra_free=extra_free
        ):
            logger.info(
                "preemption planned: %s evicts %s",
                preemptor.uid, [v.uid for v in victims],
            )
            return victims
    return None
