"""Observability plane: metrics, heartbeats, profiling, structured logs.

SURVEY.md §5.1/§5.5 equivalents, TPU-first: Prometheus-style exposition on
every process, XLA profiler capture endpoints, worker heartbeat liveness
feeding the elastic supervisor (§5.3).
"""

from kubeflow_tpu.obs.heartbeat import (
    Heartbeat,
    HeartbeatWriter,
    heartbeat_path,
    heartbeat_path_from_env,
    is_stale,
    read_heartbeat,
)
from kubeflow_tpu.obs.jsonlog import JsonFormatter, configure_json_logging
from kubeflow_tpu.obs.profiler import ObsServer, capture_trace, trace_step
from kubeflow_tpu.obs.prom import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
)

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Heartbeat",
    "HeartbeatWriter",
    "Histogram",
    "JsonFormatter",
    "ObsServer",
    "Registry",
    "capture_trace",
    "configure_json_logging",
    "heartbeat_path",
    "heartbeat_path_from_env",
    "is_stale",
    "read_heartbeat",
    "trace_step",
]
