"""Worker liveness heartbeats — the probe/peer-loss analog.

The reference detects sick workers with kubelet liveness probes and (for
SPMD gangs) the c10d/coordinator peer-loss abort (SURVEY.md §5.3). Exit
deaths are already caught by the launcher's process monitor; what that
misses is a *hung* worker — alive but stuck (deadlocked collective, wedged
host callback). The heartbeat protocol covers that gap:

- worker side: ``HeartbeatWriter`` touches a per-worker JSON file on a
  background thread (and on every recorded step);
- supervisor side (``kubeflow_tpu.orchestrator.supervisor``): a stale file
  on a Running worker ⇒ kill it, letting the normal gang-restart +
  checkpoint-restore path take over.

The file lives in the job workdir, which the orchestrator shares across the
gang (``KFT_WORKDIR``), so supervision needs no extra channel.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from pathlib import Path

from kubeflow_tpu.orchestrator import envwire

#: filename pattern inside the job workdir
_FILE = "heartbeat-{rtype}-{index}.json"


def heartbeat_path(workdir: str | Path, rtype: str, index: int) -> Path:
    return Path(workdir) / _FILE.format(rtype=rtype, index=index)


def heartbeat_path_from_env(env: dict[str, str] | None = None) -> Path | None:
    """Resolve this worker's heartbeat file from the orchestrator wiring;
    None when not running under a JAXJob gang."""
    e = os.environ if env is None else env
    workdir = e.get(envwire.ENV_WORKDIR)
    rtype = e.get(envwire.ENV_REPLICA_TYPE)
    index = e.get(envwire.ENV_REPLICA_INDEX)
    if not (workdir and rtype and index is not None):
        return None
    return heartbeat_path(workdir, rtype, int(index))


@dataclasses.dataclass
class Heartbeat:
    #: ``time.monotonic()`` stamp, NOT wall clock: staleness is duration
    #: math, and a wall-clock jump (NTP step) must never read as a hung or
    #: miraculously-fresh worker. CLOCK_MONOTONIC is boot-relative
    #: system-wide on Linux, so stamps compare correctly across the
    #: worker/supervisor process boundary on the same host.
    time: float
    pid: int
    step: int = -1
    attempt: int = 0

    def age(self, now: float | None = None) -> float:
        return (time.monotonic() if now is None else now) - self.time


class HeartbeatWriter:
    """Background beat + explicit ``beat(step=...)`` from the train loop."""

    def __init__(
        self,
        path: str | Path,
        *,
        interval: float = 1.0,
        attempt: int = 0,
    ):
        self.path = Path(path)
        self.interval = interval
        self.attempt = attempt
        self._step = -1
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._write_lock = threading.Lock()
        self.path.parent.mkdir(parents=True, exist_ok=True)

    @classmethod
    def from_env(cls, *, interval: float = 1.0) -> "HeartbeatWriter | None":
        path = heartbeat_path_from_env()
        if path is None:
            return None
        return cls(
            path,
            interval=interval,
            attempt=int(os.environ.get(envwire.ENV_ATTEMPT, "0")),
        )

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat()

    def beat(self, step: int | None = None) -> None:
        tmp = self.path.with_suffix(".tmp")
        # Lock held from step update through publish: the background thread
        # and explicit beat(step) callers share one tmp file — unserialised,
        # a replace could publish a truncated write, and a payload built
        # outside the lock could publish an OLDER step after a newer one
        # (the drain stamps step N, the background beat overwrites with
        # N-1), making observed progress regress.
        with self._write_lock:
            if step is not None:
                self._step = step
            payload = json.dumps(
                dataclasses.asdict(
                    Heartbeat(
                        time=time.monotonic(),
                        pid=os.getpid(),
                        step=self._step,
                        attempt=self.attempt,
                    )
                )
            )
            tmp.write_text(payload)
            os.replace(tmp, self.path)  # atomic: readers never see torn data

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def read_heartbeat(path: str | Path) -> Heartbeat | None:
    """None if the file is absent or torn (treat as 'no beat yet')."""
    try:
        d = json.loads(Path(path).read_text())
        return Heartbeat(**d)
    except (OSError, ValueError, TypeError):
        return None


def is_stale(
    path: str | Path,
    timeout: float,
    *,
    min_attempt: int = 0,
    now: float | None = None,
) -> bool:
    """True when the latest beat (of at least ``min_attempt``) is older than
    ``timeout``. A missing file is NOT stale — the worker may not have
    reached its first beat; the supervisor separately grace-periods startup.
    ``now`` must come from ``time.monotonic()`` (beats are stamped with it).
    """
    hb = read_heartbeat(path)
    if hb is None or hb.attempt < min_attempt:
        return False
    return hb.age(now) > timeout
