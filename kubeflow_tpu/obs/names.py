"""The single definition site for every exposition name the platform emits.

Dashboards, smoke assertions, the chaos harness, and external Prometheus
scrape configs all key off these strings — a typo'd or drifting name is a
silent outage of the signal it carried. ``kft lint``'s ``metric-registry``
pass enforces that no ``kft_*`` / ``kubeflow_tpu_*`` literal appears
anywhere else in the package: recorders and registrars must reference
these constants, so renames are single-line diffs and every name in the
exposition provably has exactly one owner.

Grouped by plane. ``*_PREFIX`` constants are the sanctioned dynamic-name
roots (engine scheduler/pager stats fan out per-key under them).
"""

from __future__ import annotations

# -- orchestrator (control plane) ------------------------------------- #

#: histogram — controller sync_all wall time
RECONCILE_SECONDS = "kft_reconcile_seconds"
#: gauge{phase} — jobs currently in the store by phase
JOBS_BY_PHASE = "kft_jobs"
#: counter{reason} — workers killed by the heartbeat supervisor
SUPERVISOR_KILLS_TOTAL = "kft_supervisor_kills_total"
#: counter — gang restarts triggered by worker failures
GANG_RESTARTS_TOTAL = "kft_gang_restarts_total"
#: counter{reason} — gangs requeued after losing placement
GANG_REQUEUES_TOTAL = "kft_gang_requeues_total"
#: counter{condition,reason} — jobs reaching a terminal condition
JOBS_FINISHED_TOTAL = "kft_jobs_finished_total"

# -- quota scheduler (sched/) ------------------------------------------ #

#: gauge{queue,generation} — nominal chip quota per ClusterQueue
QUEUE_NOMINAL_CHIPS = "kft_queue_nominal_chips"
#: gauge{queue,generation} — chips held beyond nominal (cohort-borrowed)
QUEUE_BORROWED_CHIPS = "kft_queue_borrowed_chips"
#: gauge{queue} — workloads waiting for quota admission
QUEUE_PENDING_WORKLOADS = "kft_queue_pending_workloads"
#: counter{reason} — workloads preempted by the quota scheduler
PREEMPTIONS_TOTAL = "kft_preemptions_total"
#: histogram{queue} — enqueue-to-admission wait
QUEUE_WAIT_SECONDS = "kft_queue_wait_seconds"

# -- chaos harness ------------------------------------------------------ #

#: counter{kind} — faults the chaos runner actually injected
CHAOS_INJECTED_TOTAL = "kft_chaos_injected_total"
#: histogram — fault-to-recovered wall time
RECOVERY_SECONDS = "kft_recovery_seconds"

# -- training ----------------------------------------------------------- #

#: counter — restores that walked past a corrupt/unreadable step
CHECKPOINT_FALLBACKS_TOTAL = "kft_checkpoint_fallbacks_total"
#: gauges — the hot-loop overlap split (train/prefetch.py, train/metrics.py)
TRAIN_DATA_STALL_MS = "kubeflow_tpu_train_data_stall_ms"
TRAIN_H2D_MS = "kubeflow_tpu_train_h2d_ms"
TRAIN_DEVICE_STEP_MS = "kubeflow_tpu_train_device_step_ms"
TRAIN_COMPILE_MS = "kubeflow_tpu_train_compile_ms"
TRAIN_STEPS_PER_SEC = "kubeflow_tpu_train_steps_per_sec"

# -- inference gateway (gateway/) --------------------------------------- #

#: counter{service,code} — requests answered at the edge, by HTTP status
GATEWAY_REQUESTS_TOTAL = "kft_gateway_requests_total"
#: histogram{service} — edge-observed request latency (activator queue
#: time included: the client experienced it)
GATEWAY_LATENCY_SECONDS = "kft_gateway_latency_seconds"
#: gauge{service} — requests parked in the activator FIFO right now
GATEWAY_QUEUE_DEPTH = "kft_gateway_queue_depth"
#: counter{service,reason} — requests shed at the edge
#: (rate_limit / inflight_cap / queue_full / activation_timeout / no_backend)
GATEWAY_SHED_TOTAL = "kft_gateway_shed_total"
#: counter{service} — transparent re-dispatches after a backend failure
GATEWAY_RETRIES_TOTAL = "kft_gateway_retries_total"
#: counter{service} — hedged second requests dispatched
GATEWAY_HEDGES_TOTAL = "kft_gateway_hedges_total"
#: counter{service} — requests routed by prefix/session affinity
GATEWAY_AFFINITY_ROUTED_TOTAL = "kft_gateway_affinity_routed_total"
#: gauge{backend} — 1 while the backend's circuit breaker is open/half-open
GATEWAY_BREAKER_OPEN = "kft_gateway_breaker_open"
#: counter{backend} — closed→open breaker transitions
GATEWAY_BREAKER_OPENS_TOTAL = "kft_gateway_breaker_opens_total"
#: gauge{service} — backends currently eligible for selection
GATEWAY_BACKENDS_READY = "kft_gateway_backends_ready"
#: counter{service} — scale-from-zero kicks issued by the activator
GATEWAY_ACTIVATIONS_TOTAL = "kft_gateway_activations_total"
#: gauge{service} — activator FIFO depth under its autoscaler-facing name
#: (an autoscaler input: parked demand counts as concurrency, or
#: scale-from-zero never happens)
GATEWAY_ACTIVATOR_QUEUE_DEPTH = "kft_gateway_activator_queue_depth"
#: gauge{service} — 1 while a cold-episode scale-up kick is outstanding
GATEWAY_ACTIVATOR_COLD_EPISODE = "kft_gateway_activator_cold_episode"
#: counter{service,outcome} — mid-stream failovers: a decode stream whose
#: upstream died after bytes were committed, re-dispatched to a healthy
#: peer with the x-kft-resume-tokens contract (outcome: ok /
#: budget_exhausted / no_backend / failed)
GATEWAY_STREAM_RESUMES_TOTAL = "kft_gateway_stream_resumes_total"

# -- serving autoscaler (autoscale/) ------------------------------------ #

#: gauge{service} — the recommender's current desired replica count
AUTOSCALER_DESIRED_REPLICAS = "kft_autoscaler_desired_replicas"
#: gauge{service} — stable-window average observed concurrency
AUTOSCALER_STABLE_CONCURRENCY = "kft_autoscaler_stable_concurrency"
#: gauge{service} — panic-window average observed concurrency
AUTOSCALER_PANIC_CONCURRENCY = "kft_autoscaler_panic_concurrency"
#: gauge{service} — 1 while the service is in panic mode (no scale-down)
AUTOSCALER_PANIC_MODE = "kft_autoscaler_panic_mode"
#: counter{service,direction} — actuated replica-count changes (up/down)
AUTOSCALER_SCALE_EVENTS_TOTAL = "kft_autoscaler_scale_events_total"
#: counter{service} — prefix-KV entries moved between replicas after a
#: hash-ring remap (scale-up pull / scale-down evacuation)
AUTOSCALER_KV_TRANSFERS_TOTAL = "kft_autoscaler_kv_transfers_total"
#: gauge{service} — replicas a fleet currently runs (the actuated count,
#: as opposed to the recommender's desired count above); the loadgen
#: reporter reads its movement to time 1→N scale-up
FLEET_REPLICAS = "kft_fleet_replicas"

# -- load harness (loadgen/) --------------------------------------------- #

#: counter{tenant,outcome} — client-side verdict on every loadgen request
#: (completed_in_slo / completed_late / shed / error); the client-truth
#: complement of the gateway's server-side counters
LOADGEN_REQUESTS_TOTAL = "kft_loadgen_requests_total"

# -- serving ------------------------------------------------------------ #

#: gauge{model} — requests currently executing in the dataplane (the
#: load signal the gateway's least-outstanding balancer cross-checks)
SERVER_INFLIGHT = "kft_server_inflight"
#: gauge{model} — instances waiting in the batcher queue
SERVER_QUEUE_DEPTH = "kft_server_queue_depth"

#: counter{model} — model loads that raised (ModelMesh)
MODELMESH_LOAD_FAILURES_TOTAL = "kft_modelmesh_load_failures_total"
#: gauges{model} — batcher occupancy (shared registry + /metrics)
BATCHER_BATCHES = "kubeflow_tpu_batcher_batches"
BATCHER_INSTANCES = "kubeflow_tpu_batcher_instances"
BATCHER_MEAN_OCCUPANCY = "kubeflow_tpu_batcher_mean_occupancy"
#: gauge{model} — co-batched failures re-run per caller (offender isolation)
BATCHER_FAIL_ISOLATIONS = "kubeflow_tpu_batcher_fail_isolations"
#: dataplane request metrics (ModelServer /metrics exposition)
REQUESTS_TOTAL = "kubeflow_tpu_requests_total"
LATENCY_P50_MS = "kubeflow_tpu_latency_p50_ms"
LATENCY_P99_MS = "kubeflow_tpu_latency_p99_ms"
#: continuous-batching engine gauges; per-key stats fan out under the
#: prefixes (scheduler stats, paged-KV pool pressure)
ENGINE_ACTIVE_ROWS = "kubeflow_tpu_engine_active_rows"
ENGINE_PREFIX = "kubeflow_tpu_engine_"
ENGINE_KV_PREFIX = "kubeflow_tpu_engine_kv_"
#: pipelined-decode overlap gauges (serve/engine.py `overlap` dict):
#: host time between chunk dispatches — the dead bus time the pipeline
#: exists to remove
ENGINE_DECODE_GAP_MS = "kft_engine_decode_gap_ms"
#: token-drain D2H sync time per chunk (overlapped by the next chunk)
ENGINE_D2H_DRAIN_MS = "kft_engine_d2h_drain_ms"
#: counter — carry epoch re-uploads; grows with admissions/retirements,
#: NOT with chunks (steady-state decode performs zero per-chunk H2D)
ENGINE_CARRY_UPLOADS_TOTAL = "kft_engine_carry_uploads_total"
#: EWMA occupied-row fraction at chunk dispatch
ENGINE_SLOT_OCCUPANCY = "kft_engine_slot_occupancy"
#: prefix-cache effectiveness (the signal the gateway's prefix affinity
#: steers by): cumulative hits / KV tokens reused, live entry count and
#: stored-token occupancy
ENGINE_PREFIX_HITS_TOTAL = "kft_engine_prefix_hits_total"
ENGINE_PREFIX_TOKENS_REUSED_TOTAL = "kft_engine_prefix_tokens_reused_total"
ENGINE_PREFIX_ENTRIES = "kft_engine_prefix_entries"
ENGINE_PREFIX_TOKENS_STORED = "kft_engine_prefix_tokens_stored"
#: cross-replica prefix-KV transfer (serve/server.py peer endpoints):
#: entries imported from / exported to a peer replica — a hit served
#: from an imported entry is KV that was never re-prefilled here
ENGINE_PREFIX_IMPORTED_TOTAL = "kft_engine_prefix_imported_total"
ENGINE_PREFIX_EXPORTED_TOTAL = "kft_engine_prefix_exported_total"
#: speculative decoding (serve/speculative.py): draft tokens proposed /
#: accepted by the in-graph verify, and the EWMA acceptance ratio — the
#: tokens-per-forward multiplier prompt-lookup is buying
ENGINE_SPEC_PROPOSED_TOTAL = "kft_engine_spec_proposed_total"
ENGINE_SPEC_ACCEPTED_TOTAL = "kft_engine_spec_accepted_total"
ENGINE_SPEC_ACCEPTANCE = "kft_engine_spec_acceptance"
#: int8 KV-cache quantization (ops/paged_attention.py): EWMA of the
#: mean-abs relative quantization error measured at prefill writes
ENGINE_KV_QUANT_ERROR = "kft_engine_kv_quant_error"
#: gauge — 1 while the engine's paged read path runs the Pallas kernel
#: (LMEngineConfig paged_attn_impl="kernel"), 0 for the XLA gather
ENGINE_PAGED_ATTN_KERNEL = "kft_engine_paged_attn_kernel"
#: disaggregated prefill/decode (serve/engine.py prefill_span / inject):
#: counter{model,direction} — bytes of per-request KV spans shipped over
#: the wire (direction: export on the prefill replica, import on decode)
ENGINE_KV_SHIP_BYTES_TOTAL = "kft_engine_kv_ship_bytes_total"
#: histogram{model} — one KV-span ship leg end to end, milliseconds
#: (decode-side: peer prefill RPC + decode + inject-validate)
ENGINE_KV_SHIP_MS = "kft_engine_kv_ship_ms"
#: host-RAM KV tier (serve/kv_tier.py): gauge{model} — encoded KV bytes
#: resident in the bounded host pool
ENGINE_KV_OFFLOAD_BYTES = "kft_engine_kv_offload_bytes"
#: gauge{model} — swapped-out session rows resident in the host tier
ENGINE_KV_OFFLOAD_RESIDENT_ROWS = "kft_engine_kv_offload_resident_rows"

# -- serving SRE layer (serve/deadline.py, serve/watchdog.py) ------------ #

#: counter{stage} — requests retired because their end-to-end deadline
#: expired (admission / queued / decoding / wait / batch_queue)
ENGINE_DEADLINE_EXPIRED_TOTAL = "kft_engine_deadline_expired_total"
#: counter{reason} — requests shed by deadline-aware admission control
#: (deadline_unmeetable / priority_evict) BEFORE costing a decode slot
ENGINE_ADMISSION_SHED_TOTAL = "kft_engine_admission_shed_total"
#: counter{model,reason} — engine watchdog trips (wedged / loop_dead /
#: fatal); each trip flips readiness and triggers a supervised restart
ENGINE_WATCHDOG_TRIPS_TOTAL = "kft_engine_watchdog_trips_total"
#: counter{model} — supervised engine restarts (device state rebuilt)
ENGINE_RESTARTS_TOTAL = "kft_engine_restarts_total"
#: counter{model} — requests admitted with a committed-token resume
#: prefix (the engine half of the gateway's mid-stream failover)
ENGINE_RESUME_ADMITS_TOTAL = "kft_engine_resume_admits_total"

# -- request tracing (obs/trace.py) -------------------------------------- #

#: histogram{model} — server-side time-to-first-token of traced requests,
#: milliseconds (engine enqueue → first pushed token)
SERVER_TTFT_MS = "kft_server_ttft_ms"
#: histogram{model} — server-side mean time-per-output-token after the
#: first, milliseconds (the steady-state decode pace SLOs bind to)
SERVER_TPOT_MS = "kft_server_tpot_ms"
#: counter{decision} — tail-sampler verdicts on finished traces
#: (error / slow / sampled / dropped); error+slow+sampled are retained
TRACE_SAMPLER_DECISIONS_TOTAL = "kft_trace_sampler_decisions_total"
