"""Prometheus-style process metrics: counters, gauges, histograms.

Every controller in the reference exposes controller-runtime Prometheus
metrics on ``/metrics`` (reconcile latency/counts — SURVEY.md §5.1); KServe
adds queue-proxy request metrics. This is the TPU framework's equivalent:
an in-process registry with the standard instrument types and the text
exposition format, served by ``kubeflow_tpu.obs.profiler.ObsServer`` and
scraped in tests exactly the way Prometheus would.

No client library exists in this image, so the registry is first-party —
the exposition format is the stable public contract
(``# HELP``/``# TYPE`` + ``name{labels} value`` lines).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Iterable, Mapping

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0,
)

#: millisecond-scale buckets for latency histograms recorded in ms
#: (TTFT/TPOT): the seconds-scale defaults would collapse every
#: observation into the +Inf bucket
MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)


def _label_key(labels: Mapping[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple[tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared machinery: one child per label-set, locked mutation."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Iterable[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: dict[tuple[tuple[str, str], ...], object] = {}

    def labels(self, **labels: str):
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    def _default_child(self):
        if self.label_names:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        return self.labels()

    def _make_child(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def expose(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            children = list(self._children.items())
        for key, child in children:
            lines.extend(self._expose_child(key, child))
        return lines

    def _expose_child(self, key, child) -> list[str]:  # pragma: no cover
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def _expose_child(self, key, child) -> list[str]:
        return [f"{self.name}{_fmt_labels(key)} {_fmt_value(child.value)}"]


class _GaugeChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def _expose_child(self, key, child) -> list[str]:
        return [f"{self.name}{_fmt_labels(key)} {_fmt_value(child.value)}"]


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "_lock")

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * len(buckets)  # cumulative on exposition
        self.total = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            if i < len(self.counts):
                self.counts[i] += 1
            self.total += value
            self.count += 1


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Iterable[str] = (),
        buckets: Iterable[float] = _DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def time(self):
        """Context manager observing the elapsed wall time."""
        return _Timer(self._default_child())

    def _expose_child(self, key, child) -> list[str]:
        lines = []
        cum = 0
        with child._lock:
            counts = list(child.counts)
            total, count = child.total, child.count
        for le, n in zip(child.buckets, counts):
            cum += n
            le_label = 'le="%s"' % _fmt_value(le)
            lines.append(
                f"{self.name}_bucket{_fmt_labels(key, le_label)} {cum}"
            )
        inf_label = 'le="+Inf"'
        lines.append(
            f"{self.name}_bucket{_fmt_labels(key, inf_label)} {count}"
        )
        lines.append(f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(total)}")
        lines.append(f"{self.name}_count{_fmt_labels(key)} {count}")
        return lines


class _Timer:
    def __init__(self, child: _HistogramChild):
        self._child = child

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._child.observe(time.perf_counter() - self._t0)


class Registry:
    """Holds metrics; renders the exposition document.

    ``add_collector`` registers an on-scrape callback that refreshes gauges
    from live objects (e.g. a batcher's running stats) right before every
    exposition — the pull-model analog of client_golang's Collector
    interface, so instrumented objects never need their own publish loop.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: dict[object, object] = {}

    def add_collector(self, fn, key: object | None = None) -> None:
        """Call ``fn()`` before each exposition; ``key`` enables removal."""
        with self._lock:
            self._collectors[key if key is not None else fn] = fn

    def remove_collector(self, key: object) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if (
                    type(existing) is not type(metric)
                    or existing.label_names != metric.label_names
                    or getattr(existing, "buckets", None)
                    != getattr(metric, "buckets", None)
                ):
                    raise ValueError(
                        f"metric {metric.name} re-registered with a "
                        "different type, labels, or buckets"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help: str, labels: Iterable[str] = ()) -> Counter:
        return self._register(Counter(name, help, labels))  # type: ignore[return-value]

    def gauge(self, name: str, help: str, labels: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge(name, help, labels))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help: str,
        labels: Iterable[str] = (),
        buckets: Iterable[float] = _DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram(name, help, labels, buckets))  # type: ignore[return-value]

    def expose(self) -> str:
        with self._lock:
            collectors = list(self._collectors.values())
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — one bad collector must not
                pass  # take down the whole /metrics endpoint
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


#: Process-wide default registry — what ObsServer serves and the
#: orchestrator/serve planes instrument by default.
REGISTRY = Registry()
