"""End-to-end request tracing: one trace id from the gateway edge to the
decode chunk.

The reference platform has no first-party tracer — request observability
stops at per-controller ``/metrics`` (SURVEY.md §5.1). This module is the
OpenTelemetry-shaped, dependency-free equivalent: a W3C
``traceparent``-style context minted at the gateway (or accepted from the
client) rides the ``x-kft-trace`` header through every hop alongside the
deadline/priority contract, and each hop records nested spans into a
bounded per-process buffer.

Design constraints, in order:

1. **Lock-light recorder.** Spans are recorded from the engine loop
   thread between device dispatches; ``start``/``end``/``record_span``
   are O(1) dict/list operations under one uncontended lock and NEVER
   touch device values (the jax-sync lint pass covers this file).
2. **Bounded memory.** Live traces, spans per trace, and every retention
   pool are capped; an abandoned trace (leaked span) is evicted, not
   accumulated.
3. **Tail-based sampling.** A finished trace is always kept when any
   span ended non-ok (error / shed / deadline / watchdog-poisoned) or
   when its duration reaches the rolling p99 of recent traces; the
   healthy fast majority is 1-in-N sampled. Under overload the
   interesting traces survive, the boring ones pay the memory bill.
4. **Zero cost when disabled.** ``Tracer.enabled = False`` returns a
   falsy no-op span from every call — instrumentation sites guard with
   ``if span:`` so no header is stamped, no timestamp taken, and
   responses are byte-identical.

Clocks: span timestamps are ``time.monotonic()`` (interval arithmetic
only); one wall-clock timestamp is stamped per finished trace for humans.

Export: ``Tracer.snapshot()`` feeds ``GET /debug/traces`` (ModelServer),
``/api/traces`` (dashboard), and ``to_perfetto()`` converts a snapshot to
Chrome/Perfetto ``trace_event`` JSON (``kft trace dump --perfetto``).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from contextvars import ContextVar
from typing import Any, Mapping

from kubeflow_tpu.obs import names, prom
from kubeflow_tpu.obs.headers import TRACE_HEADER

__all__ = [
    "TRACE_HEADER",
    "Span",
    "TraceContext",
    "Tracer",
    "TRACER",
    "ctx_from_headers",
    "current_ids",
    "observe_request_latency",
    "to_perfetto",
]

#: span end-statuses that force a trace into the always-keep pool
_KEEP_STATUSES = frozenset({"error", "shed", "deadline", "poisoned"})

SAMPLER_DECISIONS = prom.REGISTRY.counter(
    names.TRACE_SAMPLER_DECISIONS_TOTAL,
    "tail-sampler verdicts on finished traces",
    ("decision",),
)

#: per-model server-side TTFT/TPOT, derived from the engine span stream
#: (first pushed token / steady-state inter-token gap of traced requests;
#: warmup never carries a trace context so it never pollutes these)
TTFT_MS = prom.REGISTRY.histogram(
    names.SERVER_TTFT_MS,
    "server-side time-to-first-token of traced requests (ms)",
    ("model",),
    buckets=prom.MS_BUCKETS,
)
TPOT_MS = prom.REGISTRY.histogram(
    names.SERVER_TPOT_MS,
    "server-side mean time-per-output-token after the first (ms)",
    ("model",),
    buckets=prom.MS_BUCKETS,
)


def observe_request_latency(
    model: str, *, ttft_ms: float | None = None, tpot_ms: float | None = None
) -> None:
    """Record the latency split of one completed traced request."""
    if ttft_ms is not None:
        TTFT_MS.labels(model=model).observe(ttft_ms)
    if tpot_ms is not None:
        TPOT_MS.labels(model=model).observe(tpot_ms)


# --------------------------------------------------------------- context


class TraceContext:
    """The wire-portable half of a span: ids + sampled flag.

    Header shape is W3C traceparent's: ``00-<trace32>-<span16>-<flags>``.
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    @staticmethod
    def parse(value: str | None) -> "TraceContext | None":
        """Strictly parse a traceparent-shaped header; anything malformed
        is treated as absent (a hostile header must not break routing)."""
        if not value:
            return None
        parts = value.strip().split("-")
        if len(parts) != 4 or parts[0] != "00":
            return None
        trace_id, span_id, flags = parts[1], parts[2], parts[3]
        if len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
            return None
        try:
            int(trace_id, 16), int(span_id, 16), int(flags, 16)
        except ValueError:
            return None
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return TraceContext(trace_id, span_id, int(flags, 16) & 1 == 1)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.header()})"


def ctx_from_headers(headers: Mapping[str, str] | None) -> TraceContext | None:
    """Trace context carried by ``headers`` (CIMultiDict or plain dict —
    probe both spellings, the deadline.py idiom)."""
    if not headers:
        return None
    raw = headers.get(TRACE_HEADER) or headers.get(TRACE_HEADER.title())
    return TraceContext.parse(raw)


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


# ----------------------------------------------------------------- spans


class Span:
    """One timed operation. Mutated only by the hop that owns it."""

    __slots__ = (
        "trace_id",
        "span_id",
        "parent_span_id",
        "name",
        "start",
        "end_time",
        "attrs",
        "events",
        "status",
        "_tracer",
    )

    def __init__(
        self,
        tracer: "Tracer",
        trace_id: str,
        span_id: str,
        parent_span_id: str | None,
        name: str,
        start: float,
    ):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.start = start
        self.end_time: float | None = None
        self.attrs: dict[str, Any] = {}
        self.events: list[tuple[str, float, dict[str, Any]]] = []
        self.status = "ok"

    def __bool__(self) -> bool:
        return True

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def header(self) -> str:
        """The ``x-kft-trace`` value propagating THIS span as parent."""
        return self.ctx.header()

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def event(self, name: str, **attrs: Any) -> None:
        self.events.append((name, time.monotonic(), attrs))

    def end(self, status: str | None = None) -> None:
        if self.end_time is not None:  # idempotent: first end wins
            return
        if status is not None:
            self.status = status
        self.end_time = time.monotonic()
        self._tracer._on_end(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.end_time is None:
            self.set_attr("error", f"{exc_type.__name__}: {exc}")
            self.end("error")
        else:
            self.end()


class _NoopSpan:
    """Falsy stand-in when tracing is disabled — every method a no-op, so
    instrumentation sites stay branch-free beyond ``if span:``."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    @property
    def ctx(self) -> None:
        return None

    def header(self) -> str:
        return ""

    def set_attr(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def end(self, status: str | None = None) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _TraceRec:
    """Accumulates a trace's spans until every locally-open span ended."""

    __slots__ = ("trace_id", "spans", "open", "dropped", "t_created")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: list[Span] = []
        self.open = 0
        self.dropped = 0
        self.t_created = time.monotonic()


# ---------------------------------------------------------------- tracer


class Tracer:
    """Process-global span recorder with tail-based retention.

    A trace finishes when its locally-open span count returns to zero
    (the refcount survives retries and hedges: the gateway's ``route``
    span stays open across attempts, a cancelled hedge loser holds the
    trace live until its span unwinds). Finished traces are classified
    once and filed into bounded ring buffers:

    - ``errors``  — any span ended error/shed/deadline/poisoned (kept
      100%, the acceptance bar for explaining failures under load);
    - ``slow``    — root duration ≥ the rolling p99 of a bounded
      duration reservoir (recomputed every 64 finishes);
    - ``sampled`` — 1-in-``sample_every`` of the healthy remainder.
    """

    def __init__(
        self,
        *,
        enabled: bool | None = None,
        max_live: int = 2048,
        max_spans_per_trace: int = 512,
        keep_errors: int = 256,
        keep_slow: int = 64,
        keep_sampled: int = 64,
        sample_every: int = 16,
        p99_window: int = 512,
    ):
        if enabled is None:
            enabled = os.environ.get("KFT_TRACE", "1").lower() not in (
                "0", "false", "off",
            )
        self.enabled = enabled
        self.sample_every = max(1, int(sample_every))
        self._max_live = max_live
        self._max_spans = max_spans_per_trace
        self._lock = threading.Lock()
        self._live: dict[str, _TraceRec] = {}
        self._errors: collections.deque = collections.deque(maxlen=keep_errors)
        self._slow: collections.deque = collections.deque(maxlen=keep_slow)
        self._sampled: collections.deque = collections.deque(maxlen=keep_sampled)
        self._durations: collections.deque = collections.deque(maxlen=p99_window)
        self._p99_ms = float("inf")
        self._finished = 0

    # -- recording ----------------------------------------------------- #

    def span(
        self,
        name: str,
        *,
        parent: "Span | None" = None,
        ctx: TraceContext | None = None,
        start: float | None = None,
    ) -> "Span | _NoopSpan":
        """Open a span: child of ``parent`` (local span) or of ``ctx``
        (remote parent off the wire); with neither, mint a new trace."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is not None and parent:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif ctx is not None:
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        else:
            trace_id, parent_id = _new_trace_id(), None
        s = Span(
            self, trace_id, _new_span_id(), parent_id, name,
            time.monotonic() if start is None else start,
        )
        with self._lock:
            rec = self._rec_locked(trace_id)
            rec.open += 1
            if len(rec.spans) < self._max_spans:
                rec.spans.append(s)
            else:
                rec.dropped += 1
        return s

    def record_span(
        self,
        name: str,
        *,
        parent: "Span | None" = None,
        ctx: TraceContext | None = None,
        start: float = 0.0,
        end: float = 0.0,
        attrs: dict[str, Any] | None = None,
        status: str = "ok",
    ) -> None:
        """Record an already-completed span retroactively — the decode
        path stamps chunk boundaries and reports them at drain time so
        the engine loop never holds an open span per chunk."""
        if not self.enabled:
            return
        if parent is not None and parent:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif ctx is not None:
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        else:
            return
        s = Span(self, trace_id, _new_span_id(), parent_id, name, start)
        s.end_time = end
        s.status = status
        if attrs:
            s.attrs.update(attrs)
        with self._lock:
            rec = self._live.get(trace_id)
            if rec is None:
                # late fragment (trace already finalized): drop rather
                # than resurrect a second partial trace under the same id
                return
            if len(rec.spans) < self._max_spans:
                rec.spans.append(s)
            else:
                rec.dropped += 1

    def _rec_locked(self, trace_id: str) -> _TraceRec:
        rec = self._live.get(trace_id)
        if rec is None:
            while len(self._live) >= self._max_live:  # evict oldest live
                stale_id = next(iter(self._live))
                self._finalize_locked(self._live.pop(stale_id), evicted=True)
            rec = _TraceRec(trace_id)
            self._live[trace_id] = rec
        return rec

    def _on_end(self, span: Span) -> None:
        with self._lock:
            rec = self._live.get(span.trace_id)
            if rec is None:
                return
            rec.open -= 1
            if rec.open <= 0:
                del self._live[span.trace_id]
                self._finalize_locked(rec)

    # -- tail sampling ------------------------------------------------- #

    def _finalize_locked(self, rec: _TraceRec, evicted: bool = False) -> None:
        spans = [s for s in rec.spans if s.end_time is not None] or rec.spans
        if not spans:
            return
        t0 = min(s.start for s in spans)
        t1 = max(s.end_time if s.end_time is not None else s.start for s in spans)
        duration_ms = (t1 - t0) * 1e3
        keep = None
        for s in spans:
            if s.status in _KEEP_STATUSES:
                keep = s.status
                break
        self._finished += 1
        self._durations.append(duration_ms)
        if self._finished % 64 == 0 and self._durations:
            ordered = sorted(self._durations)
            self._p99_ms = ordered[min(len(ordered) - 1,
                                       int(0.99 * len(ordered)))]
        doc = self._render_locked(rec, spans, t0, duration_ms, evicted)
        if keep is not None:
            doc["kept"] = keep
            self._errors.append(doc)
            SAMPLER_DECISIONS.labels(decision="error").inc()
        elif len(self._durations) >= 64 and duration_ms >= self._p99_ms:
            doc["kept"] = "slow_p99"
            self._slow.append(doc)
            SAMPLER_DECISIONS.labels(decision="slow").inc()
        elif self._finished % self.sample_every == 0:
            doc["kept"] = "sampled"
            self._sampled.append(doc)
            SAMPLER_DECISIONS.labels(decision="sampled").inc()
        else:
            SAMPLER_DECISIONS.labels(decision="dropped").inc()

    @staticmethod
    def _render_locked(
        rec: _TraceRec, spans: list[Span], t0: float,
        duration_ms: float, evicted: bool,
    ) -> dict[str, Any]:
        def ms(t: float | None) -> float:
            return round(((t if t is not None else t0) - t0) * 1e3, 3)

        return {
            "trace_id": rec.trace_id,
            "kept": "",
            "duration_ms": round(duration_ms, 3),
            # wall-clock stamp for humans reading the export; every
            # interval in the trace is monotonic-derived
            "wall_time": time.time(),  # kft: noqa[monotonic-clock] — display timestamp, never used in interval arithmetic
            "evicted": evicted,
            "dropped_spans": rec.dropped,
            "spans": [
                {
                    "span_id": s.span_id,
                    "parent_span_id": s.parent_span_id,
                    "name": s.name,
                    "start_ms": ms(s.start),
                    "end_ms": ms(s.end_time),
                    "status": s.status,
                    "attrs": dict(s.attrs),
                    "events": [
                        {"name": n, "ts_ms": ms(t), "attrs": dict(a)}
                        for n, t, a in s.events
                    ],
                }
                for s in sorted(spans, key=lambda s: s.start)
            ],
        }

    # -- export -------------------------------------------------------- #

    def snapshot(self, limit: int = 64) -> dict[str, Any]:
        """Retained traces, newest first, errors before slow before
        sampled — what ``/debug/traces`` and the dashboard serve."""
        with self._lock:
            pools = (
                list(self._errors), list(self._slow), list(self._sampled),
            )
            live = len(self._live)
            p99 = self._p99_ms
            finished = self._finished
        seen: set[str] = set()
        out: list[dict[str, Any]] = []
        for pool in pools:
            for doc in reversed(pool):
                if doc["trace_id"] in seen:
                    continue
                seen.add(doc["trace_id"])
                out.append(doc)
                if len(out) >= limit:
                    break
            if len(out) >= limit:
                break
        return {
            "traces": out,
            "live": live,
            "finished": finished,
            "p99_ms": None if p99 == float("inf") else round(p99, 3),
        }

    def clear(self) -> None:
        """Drop all retained and live traces (test isolation)."""
        with self._lock:
            self._live.clear()
            self._errors.clear()
            self._slow.clear()
            self._sampled.clear()
            self._durations.clear()
            self._p99_ms = float("inf")
            self._finished = 0


#: Process-wide default tracer — every hop records here, the way REGISTRY
#: is the process-wide default metric registry.
TRACER = Tracer()


# ----------------------------------------------------- log correlation

_CURRENT: ContextVar["Span | None"] = ContextVar("kft-current-span", default=None)


def set_current(span: "Span | _NoopSpan"):
    """Bind ``span`` as the ambient span for log correlation; returns a
    token for :func:`reset_current`."""
    return _CURRENT.set(span if span else None)


def reset_current(token) -> None:
    _CURRENT.reset(token)


def current_ids() -> tuple[str, str] | None:
    """(trace_id, span_id) of the ambient span, for log records."""
    span = _CURRENT.get()
    if span is None:
        return None
    return span.trace_id, span.span_id


# ------------------------------------------------------ perfetto export


def to_perfetto(snapshot: dict[str, Any] | list[dict[str, Any]]) -> dict[str, Any]:
    """Convert a :meth:`Tracer.snapshot` document (or its ``traces``
    list) to Chrome/Perfetto ``trace_event`` JSON: one process per
    trace, complete ("X") events for spans, instant ("i") events for
    span events — load the result straight into ``ui.perfetto.dev``."""
    traces = snapshot["traces"] if isinstance(snapshot, dict) else snapshot
    events: list[dict[str, Any]] = []
    for pidx, tr in enumerate(traces):
        pid = pidx + 1
        label = f"trace {tr['trace_id'][:8]}"
        if tr.get("kept"):
            label += f" [{tr['kept']}]"
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        for s in tr.get("spans", ()):
            ts = round(s["start_ms"] * 1e3, 3)
            dur = round(max(s["end_ms"] - s["start_ms"], 0.0) * 1e3, 3)
            args = dict(s.get("attrs", {}))
            args["span_id"] = s["span_id"]
            if s.get("parent_span_id"):
                args["parent_span_id"] = s["parent_span_id"]
            if s.get("status", "ok") != "ok":
                args["status"] = s["status"]
            events.append({
                "ph": "X", "name": s["name"], "cat": "kft",
                "pid": pid, "tid": 1, "ts": ts, "dur": dur, "args": args,
            })
            for ev in s.get("events", ()):
                events.append({
                    "ph": "i", "s": "t", "name": ev["name"], "cat": "kft",
                    "pid": pid, "tid": 1,
                    "ts": round(ev["ts_ms"] * 1e3, 3),
                    "args": dict(ev.get("attrs", {})),
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
