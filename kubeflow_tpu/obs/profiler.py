"""Profiling triggers + the per-process observability server.

The reference has no first-party tracer; controllers expose /metrics and
training-side profiling is user-space TensorBoard (SURVEY.md §5.1). On TPU
the XLA profiler is dramatically richer — op-level MXU/HBM/ICI utilization
— so the framework makes it a first-class endpoint on every long-running
process (trainer, model server, controller):

- ``GET /healthz``            → liveness (200 ok)
- ``GET /metrics``            → Prometheus exposition of ``prom.REGISTRY``
- ``POST /profile?seconds=2`` → ``jax.profiler`` trace into the logdir,
  viewable with tensorboard-plugin-profile (installed in this image)
- ``GET /debug/state``        → optional JSON state dump hook

The server runs an aiohttp app on a daemon thread (same stack as the
serving plane — SURVEY.md §0: no fastapi/uvicorn in this image).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import threading
import time
from pathlib import Path
from typing import Any, Callable

from kubeflow_tpu.obs import prom
from kubeflow_tpu.obs.trace import TRACER, ctx_from_headers
from kubeflow_tpu.obs.webhost import ThreadedAiohttpServer

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def capture_trace(logdir: str | Path):
    """Trace everything inside the block into ``logdir`` (XLA ops + host)."""
    import jax

    Path(logdir).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(logdir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_step(fn: Callable[[], Any], logdir: str | Path, name: str = "step") -> Any:
    """Profile one call (e.g. a single jitted train step) under a named
    annotation; returns the call's result."""
    import jax

    with capture_trace(logdir):
        with jax.profiler.TraceAnnotation(name):
            out = fn()
        jax.block_until_ready(out)
    return out


class ObsServer(ThreadedAiohttpServer):
    """Observability sidecar-in-process. Thread-hosted aiohttp app."""

    thread_name = "kft-obs-server"

    def __init__(
        self,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: prom.Registry | None = None,
        profile_logdir: str | Path | None = None,
        state_fn: Callable[[], Any] | None = None,
    ):
        super().__init__(host=host, port=port)
        self.registry = registry or prom.REGISTRY
        self.profile_logdir = Path(profile_logdir or "profiles")
        self.state_fn = state_fn
        self._profiling = threading.Lock()

    # -- handlers ------------------------------------------------------- #

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="ok")

    async def _metrics(self, request):
        from aiohttp import web

        return web.Response(
            text=self.registry.expose(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def _profile(self, request):
        from aiohttp import web

        seconds = float(request.query.get("seconds", "2"))
        seconds = max(0.05, min(seconds, 300.0))
        logdir = self.profile_logdir / time.strftime("%Y%m%d-%H%M%S")
        if not self._profiling.acquire(blocking=False):
            return web.json_response(
                {"error": "a profile capture is already running"}, status=409
            )
        # the capture itself becomes a span: traces answer "who triggered
        # an XLA profile, when, and where did the dump land"
        span = TRACER.span(
            "profile.capture", ctx=ctx_from_headers(request.headers)
        )
        if span:
            span.set_attr("logdir", str(logdir))
            span.set_attr("seconds", seconds)

        def run():
            try:
                with capture_trace(logdir):
                    time.sleep(seconds)
            finally:
                self._profiling.release()

        # Trace on an executor thread: the capture brackets whatever the
        # process's compute threads do during the window, without blocking
        # the event loop.
        try:
            await asyncio.get_running_loop().run_in_executor(None, run)
        except Exception:
            span.end("error")
            raise
        span.end()
        return web.json_response(
            {"logdir": str(logdir), "seconds": seconds}
        )

    async def _state(self, request):
        from aiohttp import web

        if self.state_fn is None:
            return web.json_response({}, status=404)
        return web.Response(
            text=json.dumps(self.state_fn(), default=str),
            content_type="application/json",
        )

    # -- lifecycle ------------------------------------------------------ #

    def _make_app(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_post("/profile", self._profile)
        app.router.add_get("/debug/state", self._state)
        return app

    def start(self) -> "ObsServer":
        super().start()
        logger.info("obs server on http://%s:%d", self.host, self.port)
        return self
