"""Structured JSON logging for every framework process.

The reference's controllers log structured (zap/klog key-values) so fleet
log pipelines can index reconcile events; SURVEY.md §5.5 carries that
requirement over. One formatter, enabled per-process with
``configure_json_logging()``; gang identity fields (job/replica/rank) are
stamped automatically from the orchestrator's env wiring so every line from
every worker is attributable without parsing free text.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
import traceback

from kubeflow_tpu.obs import trace
from kubeflow_tpu.orchestrator import envwire


def _gang_identity() -> dict[str, str]:
    out = {}
    for field, var in (
        ("job", envwire.ENV_JOB_NAME),
        ("job_uid", envwire.ENV_JOB_UID),
        ("replica_type", envwire.ENV_REPLICA_TYPE),
        ("replica_index", envwire.ENV_REPLICA_INDEX),
        ("attempt", envwire.ENV_ATTEMPT),
    ):
        v = os.environ.get(var)
        if v is not None:
            out[field] = v
    return out


class JsonFormatter(logging.Formatter):
    def __init__(self, *, static_fields: dict[str, str] | None = None):
        super().__init__()
        self.static_fields = dict(static_fields or {})
        self.static_fields.update(_gang_identity())

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 6),
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
            **self.static_fields,
        }
        ids = trace.current_ids()
        if ids is not None:
            entry["trace_id"], entry["span_id"] = ids
        if record.exc_info and record.exc_info[0] is not None:
            entry["exc"] = "".join(
                traceback.format_exception(*record.exc_info)
            ).rstrip()
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            entry.update(extra)
        return json.dumps(entry, default=str)


def configure_json_logging(
    level: int = logging.INFO,
    *,
    stream=None,
    static_fields: dict[str, str] | None = None,
) -> logging.Handler:
    """Install a JSON handler on the root logger (replacing prior handlers
    installed by this function; idempotent)."""
    root = logging.getLogger()
    for h in list(root.handlers):
        if getattr(h, "_kft_json", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter(static_fields=static_fields))
    handler._kft_json = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.setLevel(level)
    return handler
