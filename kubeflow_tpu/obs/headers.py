"""The single definition site for the ``x-kft-*`` wire-header contract.

Every hop of the serving path (gateway → activator → dataplane → batcher →
engine) reads or stamps these headers; before this module existed the
deadline/priority names lived in ``serve/deadline.py`` while the tenant
header was a bare literal at two gateway call sites — a rename was a grep,
not a diff. All readers and stampers now import from here (``serve/deadline``
re-exports the deadline/priority trio for back-compat).

Semantics (the full contracts live with their consumers):

- ``x-kft-deadline-ms`` — remaining end-to-end budget in milliseconds,
  client- or gateway-set, REWRITTEN by the gateway at each dispatch
  (serve/deadline.py).
- ``x-kft-deadline-abs`` — process-local absolute ``time.monotonic()``
  deadline stamped once at DataPlane admission. Never crosses a process:
  the gateway strips it off the wire in both directions.
- ``x-kft-priority`` — integer tenant priority (higher = shed last),
  gateway-authoritative for managed tenants.
- ``x-kft-tenant`` — tenant identity for rate limiting / priority lookup.
- ``x-kft-trace`` — W3C ``traceparent``-shaped trace context
  (``00-<trace32hex>-<span16hex>-<flags2hex>``), minted at the gateway or
  accepted from the client, re-stamped with a child span id at every hop
  (obs/trace.py).

Header maps on the read side may be aiohttp ``CIMultiDict`` or plain
``dict``; readers probe the exact lowercase name and its ``.title()``
spelling rather than lowercasing a copy per request (deadline.py idiom).
"""

from __future__ import annotations

#: wire header: remaining budget in milliseconds (client/gateway-set)
DEADLINE_HEADER = "x-kft-deadline-ms"
#: process-local absolute time.monotonic() deadline (DataPlane-stamped)
DEADLINE_ABS_HEADER = "x-kft-deadline-abs"
#: integer tenant priority, higher = shed last (gateway-stamped)
PRIORITY_HEADER = "x-kft-priority"
#: tenant identity for policy lookup (rate limit, in-flight cap, priority)
TENANT_HEADER = "x-kft-tenant"
#: W3C traceparent-shaped trace context (obs/trace.py mints and parses)
TRACE_HEADER = "x-kft-trace"
#: disaggregated serving: URL of the prefill-pool replica the decode
#: replica should pull this request's KV span from (gateway-stamped on
#: generate dispatches when the service has prefill-role backends;
#: stripped off the wire inbound — only the gateway may assert it)
PREFILL_PEER_HEADER = "x-kft-prefill-peer"
#: session identity for the host-RAM KV tier (client-set, opaque): turns
#: of the same session swap their KV span out/in across requests
SESSION_HEADER = "x-kft-session"
#: mid-stream failover resume contract: comma-separated generated token
#: ids the gateway already committed to the client. The engine admits
#: prompt+committed as a suffix-prefill (or a KV-span/host-tier hit) and
#: emits only tokens past the committed prefix. Gateway-stamped on resume
#: dispatches; stripped off the wire inbound — only the gateway may
#: assert a committed prefix
RESUME_TOKENS_HEADER = "x-kft-resume-tokens"
#: per-request sampling seed (gateway-stamped, deterministic from the
#: request id): temperature>0 rows draw token t from
#: fold_in(PRNGKey(seed), absolute_position_of_t), so a resumed stream on
#: ANY replica continues the exact sampling stream the dead replica began
SEED_HEADER = "x-kft-seed"
#: adapter identity (client-set, opaque): names the fine-tuned adapter a
#: request wants served (LoRA-style multi-adapter serving). Reserved for
#: adapter-aware routing; today it rides the wire untouched so the load
#: harness can exercise realistic per-tenant adapter mixes end to end
ADAPTER_HEADER = "x-kft-adapter"

__all__ = [
    "DEADLINE_HEADER",
    "DEADLINE_ABS_HEADER",
    "PRIORITY_HEADER",
    "TENANT_HEADER",
    "TRACE_HEADER",
    "PREFILL_PEER_HEADER",
    "SESSION_HEADER",
    "RESUME_TOKENS_HEADER",
    "SEED_HEADER",
    "ADAPTER_HEADER",
]
