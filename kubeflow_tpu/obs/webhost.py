"""Shared thread-hosted aiohttp server base.

Every long-lived HTTP surface in the framework (obs endpoints, the
dashboard, ad-hoc servers) runs the same way: an aiohttp app on a daemon
thread with its own event loop. This base owns that lifecycle once —
including the failure path: a bind error in the thread surfaces to the
``start()`` caller immediately (not after a timeout) and resets state so a
retry actually retries.
"""

from __future__ import annotations

import asyncio
import threading


class ThreadedAiohttpServer:
    """Subclass and implement ``_make_app() -> aiohttp.web.Application``."""

    thread_name = "kft-web"

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None
        self._started = threading.Event()
        self._settled = threading.Event()  # set on success OR failure
        self._start_error: BaseException | None = None

    def _make_app(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def start(self):
        if self._thread is not None:
            return self
        self._started.clear()
        self._settled.clear()
        self._start_error = None

        def run():
            from aiohttp import web

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def serve():
                runner = web.AppRunner(self._make_app())
                await runner.setup()
                site = web.TCPSite(runner, self.host, self.port)
                await site.start()
                self._runner = runner
                self.port = runner.addresses[0][1]
                self._started.set()
                self._settled.set()

            try:
                loop.run_until_complete(serve())
            except BaseException as e:  # noqa: BLE001 — reported to caller
                self._start_error = e
                self._settled.set()
                loop.close()
                return
            loop.run_forever()
            loop.run_until_complete(self._runner.cleanup())
            loop.close()

        self._thread = threading.Thread(
            target=run, daemon=True, name=self.thread_name
        )
        self._thread.start()
        self._settled.wait(timeout=10)
        if not self._started.is_set():
            # reset so a retry actually retries instead of no-opping
            self._thread.join(timeout=1)
            self._thread = None
            self._loop = None
            cause = self._start_error
            raise RuntimeError(
                f"{self.thread_name} failed to start: {cause}"
            ) from cause
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._loop = None
        self._started.clear()
        self._settled.clear()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
