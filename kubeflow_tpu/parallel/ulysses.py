"""Ulysses sequence parallelism: all_to_all seq<->heads re-sharding.

The DeepSpeed-Ulysses pattern (SURVEY.md §2.6 SP row), TPU-native: on entry
each rank holds all heads for a sequence shard; two ``lax.all_to_all``s swap
to all-sequence/head-shard around a standard (full-sequence) flash kernel,
then swap back. Cheaper than ring when heads >= ring size and sequence fits
per-chip after the head split; ring wins beyond that (SURVEY.md §5.7 chooses
per layer via config).
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.mesh import Axis
from kubeflow_tpu.ops.flash_attention import flash_attention


def ulysses_attention_local(
    q, k, v, *,
    axis_name: str = Axis.SEQ,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Inside shard_map: q/k/v are (B, H, S_local, D); H must divide the
    axis size. Returns (B, H, S_local, D)."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return flash_attention(
            q, k, v, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
    H = q.shape[1]
    if H % n:
        raise ValueError(
            f"Ulysses needs heads ({H}) divisible by seq axis size ({n}); "
            "use ring attention instead"
        )

    def seq_to_heads(x):  # (B, H, S/n, D) → (B, H/n, S, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):  # (B, H/n, S, D) → (B, H, S/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    o = flash_attention(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return heads_to_seq(o)


def ulysses_attention(
    q, k, v, mesh: Mesh, *,
    axis_name: str = Axis.SEQ,
    causal: bool = False,
    scale: float | None = None,
    interpret: bool = False,
):
    """Global-array convenience wrapper (batch over data, heads over model,
    seq over ``axis_name``)."""
    spec = P(Axis.DATA, Axis.MODEL, axis_name, None)

    def local(q, k, v):
        return ulysses_attention_local(
            q, k, v, axis_name=axis_name, causal=causal,
            scale=scale, interpret=interpret,
        )

    fn = jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)
