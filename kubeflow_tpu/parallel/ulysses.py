"""Ulysses sequence parallelism: all_to_all seq<->heads re-sharding.

The DeepSpeed-Ulysses pattern (SURVEY.md §2.6 SP row), TPU-native: on entry
each rank holds all heads for a sequence shard; two ``lax.all_to_all``s swap
to all-sequence/head-shard around a standard (full-sequence) flash kernel,
then swap back. Cheaper than ring when heads >= ring size and sequence fits
per-chip after the head split; ring wins beyond that (SURVEY.md §5.7 chooses
per layer via config).
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.collectives import axis_size, shard_map

from kubeflow_tpu.core.mesh import Axis
from kubeflow_tpu.ops.flash_attention import flash_attention
from kubeflow_tpu.parallel.ring_attention import global_seg_operand


def ulysses_attention_local(
    q, k, v, *,
    axis_name: str = Axis.SEQ,
    causal: bool = False,
    scale: float | None = None,
    segment_ids=None,
    block_q: int | None = 128,
    block_k: int | None = 128,
    interpret: bool = False,
):
    """Inside shard_map: q/k/v are (B, H, S_local, D); H must divide the
    axis size. ``segment_ids`` (B, S_local) gives packed-sequence
    block-diagonal masking. Returns (B, H, S_local, D). None block sizes
    resolve per the FULL-sequence shapes the inner kernel sees (after the
    all_to_all the local view is full-seq, head-sharded)."""
    seg_kw = {}
    if segment_ids is not None:
        # after the all_to_all each rank attends over the FULL sequence, so
        # it needs the full segment vector — a (B, S) int gather, cheap
        # next to the qkv all_to_alls
        full_seg = lax.all_gather(
            segment_ids, axis_name, axis=1, tiled=True
        )
        seg_kw = {"q_segment_ids": full_seg, "kv_segment_ids": full_seg}
    n = axis_size(axis_name)
    if n == 1:
        return flash_attention(
            q, k, v, causal=causal, scale=scale, **seg_kw,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
    H = q.shape[1]
    if H % n:
        raise ValueError(
            f"Ulysses needs heads ({H}) divisible by seq axis size ({n}); "
            "use ring attention instead"
        )

    def seq_to_heads(x):  # (B, H, S/n, D) → (B, H/n, S, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):  # (B, H/n, S, D) → (B, H, S/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    o = flash_attention(
        q, k, v, causal=causal, scale=scale, **seg_kw,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return heads_to_seq(o)


def ulysses_attention(
    q, k, v, mesh: Mesh, *,
    axis_name: str = Axis.SEQ,
    causal: bool = False,
    scale: float | None = None,
    segment_ids=None,
    interpret: bool = False,
):
    """Global-array convenience wrapper (batch over data, heads over model,
    seq over ``axis_name``); ``segment_ids`` (B, S) for packed sequences
    shards with the seq axis."""
    spec = P(Axis.DATA, Axis.MODEL, axis_name, None)
    seg_spec = P(Axis.DATA, axis_name)
    has_seg = segment_ids is not None

    def local(q, k, v, seg):
        return ulysses_attention_local(
            q, k, v, axis_name=axis_name, causal=causal,
            scale=scale, segment_ids=seg if has_seg else None,
            interpret=interpret,
        )

    fn = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec, check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v, global_seg_operand(mesh, seg_spec, segment_ids, q))
