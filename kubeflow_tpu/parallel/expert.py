"""Expert parallelism: MoE routing + dispatch over the ``expert`` axis.

The DeepSpeed-MoE row of SURVEY.md §2.6, in the canonical TPU (GShard/
Switch) dense-dispatch form: top-k routing builds a (tokens, experts,
capacity) dispatch tensor, expert inputs/outputs are einsums against it, and
``with_sharding_constraint`` over the ``expert`` axis makes XLA emit the
token all_to_all on ICI — no manual collective code, which is exactly the
TPU-native translation of the reference's explicit all_to_all dispatch.

Includes the standard load-balancing auxiliary loss and router z-loss.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.core.mesh import Axis, current_mesh


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """Sharding constraint that no-ops outside a mesh context (pure
    single-device use keeps working)."""
    mesh = current_mesh()
    if mesh.empty or Axis.EXPERT not in mesh.axis_names:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    expert_dim: int = 256        # per-expert FFN hidden dim
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    z_loss_weight: float = 1e-3


def router_probs(logits: jax.Array) -> jax.Array:
    return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)


def top_k_routing(
    probs: jax.Array, k: int, capacity: int
) -> tuple[jax.Array, jax.Array]:
    """Build combine/dispatch tensors.

    probs: (T, E). Returns combine (T, E, C) float and dispatch (T, E, C)
    bool. Tokens beyond an expert's capacity are dropped (Switch semantics).
    Position within each expert's buffer is assigned in token order via a
    cumulative count over the top-k choice masks.
    """
    T, E = probs.shape
    _, top_idx = jax.lax.top_k(probs, k)               # (T, k)
    onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # (T, k, E)

    # Position of each (token, choice) in its expert's buffer: tokens first,
    # then choice rank (priority to primary experts at equal token index).
    flat = onehot.transpose(1, 0, 2).reshape(k * T, E)  # choice-major
    pos_flat = jnp.cumsum(flat, axis=0) - flat          # (k*T, E)
    pos = pos_flat.reshape(k, T, E).transpose(1, 0, 2)  # (T, k, E)

    within = (pos < capacity) & (onehot > 0)            # (T, k, E)
    gate = probs[:, None, :] * onehot                   # (T, k, E)
    # renormalize over the k kept choices
    denom = jnp.sum(gate * within, axis=(1, 2), keepdims=True)
    gate = jnp.where(within, gate, 0.0) / jnp.maximum(denom, 1e-9)

    pos_clip = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
    cap_onehot = jax.nn.one_hot(pos_clip, capacity, dtype=jnp.float32)  # (T,k,E,C)
    combine = jnp.einsum("tke,tkec->tec", gate, cap_onehot * within[..., None])
    dispatch = combine > 0.0
    return combine, dispatch


def load_balancing_loss(probs: jax.Array, dispatch: jax.Array) -> jax.Array:
    """Switch-style aux loss: E * dot(mean router prob, mean tokens/expert)."""
    E = probs.shape[-1]
    density = jnp.mean(dispatch.any(-1).astype(jnp.float32), axis=0)  # (E,)
    mean_prob = jnp.mean(probs, axis=0)                                # (E,)
    return E * jnp.sum(density * mean_prob)


def moe_ffn(
    x: jax.Array,                 # (T, d_model) token activations
    router_kernel: jax.Array,     # (d_model, E)
    up_kernel: jax.Array,         # (E, d_model, expert_dim)
    down_kernel: jax.Array,       # (E, expert_dim, d_model)
    cfg: MoEConfig,
) -> tuple[jax.Array, jax.Array, dict]:
    """Dense-dispatch MoE FFN. Returns (out (T, d_model), aux_loss, stats)."""
    T, d = x.shape
    E = cfg.num_experts
    capacity = max(int(cfg.capacity_factor * cfg.top_k * T / E), 1)

    logits = x.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    probs = router_probs(logits)
    combine, dispatch = top_k_routing(probs, cfg.top_k, capacity)

    # all_to_all moment #1: token-sharded → expert-sharded (XLA emits it
    # from this constraint when x is dp/fsdp-sharded and buffers are
    # expert-sharded).
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch.astype(x.dtype), x
    )
    expert_in = _constrain(expert_in, P(Axis.EXPERT, None, None))
    h = jnp.einsum("ecd,edf->ecf", expert_in, up_kernel.astype(x.dtype))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, down_kernel.astype(x.dtype))
    expert_out = _constrain(expert_out, P(Axis.EXPERT, None, None))
    # all_to_all moment #2: back to token sharding, weighted combine.
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)

    aux = cfg.aux_loss_weight * load_balancing_loss(probs, dispatch)
    z = cfg.z_loss_weight * jnp.mean(
        jax.nn.logsumexp(logits, axis=-1) ** 2
    )
    stats = {
        "moe_dropped_frac": 1.0
        - jnp.sum(dispatch.astype(jnp.float32)) / (cfg.top_k * T),
        "moe_aux_loss": aux,
        "moe_z_loss": z,
    }
    return out.astype(x.dtype), aux + z, stats
