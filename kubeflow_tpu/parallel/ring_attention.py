"""Ring attention: context parallelism over the ``seq`` mesh axis.

SURVEY.md §5.7's headline differentiator. Sequence-sharded Q stays put; the
KV shards rotate around the ICI ring via ``lax.ppermute`` (torus neighbors →
each hop is a single physical link), and every rank merges the per-block
partial attention results with online-softmax algebra. Memory per chip is
O(S/n · S/n) blockwise — never the full S×S matrix — which is what makes
million-token contexts fit.

The per-block compute is the Pallas flash kernel
(``kubeflow_tpu.ops.flash_attention``) with ``return_residuals=True`` — its
(out, logsumexp) pairs are exactly the mergeable form. The backward pass is
a second ring sweep: dq accumulates at home, dk/dv accumulate on the
rotating shard and arrive home after n hops (both passes are n ppermutes of
the same payload size — communication-optimal for the ring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.collectives import axis_size, shard_map

from kubeflow_tpu.core.mesh import Axis
from kubeflow_tpu.ops.flash_attention import (
    NEG_INF,
    flash_attention,
    flash_attention_bwd,
    float0_zeros,
    reference_attention,
)


def global_seg_operand(mesh, seg_spec, segment_ids, q):
    """Shared wrapper plumbing: shard_map needs a concrete seg operand even
    when the caller passed None — substitute zeros (ignored by the local fn
    when has_seg is False) and place it on the seq-sharded layout."""
    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:1] + q.shape[2:3], jnp.int32)
    return jax.device_put(segment_ids, NamedSharding(mesh, seg_spec))


def _rotate(x, axis_name: str):
    """One ring hop: shard i → shard i+1."""
    n = axis_size(axis_name)
    return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def _block_flash(q, k, v, q_seg, kv_seg, *, step: int, src, me, causal,
                 scale, block_q, block_k, interpret):
    """Partial attention of local q vs the kv shard currently held (from
    ring rank ``src``). Returns (out, lse).

    ``step`` is a Python int (the ring loop is unrolled), so the causal
    structure resolves statically where possible: step 0 always holds the
    home shard (src == me → diagonal block); later steps are never
    diagonal, leaving one traced full-vs-skip choice. This keeps each hop
    to a single flash kernel instead of tracing all three branches."""
    B, H, S, D = q.shape

    seg_kw = (
        {"q_segment_ids": q_seg, "kv_segment_ids": kv_seg}
        if q_seg is not None
        else {}
    )

    def full(_):
        return flash_attention(
            q, k, v, causal=False, scale=scale, **seg_kw,
            block_q=block_q, block_k=block_k,
            interpret=interpret, return_residuals=True,
        )

    def skip(_):
        return (
            jnp.zeros_like(q),
            jnp.full((B, H, S), NEG_INF, jnp.float32),
        )

    if not causal:
        return full(None)
    if step == 0:
        return flash_attention(
            q, k, v, causal=True, scale=scale, **seg_kw,
            block_q=block_q, block_k=block_k,
            interpret=interpret, return_residuals=True,
        )
    return lax.cond(src < me, full, skip, None)


def _merge(o, lse, o_t, lse_t):
    """Online-softmax merge of normalized partials (o, lse)."""
    lse_new = jnp.logaddexp(lse, lse_t)
    w = jnp.exp(lse - lse_new)[..., None]
    w_t = jnp.exp(lse_t - lse_new)[..., None]
    return o * w + o_t * w_t.astype(o.dtype), lse_new


def _ring_fwd_pass(
    q, k, v, q_seg, kv_seg, axis_name, causal, scale, block_q, block_k,
    interpret,
):
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    o = jnp.zeros_like(q)
    lse = jnp.full((B, H, S), NEG_INF, jnp.float32)
    for step in range(n):
        src = (me - step) % n  # whose kv shard we currently hold
        o_t, lse_t = _block_flash(
            q, k, v, q_seg, kv_seg, step=step, src=src, me=me,
            causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
        o, lse = _merge(o, lse, o_t, lse_t)
        if step != n - 1:
            k = _rotate(k, axis_name)
            v = _rotate(v, axis_name)
            if kv_seg is not None:
                # the segment labels belong to the kv shard: they ride the
                # same ring hop so masking stays aligned with the data
                kv_seg = _rotate(kv_seg, axis_name)
    return o, lse


# --------------------------------------------------------------------------- #
# custom VJP (operates on LOCAL shards inside shard_map)
# --------------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ring_local(q, k, v, q_seg, kv_seg, axis_name, causal, scale, blocks,
                interpret):
    o, _ = _ring_fwd_pass(
        q, k, v, q_seg, kv_seg, axis_name, causal, scale, blocks[0],
        blocks[1], interpret
    )
    return o


def _ring_local_fwd(q, k, v, q_seg, kv_seg, axis_name, causal, scale,
                    blocks, interpret):
    o, lse = _ring_fwd_pass(
        q, k, v, q_seg, kv_seg, axis_name, causal, scale, blocks[0],
        blocks[1], interpret
    )
    return o, (q, k, v, q_seg, kv_seg, o, lse)


def _ring_local_bwd(axis_name, causal, scale, blocks, interpret, res, do):
    """Second ring sweep reusing the Pallas backward kernel per hop.

    The forward saved the GLOBAL (merged) out/lse, so each hop's
    ``flash_attention_bwd`` — probabilities normalized against the global
    lse — yields exactly that kv shard's partial terms of the global
    softmax gradient. Peak memory per hop is O(block_q × block_k), same as
    the forward; the whole-shard S×S matrix is never built.
    """
    block_q, block_k = blocks
    q, k, v, q_seg, kv_seg, o, lse = res
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)

    dq = jnp.zeros_like(q, dtype=jnp.float32)
    dk = jnp.zeros_like(k, dtype=jnp.float32)  # rides the ring with k,v
    dv = jnp.zeros_like(v, dtype=jnp.float32)

    def hop(step, src, k, v, kv_seg):
        # mirrors _block_flash's static structure: step 0 = diagonal,
        # later causal steps = traced full-vs-skip, non-causal = full
        seg_kw = (
            {"q_segment_ids": q_seg, "kv_segment_ids": kv_seg}
            if q_seg is not None
            else {}
        )

        def bwd(hop_causal):
            return flash_attention_bwd(
                q, k, v, o, lse, do, causal=hop_causal, scale=scale,
                **seg_kw,
                block_q=block_q, block_k=block_k, interpret=interpret,
            )

        def skip(_):
            return (
                jnp.zeros_like(q, dtype=jnp.float32),
                jnp.zeros_like(k, dtype=jnp.float32),
                jnp.zeros_like(v, dtype=jnp.float32),
            )

        if not causal:
            return bwd(False)
        if step == 0:
            return bwd(True)
        return lax.cond(src < me, lambda _: bwd(False), skip, None)

    for step in range(n):
        src = (me - step) % n  # whose kv shard we currently hold
        dq_t, dk_t, dv_t = hop(step, src, k, v, kv_seg)
        dq = dq + dq_t
        dk = dk + dk_t
        dv = dv + dv_t
        if step != n - 1:
            k = _rotate(k, axis_name)
            v = _rotate(v, axis_name)
            if kv_seg is not None:
                kv_seg = _rotate(kv_seg, axis_name)
            dk = _rotate(dk, axis_name)
            dv = _rotate(dv, axis_name)
    # after n-1 hops the accumulators sit one hop short of home
    dk = _rotate(dk, axis_name)
    dv = _rotate(dv, axis_name)
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
        float0_zeros(q_seg), float0_zeros(kv_seg),
    )


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)


def ring_attention_local(
    q, k, v, *,
    axis_name: str = Axis.SEQ,
    causal: bool = False,
    scale: float | None = None,
    segment_ids=None,
    block_q: int | None = 128,
    block_k: int | None = 128,
    interpret: bool = False,
):
    """Ring attention on LOCAL seq shards — call inside shard_map where
    ``axis_name`` is a mesh axis and q/k/v are (B, H, S_local, D).
    ``segment_ids`` (B, S_local): packed-sequence block-diagonal masking —
    the local labels mask q, and a rotating copy rides the ring with each
    kv shard. ``block_q``/``block_k`` None → per-LOCAL-shape selection
    (ops/flash_tuning.py), resolved here once so fwd and bwd hops agree."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if block_q is None or block_k is None:
        from kubeflow_tpu.ops.flash_tuning import resolve_blocks

        block_q, block_k = resolve_blocks(q, k, block_q, block_k)
    return _ring_local(
        q, k, v, segment_ids, segment_ids, axis_name, causal, scale,
        (block_q, block_k), interpret
    )


def ring_attention(
    q, k, v, mesh: Mesh, *,
    axis_name: str = Axis.SEQ,
    causal: bool = False,
    scale: float | None = None,
    segment_ids=None,
    interpret: bool = False,
):
    """Global-array convenience wrapper: shards seq over ``axis_name``,
    batch over data, heads over model; ``segment_ids`` (B, S) for packed
    sequences shards with the seq axis."""
    spec = P(Axis.DATA, Axis.MODEL, axis_name, None)
    seg_spec = P(Axis.DATA, axis_name)
    has_seg = segment_ids is not None

    def local(q, k, v, seg):
        return ring_attention_local(
            q, k, v, axis_name=axis_name, causal=causal,
            scale=scale, segment_ids=seg if has_seg else None,
            interpret=interpret,
        )

    fn = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec, check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v, global_seg_operand(mesh, seg_spec, segment_ids, q))
