"""Rule-based parameter sharding: param path regex → PartitionSpec.

The reference stack's TP/FSDP layouts live in user containers as
Megatron/DeepSpeed config (SURVEY.md §2.6 rows FSDP/TP); TPU-natively they
are just PartitionSpecs over named mesh axes, assigned here by first-match
path rules (the t5x/maxtext idiom, re-implemented):

- FSDP:  shard a big dim of every weight over ``fsdp``; XLA inserts the
  ZeRO all-gather (params) / reduce-scatter (grads) on ICI.
- TP:    Megatron pattern over ``model``: column-parallel in-projections
  (qkv, ffn-up) shard the OUTPUT dim; row-parallel out-projections (attn-o,
  ffn-down) shard the INPUT dim, so each pair needs one psum, which XLA
  emits from the specs.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Sequence

import jax
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.core.mesh import Axis


def path_str(path) -> str:
    """jax key-path → 'layers/0/attn/q_proj/kernel' style string."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Ordered (regex, PartitionSpec) pairs; first match wins.

    ``default`` applies when nothing matches (P() = replicate). Call the
    instance on a param pytree to get the spec tree (the ``param_spec_fn``
    contract of ``kubeflow_tpu.train.loop.Trainer``).
    """

    rules: Sequence[tuple[str, P]]
    default: P = P()

    def spec_for(self, path: str, shape: tuple[int, ...]) -> P:
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                if len([a for a in spec if a is not None]) > len(shape):
                    raise ValueError(
                        f"rule {pattern!r} spec {spec} has more axes than "
                        f"param {path} shape {shape}"
                    )
                return spec
        return self.default

    def __call__(self, params: Any) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(path_str(path), leaf.shape),
            params,
        )

    def validate_divisibility(self, params: Any, mesh_shape: dict[str, int]) -> None:
        """Fail fast when a sharded dim doesn't divide by its axis size."""

        def check(path, leaf):
            spec = self.spec_for(path_str(path), leaf.shape)
            for dim, axes in enumerate(spec):
                if axes is None:
                    continue
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    n = mesh_shape.get(ax, 1)
                    if leaf.shape[dim] % n:
                        raise ValueError(
                            f"{path_str(path)} dim {dim} ({leaf.shape[dim]}) "
                            f"not divisible by axis {ax!r} size {n}"
                        )

        jax.tree_util.tree_map_with_path(check, params)


def transformer_rules(
    *,
    fsdp: bool = True,
    tensor: bool = True,
) -> ShardingRules:
    """Standard rules for ``kubeflow_tpu.models.transformer`` param names.

    Matrix layout conventions (flax kernels are (in, out)):

    - embed/unembed: shard vocab over model (TP) + d_model over fsdp
    - q/k/v proj (in=d_model, out=heads*head_dim): column-parallel → out dim
      over ``model``; fsdp shards the in dim
    - o proj (in=heads*head_dim, out=d_model): row-parallel → in dim over
      ``model``; fsdp shards the out dim
    - mlp up/gate (in=d_model, out=d_ff): column-parallel
    - mlp down (in=d_ff, out=d_model): row-parallel
    - layernorm scales/biases: replicated
    """
    m = Axis.MODEL if tensor else None
    f = Axis.FSDP if fsdp else None
    rules: list[tuple[str, P]] = [
        (r"embed/embedding$", P(m, f)),            # (vocab, d_model)
        (r"(q_proj|k_proj|v_proj)/kernel$", P(f, m)),
        (r"o_proj/kernel$", P(m, f)),
        (r"(up_proj|gate_proj)/kernel$", P(f, m)),
        (r"down_proj/kernel$", P(m, f)),
        (r"unembed/kernel$", P(f, m)),             # (d_model, vocab)
        (r"(q_proj|k_proj|v_proj|up_proj|gate_proj)/bias$", P(m)),
        (r"(scale|bias)$", P()),
        # MoE experts: (n_experts, in, out) — expert dim over expert axis
        (r"experts/(up|gate)_kernel$", P(Axis.EXPERT, f, m)),
        (r"experts/down_kernel$", P(Axis.EXPERT, m, f)),
        (r"experts/router_kernel$", P(f, None)),
    ]
    return ShardingRules(tuple(rules))
