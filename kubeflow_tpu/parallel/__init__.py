"""Parallelism strategies as named mesh axes (SURVEY.md §2.6 matrix).

Every row of the reference stack's strategy table is first-class here:

- DP/FSDP:  ``sharding`` rules (replicate vs shard params over ``fsdp``)
- TP:       ``sharding`` Megatron-style column/row rules over ``model``
- PP:       ``pipeline`` GPipe microbatching over ``pipe``
- SP:       ``ulysses`` all_to_all seq<->heads re-sharding over ``seq``
- CP:       ``ring_attention`` ppermute KV rotation over ``seq``
- EP:       ``expert`` all_to_all token dispatch over ``expert``
"""

from kubeflow_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    transformer_rules,
)
