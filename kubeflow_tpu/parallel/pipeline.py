"""Pipeline parallelism: SPMD GPipe over the ``pipe`` mesh axis.

The Megatron/DeepSpeed pipeline-engine row of SURVEY.md §2.6, TPU-native:
instead of P2P sends between per-stage processes, every rank runs the SAME
program (SPMD); stage s holds its layer shard, microbatch activations hop to
the next stage with one ``lax.ppermute`` per tick, and bubble ticks are
predicated out with ``jnp.where``. The whole schedule is differentiable, so
the 1B1F backward schedule falls out of autodiff (reverse ppermutes) with no
custom VJP.

Tick layout (GPipe): T = n_micro + n_stages - 1 ticks; at tick t stage s
works on microbatch (t - s). With n_micro >> n_stages the bubble fraction
(n_stages-1)/T amortizes away.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.mesh import Axis


def spmd_pipeline_local(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,  # (n_micro, mb, ...) — consumed by stage 0
    *,
    axis_name: str = Axis.PIPE,
) -> jax.Array:
    """Run inside shard_map over ``axis_name``.

    ``stage_params`` are THIS stage's params (callers shard a stacked
    param tree over the axis). Returns (n_micro, mb, ...) outputs (valid on
    every rank — the last stage's results are broadcast back with a psum
    over one-hot masking).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = n_micro + n - 1

    def tick(carry, t):
        state, outputs = carry  # state: (mb, ...) activation entering this stage
        mb_idx = t - s
        # stage 0 injects a fresh microbatch on ticks 0..n_micro-1
        inject = jnp.logical_and(s == 0, t < n_micro)
        x_inject = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, n_micro - 1), keepdims=False
        )
        x_in = jnp.where(inject, x_inject, state)
        active = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, x_in)  # bubble ticks pass through
        # last stage banks its finished microbatch
        bank = jnp.logical_and(s == n - 1, active)
        idx = jnp.clip(mb_idx, 0, n_micro - 1)
        current = lax.dynamic_index_in_dim(outputs, idx, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, current), idx, axis=0
        )
        # activations hop to the next stage
        state = lax.ppermute(
            y, axis_name, [(i, (i + 1) % n) for i in range(n)]
        )
        return (state, outputs), None

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    outputs0 = jnp.zeros((n_micro, *mb_shape), microbatches.dtype)
    (_, outputs), _ = lax.scan(
        tick, (state0, outputs0), jnp.arange(ticks)
    )
    # broadcast the last stage's outputs to every rank
    is_last = (s == n - 1).astype(outputs.dtype)
    return lax.psum(outputs * is_last, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,   # leaves with leading dim = n_stages
    x: jax.Array,          # (batch, ...) global input
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis_name: str = Axis.PIPE,
    batch_axes: tuple[str, ...] = (Axis.DATA, Axis.FSDP),
) -> jax.Array:
    """Global wrapper: shard stacked stage params over ``axis_name``, split
    the batch into microbatches, run the SPMD pipeline."""
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible into {n_microbatches} microbatches")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked param leading dim {leaf.shape[0]} != pipe axis {n_stages}"
            )
    mb = batch // n_microbatches
    xm = x.reshape(n_microbatches, mb, *x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    x_spec = P(None, batch_axes)  # microbatch dim replicated, batch sharded

    def local(params_stage, xm_local):
        # params arrive with a leading stage dim of 1 on each shard
        squeezed = jax.tree_util.tree_map(lambda p: p[0], params_stage)
        return spmd_pipeline_local(
            stage_fn, squeezed, xm_local, axis_name=axis_name
        )

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    stacked_params = jax.tree_util.tree_map(
        lambda leaf, spec: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec)
        ) if isinstance(leaf, jax.Array) else leaf,
        stacked_params, param_specs,
    )
    out = fn(stacked_params, xm)
    return out.reshape(batch, *out.shape[2:])
