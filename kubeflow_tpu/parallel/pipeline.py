"""Pipeline parallelism: SPMD GPipe over the ``pipe`` mesh axis.

The Megatron/DeepSpeed pipeline-engine row of SURVEY.md §2.6, TPU-native:
instead of P2P sends between per-stage processes, every rank runs the SAME
program (SPMD); stage s holds its layer shard, microbatch activations hop to
the next stage with one ``lax.ppermute`` per tick, and bubble ticks are
predicated out with ``jnp.where``. The whole schedule is differentiable, so
the 1B1F backward schedule falls out of autodiff (reverse ppermutes) with no
custom VJP.

Tick layout (GPipe): T = n_micro + n_stages - 1 ticks; at tick t stage s
works on microbatch (t - s). With n_micro >> n_stages the bubble fraction
(n_stages-1)/T amortizes away.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.collectives import axis_size, shard_map

from kubeflow_tpu.core.mesh import Axis


def spmd_pipeline_local(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,  # (n_micro, mb, ...) — consumed by stage 0
    *,
    axis_name: str = Axis.PIPE,
) -> jax.Array:
    """Run inside shard_map over ``axis_name``.

    ``stage_params`` are THIS stage's params (callers shard a stacked
    param tree over the axis). Returns (n_micro, mb, ...) outputs (valid on
    every rank — the last stage's results are broadcast back with a psum
    over one-hot masking).
    """
    n = axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = n_micro + n - 1

    def tick(carry, t):
        state, outputs = carry  # state: (mb, ...) activation entering this stage
        mb_idx = t - s
        # stage 0 injects a fresh microbatch on ticks 0..n_micro-1
        inject = jnp.logical_and(s == 0, t < n_micro)
        x_inject = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, n_micro - 1), keepdims=False
        )
        x_in = jnp.where(inject, x_inject, state)
        active = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        y = stage_fn(stage_params, x_in)
        y = jnp.where(active, y, x_in)  # bubble ticks pass through
        # last stage banks its finished microbatch
        bank = jnp.logical_and(s == n - 1, active)
        idx = jnp.clip(mb_idx, 0, n_micro - 1)
        current = lax.dynamic_index_in_dim(outputs, idx, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, current), idx, axis=0
        )
        # activations hop to the next stage
        state = lax.ppermute(
            y, axis_name, [(i, (i + 1) % n) for i in range(n)]
        )
        return (state, outputs), None

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    outputs0 = jnp.zeros((n_micro, *mb_shape), microbatches.dtype)
    (_, outputs), _ = lax.scan(
        tick, (state0, outputs0), jnp.arange(ticks)
    )
    # broadcast the last stage's outputs to every rank
    is_last = (s == n - 1).astype(outputs.dtype)
    return lax.psum(outputs * is_last, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,   # leaves with leading dim = n_stages
    x: jax.Array,          # (batch, ...) global input
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis_name: str = Axis.PIPE,
    batch_axes: tuple[str, ...] = (Axis.DATA, Axis.FSDP),
) -> jax.Array:
    """Global wrapper: shard stacked stage params over ``axis_name``, split
    the batch into microbatches, run the SPMD pipeline."""
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible into {n_microbatches} microbatches")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked param leading dim {leaf.shape[0]} != pipe axis {n_stages}"
            )
    mb = batch // n_microbatches
    xm = x.reshape(n_microbatches, mb, *x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    x_spec = P(None, batch_axes)  # microbatch dim replicated, batch sharded

    def local(params_stage, xm_local):
        # params arrive with a leading stage dim of 1 on each shard
        squeezed = jax.tree_util.tree_map(lambda p: p[0], params_stage)
        return spmd_pipeline_local(
            stage_fn, squeezed, xm_local, axis_name=axis_name
        )

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    stacked_params = jax.tree_util.tree_map(
        lambda leaf, spec: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec)
        ) if isinstance(leaf, jax.Array) else leaf,
        stacked_params, param_specs,
    )
    out = fn(stacked_params, xm)
    return out.reshape(batch, *out.shape[2:])


# --------------------------------------------------------------------------- #
# 1F1B schedule (SURVEY.md §2.6 PP row: "microbatch schedule (1F1B/GPipe)")
# --------------------------------------------------------------------------- #


def live_activation_buffers(
    schedule: str, n_stages: int, n_microbatches: int
) -> int:
    """Peak per-stage stashed stage-input activations for a schedule.

    GPipe runs every forward before any backward, so each stage must keep
    one residual per microbatch: m buffers. The lockstep SPMD 1F1B below
    starts microbatch j's backward at stage s exactly ``2*(n-1-s)`` ticks
    after its forward, so a circular buffer of ``2*(n_stages-1)+1`` slots
    suffices — independent of the microbatch count, which is the whole
    point of 1F1B at realistic m (VERDICT r3 missing #4).
    """
    if schedule == "gpipe":
        return n_microbatches
    if schedule == "1f1b":
        return 2 * (n_stages - 1) + 1
    raise ValueError(f"unknown schedule {schedule!r}")


def pipeline_value_and_grad(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    n_microbatches: int,
    schedule: str = "1f1b",
    axis_name: str = Axis.PIPE,
    batch_axes: tuple[str, ...] = (Axis.DATA, Axis.FSDP),
) -> tuple[jax.Array, Any]:
    """(loss, param_grads) for ``loss = mean_j loss_fn(y_j)`` through the
    pipeline, under the chosen microbatch schedule.

    ``loss_fn`` maps one microbatch of final-stage activations to a scalar
    (mean over its elements), so the total equals ``loss_fn`` of the whole
    batch for any elementwise-mean loss. ``schedule="gpipe"`` differentiates
    the scan in ``pipeline_apply`` (all forwards, then all backwards —
    residuals live per microbatch); ``schedule="1f1b"`` runs the
    one-forward-one-backward lockstep schedule with a bounded circular
    residual stash and hand-threaded VJPs: at tick t stage s forwards
    microbatch ``t - s`` and backwards microbatch ``t - 2(n-1) + s``, with
    cotangents hopping stage-to-stage over reverse ICI ``ppermute``. The
    two schedules compute identical math (same per-microbatch loss
    cotangents, same per-stage VJPs) — only residual lifetime and
    accumulation order differ.
    """
    if schedule == "gpipe":

        def total_loss(p):
            y = pipeline_apply(
                stage_fn, p, x, mesh,
                n_microbatches=n_microbatches,
                axis_name=axis_name, batch_axes=batch_axes,
            )
            ym = y.reshape(n_microbatches, -1, *y.shape[1:])
            losses = jax.vmap(loss_fn)(ym)
            return losses.mean()

        return jax.value_and_grad(total_loss)(stacked_params)
    if schedule != "1f1b":
        raise ValueError(f"unknown schedule {schedule!r}")

    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible into {n_microbatches} microbatches"
        )
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked param leading dim {leaf.shape[0]} != pipe axis {n_stages}"
            )
    mb = batch // n_microbatches
    xm = x.reshape(n_microbatches, mb, *x.shape[1:])
    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    x_spec = P(None, batch_axes)

    def local(params_stage, xm_local):
        params = jax.tree_util.tree_map(lambda p: p[0], params_stage)
        n = axis_size(axis_name)
        s = lax.axis_index(axis_name)
        m = xm_local.shape[0]
        mb_shape = xm_local.shape[1:]
        stash = live_activation_buffers("1f1b", n, m)
        ticks = m + 2 * (n - 1)

        def tick(carry, t):
            fwd_state, ct_state, resid, grads, loss_acc = carry
            # ---------- forward half: microbatch jf = t - s ---------- #
            jf = t - s
            active_f = jnp.logical_and(jf >= 0, jf < m)
            inject = jnp.logical_and(s == 0, t < m)
            x_inj = lax.dynamic_index_in_dim(
                xm_local, jnp.clip(t, 0, m - 1), keepdims=False
            )
            x_in = jnp.where(inject, x_inj, fwd_state)
            y = stage_fn(params, x_in)
            y_out = jnp.where(active_f, y, x_in)
            # stash this stage input for the backward tick (slot = jf mod
            # stash; lifetime 2(n-1-s) < stash guarantees no clobber)
            slot = jnp.mod(jnp.clip(jf, 0, m - 1), stash)
            old = lax.dynamic_index_in_dim(resid, slot, keepdims=False)
            resid = lax.dynamic_update_index_in_dim(
                resid, jnp.where(active_f, x_in, old), slot, axis=0
            )
            # ---------- backward half: jb = t - 2(n-1) + s ---------- #
            jb = t - 2 * (n - 1) + s
            active_b = jnp.logical_and(jb >= 0, jb < m)
            # last stage: loss cotangent of the microbatch it JUST forwarded
            # (for s == n-1, jb == jf — backward starts the same tick)
            loss_j, dy_loss = jax.value_and_grad(loss_fn)(y)
            ct_in = jnp.where(s == n - 1, dy_loss / m, ct_state)
            x_saved = lax.dynamic_index_in_dim(
                resid, jnp.mod(jnp.clip(jb, 0, m - 1), stash), keepdims=False
            )
            _, vjp = jax.vjp(stage_fn, params, x_saved)
            dparams, dx = vjp(ct_in)
            # select, don't multiply: bubble-tick VJPs run on the zero
            # residual, and a stage whose gradient is non-finite at 0 would
            # poison the accumulator through NaN*0
            grads = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(active_b, d, jnp.zeros_like(d)),
                grads,
                dparams,
            )
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(s == n - 1, active_f), loss_j / m, 0.0
            )
            # activation hop forward, cotangent hop backward
            fwd_state = lax.ppermute(
                y_out, axis_name, [(i, (i + 1) % n) for i in range(n)]
            )
            ct_state = lax.ppermute(
                jnp.where(active_b, dx, jnp.zeros_like(dx)),
                axis_name,
                [(i, (i - 1) % n) for i in range(n)],
            )
            return (fwd_state, ct_state, resid, grads, loss_acc), None

        zeros_mb = jnp.zeros(mb_shape, x.dtype)
        carry0 = (
            zeros_mb,
            zeros_mb,
            jnp.zeros((stash, *mb_shape), x.dtype),
            jax.tree_util.tree_map(jnp.zeros_like, params),
            jnp.zeros((), jnp.float32),
        )
        (_, _, _, grads, loss_acc), _ = lax.scan(
            tick, carry0, jnp.arange(ticks)
        )
        # loss lives on the last stage; params are replicated across batch
        # axes, so their grads (and the loss) average across those shards
        loss = lax.psum(loss_acc, axis_name)
        if batch_axes:
            loss = lax.pmean(loss, batch_axes)
            grads = lax.pmean(grads, batch_axes)
        grads = jax.tree_util.tree_map(lambda g: g[None], grads)
        return loss, grads

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
    return fn(stacked_params, xm)
