"""kubeflow_tpu: a TPU-native ML platform.

A from-scratch framework with the capabilities of the Kubeflow GPU execution
plane (training-job orchestration, model serving, hyperparameter tuning,
pipelines), re-designed TPU-first:

- ``core``        — device mesh / ICI+DCN topology, ``jax.distributed``
                    bootstrap, collective helpers.
- ``orchestrator``— the JAXJob control plane: declarative job specs with
                    ReplicaSpec/RunPolicy/gang-scheduling semantics, a
                    reconciler engine, and a process-gang launcher.
- ``train``       — SPMD training loop, Orbax checkpointing, metric writers.
- ``models``      — flax model zoo (MNIST CNN, ResNet, BERT, TransformerLM, MoE).
- ``parallel``    — DP/FSDP/TP/PP/SP(Ulysses)/CP(ring attention)/EP as named
                    mesh axes.
- ``ops``         — Pallas TPU kernels (flash attention, ring attention, ...).
- ``serve``       — TPUPredictor model server (KServe-equivalent data plane).
- ``tune``        — hyperparameter tuning (Katib-equivalent).
- ``pipelines``   — DAG pipelines (KFP-equivalent).
- ``obs``         — profiling, metrics, failure supervision.

Scope and semantics follow ``SURVEY.md`` (structural analysis of the
zxhx/kubeflow reference); the reference mount was empty at survey and build
time (SURVEY.md §0), so reference citations in docstrings use the upstream
Kubeflow layout and are tagged UNVERIFIED.
"""

__version__ = "0.1.0"
