"""Activator: park requests while a service has zero ready backends.

Reference analog: the Knative activator (SURVEY.md §2.2) — when a service
is scaled to zero, the activator sits in the data path, buffers requests,
pokes the autoscaler, and replays the buffer once a pod is up. Here the
same contract fronts real ``ModelServer`` processes:

- a request arriving with no eligible backend parks in a **bounded FIFO**
  per service (overflow → ``QueueOverflow`` ⇒ 429, deadline →
  ``ActivationTimeout`` ⇒ 503 — the two Knative envelope semantics);
- parking kicks ``scale_up(service)`` once per cold episode (not per
  request), which is where a controller loads the model / starts a
  replica **off the request path** — the synchronous cold-start load that
  used to live inside ``controller.route()`` happens here, concurrently
  with the client waiting;
- when the pool reports a backend ready, the queue flushes strictly in
  admission order (the event loop wakes futures FIFO).

Event-loop confined: no threads, no locks.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict, deque
from typing import Callable

from kubeflow_tpu.obs import names, prom

QUEUE_DEPTH = prom.REGISTRY.gauge(
    names.GATEWAY_QUEUE_DEPTH,
    "requests parked in the activator FIFO",
    ("service",),
)
#: the same depth under its autoscaler-facing name: parked demand counts
#: as concurrency (autoscale/signals.py), or scale-from-zero never fires
ACTIVATOR_QUEUE_DEPTH = prom.REGISTRY.gauge(
    names.GATEWAY_ACTIVATOR_QUEUE_DEPTH,
    "autoscaler input: requests parked in the activator FIFO",
    ("service",),
)
COLD_EPISODE = prom.REGISTRY.gauge(
    names.GATEWAY_ACTIVATOR_COLD_EPISODE,
    "1 while a cold-episode scale-up kick is outstanding",
    ("service",),
)
ACTIVATIONS = prom.REGISTRY.counter(
    names.GATEWAY_ACTIVATIONS_TOTAL,
    "scale-from-zero kicks issued by the activator",
    ("service",),
)


class QueueOverflow(Exception):
    """Parked-queue capacity exceeded — shed with 429."""


class ActivationTimeout(Exception):
    """No backend became ready within the deadline — shed with 503."""


class Activator:
    def __init__(
        self,
        *,
        queue_limit: int = 256,
        timeout_s: float = 30.0,
        scale_up: Callable[[str], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.queue_limit = queue_limit
        self.timeout_s = timeout_s
        self.scale_up = scale_up
        self._clock = clock
        self._parked: dict[str, deque[asyncio.Future]] = {}
        #: services with a scale-up kick outstanding; cleared on flush so
        #: the next cold episode kicks again. Ordered for stable views.
        self._kicked: OrderedDict[str, float] = OrderedDict()

    def depth(self, service: str) -> int:
        return len(self._parked.get(service, ()))

    async def wait(
        self,
        service: str,
        *,
        timeout_s: float | None = None,
        span=None,
    ) -> None:
        """Park until ``notify(service)`` — admission order preserved.

        ``span`` (the gateway's ``activator.park`` span) records how deep
        the request parked and whether the episode ended in activation or
        a timeout."""
        q = self._parked.setdefault(service, deque())
        if len(q) >= self.queue_limit:
            if span:
                span.event("overflow", parked=len(q))
            raise QueueOverflow(
                f"activator queue for {service!r} is full "
                f"({self.queue_limit} parked)"
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        q.append(fut)
        if span:
            span.set_attr("parked_depth", len(q))
        QUEUE_DEPTH.labels(service=service).set(len(q))
        ACTIVATOR_QUEUE_DEPTH.labels(service=service).set(len(q))
        if service not in self._kicked and self.scale_up is not None:
            self._kicked[service] = self._clock()
            ACTIVATIONS.labels(service=service).inc()
            COLD_EPISODE.labels(service=service).set(1)
            try:
                self.scale_up(service)
            except Exception:  # noqa: BLE001 — a failed kick must not kill
                pass  # the parked request; the deadline still bounds it
        try:
            await asyncio.wait_for(
                fut, self.timeout_s if timeout_s is None else timeout_s
            )
            if span:
                span.event("activated")
        except asyncio.TimeoutError:
            if span:
                span.event("timeout")
            raise ActivationTimeout(
                f"no backend for {service!r} became ready in time"
            ) from None
        finally:
            if fut in q:
                q.remove(fut)
            QUEUE_DEPTH.labels(service=service).set(len(q))
            ACTIVATOR_QUEUE_DEPTH.labels(service=service).set(len(q))

    def notify(self, service: str) -> None:
        """A backend for ``service`` is ready: wake every parked waiter in
        admission (FIFO) order. Waiters re-select a backend themselves —
        the first may consume capacity, later ones may re-park."""
        self._kicked.pop(service, None)
        COLD_EPISODE.labels(service=service).set(0)
        q = self._parked.get(service)
        if not q:
            return
        # snapshot: waking a future triggers its finally-removal from q
        for fut in list(q):
            if not fut.done():
                fut.set_result(True)

    def view(self) -> dict:
        return {
            "queue_depth": {s: len(q) for s, q in self._parked.items() if q},
            "pending_scale_ups": list(self._kicked),
        }
