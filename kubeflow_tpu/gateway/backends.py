"""Backend pool: the gateway's view of real server replicas.

Reference analogs: Envoy's cluster model as Istio deploys it (endpoint
health checking, outlier detection, circuit breaking — SURVEY.md §2.2) and
Knative's revision-backed endpoints. A ``Backend`` is one live
``ModelServer`` process addressed by URL; the pool owns everything about
its fitness to receive traffic:

- **readiness probing** — ``GET /v2/health/ready`` on an interval; a
  backend that fails ``eject_threshold`` consecutive probes is ejected
  (outlier detection) and re-admitted on the first passing probe;
- **circuit breaking** — request outcomes drive a per-backend breaker:
  ``failure_threshold`` consecutive failures open it, after ``recovery_s``
  it goes half-open and admits ONE trial request; success closes it,
  failure re-opens. Open/half-open state is visible on /metrics so a
  flapping replica is diagnosable from the edge;
- **drain-aware removal** — ``drain()`` stops new selection immediately
  and removes the backend once its last in-flight request releases, so
  rolling restarts are lossless;
- **least-outstanding selection** — the balancer picks the eligible
  backend with the fewest in-flight requests (round-robin among ties, a
  counter rather than RNG so routing stays deterministic and seedless).

Everything here runs on the gateway's event loop — no threads, no locks.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from kubeflow_tpu.obs import names, prom

BREAKER_OPEN = prom.REGISTRY.gauge(
    names.GATEWAY_BREAKER_OPEN,
    "1 while this backend's circuit breaker is open or half-open",
    ("backend",),
)
BREAKER_OPENS = prom.REGISTRY.counter(
    names.GATEWAY_BREAKER_OPENS_TOTAL,
    "closed-to-open breaker transitions",
    ("backend",),
)
BACKENDS_READY = prom.REGISTRY.gauge(
    names.GATEWAY_BACKENDS_READY,
    "backends currently eligible for selection",
    ("service",),
)


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    failure_threshold: int = 3
    recovery_s: float = 5.0


class CircuitBreaker:
    """Per-backend request-outcome state machine (closed → open → half-open).

    ``clock`` is injectable so tests drive recovery without sleeping.
    """

    def __init__(
        self,
        config: BreakerConfig | None = None,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or BreakerConfig()
        self._clock = clock
        self.state = "closed"
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._trial_in_flight = False

    def current_state(self) -> str:
        """State after applying the open→half-open time transition."""
        if (
            self.state == "open"
            and self._clock() - self._opened_at >= self.config.recovery_s
        ):
            self.state = "half_open"
            self._trial_in_flight = False
        return self.state

    def allow(self) -> bool:
        """May a request be dispatched now? Half-open grants exactly one
        trial at a time; the trial's outcome decides the next state."""
        st = self.current_state()
        if st == "closed":
            return True
        if st == "half_open" and not self._trial_in_flight:
            self._trial_in_flight = True
            return True
        return False

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._trial_in_flight = False
        self.state = "closed"

    def record_failure(self) -> bool:
        """Returns True when this failure TRANSITIONS the breaker to open
        (callers count distinct opens, not every failed request)."""
        self.consecutive_failures += 1
        st = self.current_state()
        if st == "half_open":
            self.state = "open"
            self._opened_at = self._clock()
            self._trial_in_flight = False
            return False  # re-open of an already-tripped breaker
        if st == "closed" and (
            self.consecutive_failures >= self.config.failure_threshold
        ):
            self.state = "open"
            self._opened_at = self._clock()
            return True
        return False


@dataclasses.dataclass
class Backend:
    """One addressable server replica behind the gateway."""

    url: str
    service: str
    revision: str = "default"  # "default" | "canary"
    #: disaggregated-serving role: "both" replicas take any traffic,
    #: "decode" replicas take client traffic but skip prefill work (they
    #: pull KV spans from a peer), "prefill" replicas NEVER receive
    #: client requests — the gateway only hands their URL to decode
    #: replicas via the x-kft-prefill-peer header
    role: str = "both"  # "both" | "prefill" | "decode"
    state: str = "active"  # "active" | "draining"
    outstanding: int = 0
    probe_ok: bool = True  # optimistic until the first probe says otherwise
    consecutive_probe_failures: int = 0
    breaker: CircuitBreaker = dataclasses.field(default_factory=CircuitBreaker)

    def view(self) -> dict:
        return {
            "url": self.url,
            "service": self.service,
            "revision": self.revision,
            "role": self.role,
            "state": self.state,
            "outstanding": self.outstanding,
            "probe_ok": self.probe_ok,
            "breaker": self.breaker.current_state(),
        }


class BackendPool:
    """All backends the gateway may route to, keyed by service."""

    def __init__(
        self,
        *,
        breaker: BreakerConfig | None = None,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 2.0,
        eject_threshold: int = 3,
        on_ready: Callable[[str], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._breaker_cfg = breaker or BreakerConfig()
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.eject_threshold = eject_threshold
        #: called with the service name whenever a backend becomes eligible
        #: again (probe recovery, breaker close, new backend) — the
        #: activator flushes its parked queue off this signal
        self.on_ready = on_ready
        self._clock = clock
        self._backends: dict[str, list[Backend]] = {}
        self._rr: dict[str, int] = {}  # tie-break rotation per service

    # -- membership ------------------------------------------------------ #

    def add(
        self,
        service: str,
        url: str,
        *,
        revision: str = "default",
        role: str = "both",
    ) -> Backend:
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown backend role: {role!r}")
        existing = self.find(url)
        if existing is not None:
            # re-add of a draining/known URL revives it in place
            existing.state = "active"
            existing.service = service
            existing.revision = revision
            existing.role = role
            self._notify_ready(service)
            return existing
        b = Backend(
            url=url.rstrip("/"),
            service=service,
            revision=revision,
            role=role,
            breaker=CircuitBreaker(self._breaker_cfg, clock=self._clock),
        )
        self._backends.setdefault(service, []).append(b)
        self._refresh_ready_gauge(service)
        self._notify_ready(service)
        return b

    def find(self, url: str) -> Backend | None:
        url = url.rstrip("/")
        for blist in self._backends.values():
            for b in blist:
                if b.url == url:
                    return b
        return None

    def drain(self, url: str) -> None:
        """Stop selecting the backend; it is removed once its in-flight
        count hits zero (lossless rolling-restart removal)."""
        b = self.find(url)
        if b is None:
            return
        b.state = "draining"
        if b.outstanding == 0:
            self._remove(b)
        self._refresh_ready_gauge(b.service)

    def remove(self, url: str) -> None:
        b = self.find(url)
        if b is not None:
            self._remove(b)

    def _remove(self, b: Backend) -> None:
        blist = self._backends.get(b.service, [])
        if b in blist:
            blist.remove(b)
        self._refresh_ready_gauge(b.service)

    def services(self) -> list[str]:
        return sorted(self._backends)

    def backends_of(self, service: str) -> list[Backend]:
        return list(self._backends.get(service, []))

    # -- selection ------------------------------------------------------- #

    def selectable(self, service: str, revision: str | None = None) -> list[Backend]:
        """Backends eligible for new traffic (active, probe-healthy; the
        breaker filter happens in ``pick`` so half-open trials stay single).
        Prefill-role backends are never traffic-eligible: they only serve
        ``kv_span:prefill`` pulls from their decode peers."""
        return [
            b
            for b in self._backends.get(service, [])
            if b.state == "active"
            and b.probe_ok
            and b.role != "prefill"
            and (revision is None or b.revision == revision)
        ]

    def pick_prefill(self, service: str) -> Backend | None:
        """Least-outstanding healthy prefill-pool backend for a service,
        or None when the service runs colocated (no prefill backends —
        the common case, and the disagg fallback when every prefill
        replica is ejected/tripped)."""
        cands = [
            b
            for b in self._backends.get(service, [])
            if b.role == "prefill"
            and b.state == "active"
            and b.probe_ok
            and b.breaker.current_state() == "closed"
        ]
        if not cands:
            return None
        return min(cands, key=lambda b: (b.outstanding, b.url))

    def pick(
        self, service: str, revision: str | None = None,
        *, exclude: Backend | None = None,
    ) -> Backend | None:
        """Least-outstanding-requests among breaker-closed backends;
        falls back to granting one half-open trial when nothing is closed.
        ``exclude`` drops one backend from consideration when siblings
        exist (mid-stream failover must prefer a peer over the replica
        that just died, but a lone backend is still better than nothing —
        the watchdog may already be restarting its engine)."""
        base = self.selectable(service, revision)
        if exclude is not None and any(b is not exclude for b in base):
            base = [b for b in base if b is not exclude]
        closed = [b for b in base if b.breaker.current_state() == "closed"]
        if closed:
            low = min(b.outstanding for b in closed)
            tied = [b for b in closed if b.outstanding == low]
            i = self._rr.get(service, 0)
            self._rr[service] = i + 1
            return tied[i % len(tied)]
        # every healthy backend is tripped: probe the least-loaded one
        # whose breaker grants a trial (half-open single-request semantics)
        for b in sorted(base, key=lambda b: (b.outstanding, b.url)):
            if b.breaker.allow():
                return b
        return None

    def acquire(self, b: Backend) -> None:
        b.outstanding += 1

    def release(self, b: Backend) -> None:
        b.outstanding -= 1
        if b.state == "draining" and b.outstanding <= 0:
            self._remove(b)

    # -- request outcomes ------------------------------------------------ #

    def record(self, b: Backend, ok: bool) -> None:
        if ok:
            was_open = b.breaker.state != "closed"
            b.breaker.record_success()
            BREAKER_OPEN.labels(backend=b.url).set(0)
            if was_open:
                self._notify_ready(b.service)
        else:
            if b.breaker.record_failure():
                BREAKER_OPENS.labels(backend=b.url).inc()
            BREAKER_OPEN.labels(backend=b.url).set(
                0 if b.breaker.state == "closed" else 1
            )
        self._refresh_ready_gauge(b.service)

    # -- probing --------------------------------------------------------- #

    async def probe_all(self, session) -> None:
        """One probe sweep over every backend (the gateway's probe task
        calls this on ``probe_interval_s``). ``session`` is an aiohttp
        ClientSession owned by the caller."""
        import asyncio

        import aiohttp

        async def probe(b: Backend) -> None:
            ok = False
            try:
                async with session.get(
                    f"{b.url}/v2/health/ready",
                    timeout=aiohttp.ClientTimeout(total=self.probe_timeout_s),
                ) as resp:
                    ok = resp.status == 200 and bool(
                        (await resp.json()).get("ready", False)
                    )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                ok = False
            self.observe_probe(b, ok)

        backends = [b for bl in self._backends.values() for b in bl]
        if backends:
            await asyncio.gather(*[probe(b) for b in backends])

    def observe_probe(self, b: Backend, ok: bool) -> None:
        """Fold one probe result into ejection state (also the unit-test
        seam — tests drive ejection without HTTP)."""
        if ok:
            b.consecutive_probe_failures = 0
            if not b.probe_ok:
                b.probe_ok = True
                self._notify_ready(b.service)
        else:
            b.consecutive_probe_failures += 1
            if b.consecutive_probe_failures >= self.eject_threshold:
                b.probe_ok = False  # outlier ejected until a probe passes
        self._refresh_ready_gauge(b.service)

    # -- plumbing -------------------------------------------------------- #

    def ready_count(self, service: str) -> int:
        return len(
            [
                b
                for b in self.selectable(service)
                if b.breaker.current_state() != "open"
            ]
        )

    def _refresh_ready_gauge(self, service: str) -> None:
        BACKENDS_READY.labels(service=service).set(self.ready_count(service))

    def _notify_ready(self, service: str) -> None:
        self._refresh_ready_gauge(service)
        if self.on_ready is not None and self.ready_count(service) > 0:
            self.on_ready(service)

    def view(self) -> list[dict]:
        return [
            b.view()
            for svc in sorted(self._backends)
            for b in self._backends[svc]
        ]
