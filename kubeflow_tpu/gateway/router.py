"""Routing decisions: host/path → service, canary split, prefix affinity.

Reference analogs: Istio VirtualService weighted routing + KServe's
traffic-split annotations (SURVEY.md §2.2), plus the LM-aware divergence:
vLLM-ecosystem routers send repeated prompts to the replica whose prefix
cache already holds their KV (Kwon et al., PagedAttention) — a signal only
the edge can exploit, because single replicas never see each other's
prompts.

Everything here is pure computation — no I/O, no serve-plane imports — so
``serve/controller.py`` reuses ``canary_slot`` for its own per-request
split without an import cycle.

Determinism rules (enforced by design, not convention):

- the canary decision is a **salted hash of the request id**, never RNG:
  a retried request re-hashes to the same revision, so a retry cannot
  flap default↔canary mid-rollout, and the split is exactly pct in
  expectation over distinct ids;
- affinity is a **consistent-hash ring** (64 vnodes per backend): the
  same prompt prefix lands on the same replica, and membership churn
  remaps only the keys that hashed to the lost/new vnode arcs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from bisect import bisect_right
from typing import Any, Mapping


def _h64(s: str) -> int:
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


def canary_slot(request_id: str, salt: str = "kft-canary") -> float:
    """Deterministic slot in [0, 100) for a request id: take the canary
    iff ``slot < canary_percent``. Salted so operators can re-shuffle which
    ids land in the canary cohort without touching client ids."""
    return _h64(f"{salt}:{request_id}") / 2.0**64 * 100.0


def pick_revision(
    request_id: str, canary_percent: float, salt: str = "kft-canary"
) -> str:
    return (
        "canary"
        if canary_percent > 0 and canary_slot(request_id, salt) < canary_percent
        else "default"
    )


class HashRing:
    """Consistent hashing over backend URLs (vnode ring)."""

    VNODES = 64

    def __init__(self, urls: tuple[str, ...]):
        points: list[tuple[int, str]] = []
        for url in urls:
            for i in range(self.VNODES):
                points.append((_h64(f"{url}#{i}"), url))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._urls = [p[1] for p in points]

    def pick(self, key: str) -> str | None:
        if not self._urls:
            return None
        i = bisect_right(self._hashes, _h64(key)) % len(self._urls)
        return self._urls[i]


@dataclasses.dataclass
class ServiceRoute:
    """Edge routing policy for one service."""

    name: str
    hosts: tuple[str, ...] = ()
    path_prefixes: tuple[str, ...] = ()
    canary_percent: float = 0.0
    #: "none" | "prefix" (LM prefix-cache affinity) | "session"
    affinity: str = "none"
    #: how much of the prompt keys the affinity hash; 16 matches the
    #: engine's prefix-cache granularity (serve/engine.py stores 16-token
    #: multiples), so requests sharing a cached prefix share a replica
    affinity_prefix_tokens: int = 16
    #: spill to least-outstanding when the affine replica is this loaded
    #: (None = always honor affinity)
    affinity_max_outstanding: int | None = None
    #: dispatch a hedged second request after this long (idempotent only)
    hedge_ms: float | None = None
    max_attempts: int = 3

    def view(self) -> dict:
        return {
            "name": self.name,
            "hosts": list(self.hosts),
            "path_prefixes": list(self.path_prefixes),
            "canary_percent": self.canary_percent,
            "affinity": self.affinity,
            "hedge_ms": self.hedge_ms,
        }

    def trace_attrs(self) -> dict:
        """Span attributes for the edge ``route`` span — only the routing
        policy that shaped THIS decision, not the whole view."""
        attrs: dict = {"service": self.name, "affinity": self.affinity}
        if self.canary_percent:
            attrs["canary_percent"] = self.canary_percent
        if self.hedge_ms is not None:
            attrs["hedge_ms"] = self.hedge_ms
        return attrs


_MODEL_PATH = re.compile(r"^/v[12]/models/([^/:]+)")
_GENERATE_PATH = re.compile(r"^/v2/models/[^/:]+/(generate|generate_stream)$")

#: model formats whose replicas hold per-process prefix KV caches — the
#: controller-fed table turns prefix affinity on for these automatically
LM_ENGINE_FORMATS = ("causal-lm-engine", "vllm", "causal-lm", "llm")


class RouteTable:
    """host/path → ``ServiceRoute``.

    Resolution order (first match wins):

    1. exact ``Host`` header match (port stripped) against ``hosts``, or a
       Knative-style first-label match (``{service}.anything``);
    2. longest declared ``path_prefixes`` match — the prefix is stripped
       before forwarding, so ``/edge/echo/v1/models/...`` proxies to
       ``/v1/models/...``;
    3. the model name baked into v1/v2 inference paths, when it names a
       registered service — zero-config for the common one-model-per-
       service layout.
    """

    def __init__(self, *, salt: str = "kft-canary"):
        self.salt = salt
        self._routes: dict[str, ServiceRoute] = {}

    def upsert(self, route: ServiceRoute) -> ServiceRoute:
        self._routes[route.name] = route
        return route

    def get(self, name: str) -> ServiceRoute | None:
        return self._routes.get(name)

    def routes(self) -> list[ServiceRoute]:
        return [self._routes[k] for k in sorted(self._routes)]

    def resolve(
        self, host: str | None, path: str
    ) -> tuple[ServiceRoute, str] | None:
        """→ ``(route, upstream_path)`` or None when nothing matches."""
        hostname = (host or "").rsplit(":", 1)[0] if host else ""
        if hostname:
            for r in self._routes.values():
                if hostname in r.hosts:
                    return r, path
            first_label = hostname.split(".", 1)[0]
            r = self._routes.get(first_label)
            if r is not None and "." in hostname:
                return r, path
        best: tuple[ServiceRoute, str] | None = None
        best_len = -1
        for r in self._routes.values():
            for prefix in r.path_prefixes:
                p = prefix.rstrip("/")
                if (path == p or path.startswith(p + "/")) and len(p) > best_len:
                    best = (r, path[len(p):] or "/")
                    best_len = len(p)
        if best is not None:
            return best
        m = _MODEL_PATH.match(path)
        if m and m.group(1) in self._routes:
            return self._routes[m.group(1)], path
        return None

    def revision_for(self, route: ServiceRoute, request_id: str) -> str:
        return pick_revision(request_id, route.canary_percent, self.salt)

    # -- controller feed ------------------------------------------------- #

    def update_from_controller(self, controller: Any) -> None:
        """Refresh the table from ``InferenceServiceController`` state:
        one route per service, Knative-style ``{name}.{namespace}`` host,
        the live canary percent (0 unless a canary materialisation is
        actually serving), and prefix affinity switched on for LM-engine
        predictors. Duck-typed — no serve-plane import, no cycle."""
        for key, st in controller._services.items():
            namespace, name = key.split("/", 1)
            pct = st.spec.predictor.canary_traffic_percent
            live_canary = st.canary_model is not None and 0 < pct < 100
            fmt = st.spec.predictor.model_format
            prev = self._routes.get(name)
            self.upsert(
                ServiceRoute(
                    name=name,
                    hosts=(f"{name}.{namespace}",),
                    path_prefixes=prev.path_prefixes if prev else (),
                    canary_percent=float(pct) if live_canary else 0.0,
                    affinity=(
                        "prefix" if fmt in LM_ENGINE_FORMATS else "none"
                    ),
                    hedge_ms=prev.hedge_ms if prev else None,
                )
            )


# -- affinity keys ------------------------------------------------------- #


def prefix_affinity_key(tokens, n: int = 16) -> str:
    """The ring key for a token-id prompt prefix. ONE definition shared by
    the edge (``affinity_key_of``) and the prefix-KV transfer planner
    (autoscale/kv_transfer.py): both must hash an engine prefix-cache
    entry to the same replica, or transfers land where traffic won't."""

    def norm(t) -> str:
        try:  # "3", 3, 3.0 → "3"; non-numeric tokens key as themselves
            return str(int(t))
        except (TypeError, ValueError):
            return str(t)

    return "prefix:" + ",".join(norm(t) for t in list(tokens)[:n])


def affinity_key_of(
    route: ServiceRoute,
    headers: Mapping[str, str],
    body: Any,
) -> str | None:
    """The stickiness key for one request, or None to fall back to
    least-outstanding. Session affinity keys on ``x-session-id``; prefix
    affinity keys on the leading ``affinity_prefix_tokens`` tokens (or
    characters, for text prompts) of the first instance — the same
    granularity the engine's prefix cache stores, so sticky requests HIT
    the replica-local cache instead of re-prefilling elsewhere."""
    if route.affinity == "session":
        sid = headers.get("x-session-id")
        return f"session:{sid}" if sid else None
    if route.affinity != "prefix":
        return None
    sid = headers.get("x-session-id")
    if sid:
        return f"session:{sid}"
    row = body
    if isinstance(body, Mapping):
        insts = body.get("instances")
        row = insts[0] if isinstance(insts, (list, tuple)) and insts else body
    prefix: Any = None
    if isinstance(row, Mapping):
        for k in ("ids", "input_ids", "prompt", "text"):
            if row.get(k) is not None:
                prefix = row[k]
                break
    elif isinstance(row, (list, tuple, str)):
        prefix = row
    if prefix is None:
        return None
    n = route.affinity_prefix_tokens
    if isinstance(prefix, str):
        # ~chars per token, close enough for keying
        return "prefix:" + prefix[: n * 4]
    return prefix_affinity_key(prefix, n)
