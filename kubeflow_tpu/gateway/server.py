"""The L7 inference gateway: one front door over N ModelServer replicas.

Reference analog: the half of KServe's request path we had not built —
Istio ingress + the Knative activator (SURVEY.md §2.2). Every request
flows:

    client → policy (tenant rate/in-flight) → route table (host/path)
           → revision split (salted hash, edge-decided)
           → activator (park if scaled to zero)
           → backend pick (prefix affinity | least-outstanding)
           → proxy (retries within budget, optional hedging, SSE passthrough)

Design commitments, each load-bearing:

- **deterministic routing** — the canary decision hashes the request id
  (``router.canary_slot``), so retries never flap revisions; balancing
  ties rotate a counter; NOTHING in the request path draws randomness;
- **cold start off the request path** — zero ready backends parks the
  request in the activator's bounded FIFO and kicks ``scale_up`` once;
  the model load happens concurrently with the client waiting, not
  inside it;
- **failures are the gateway's job** — connect errors and 502/503/504
  feed the backend's breaker and are retried transparently (idempotent
  verbs only, within the retry budget); an SSE stream that dies
  mid-flight surfaces a clean terminal error frame instead of a torn
  socket; a client that disconnects mid-stream tears down the upstream
  connection so the backend cancels the engine row;
- **observable** — every decision increments a ``kft_gateway_*`` metric
  (obs/names.py), served at ``GET /metrics`` in Prometheus text format.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import time
import uuid
from typing import Any, Callable

from kubeflow_tpu.obs import names, prom
from kubeflow_tpu.gateway.activator import (
    ActivationTimeout,
    Activator,
    QueueOverflow,
)
from kubeflow_tpu.gateway.backends import Backend, BackendPool, BreakerConfig
from kubeflow_tpu.gateway.policy import (
    PolicyEngine,
    RateLimited,
    RetryBudget,
    TenantPolicy,
    TokenBucket,
    TooManyInFlight,
)
from kubeflow_tpu.gateway.router import (
    HashRing,
    RouteTable,
    ServiceRoute,
    affinity_key_of,
)
from kubeflow_tpu.gateway.sse import SSEFrameSplitter, sse_payload
from kubeflow_tpu.obs.headers import (
    PREFILL_PEER_HEADER,
    RESUME_TOKENS_HEADER,
    SEED_HEADER,
    TENANT_HEADER,
    TRACE_HEADER,
)
from kubeflow_tpu.obs.trace import TRACER, ctx_from_headers
from kubeflow_tpu.serve.deadline import (
    DEADLINE_ABS_HEADER,
    DEADLINE_HEADER,
    PRIORITY_HEADER,
    deadline_from_headers,
)

REQUESTS = prom.REGISTRY.counter(
    names.GATEWAY_REQUESTS_TOTAL,
    "requests answered at the edge, by status",
    ("service", "code"),
)
LATENCY = prom.REGISTRY.histogram(
    names.GATEWAY_LATENCY_SECONDS,
    "edge-observed request latency (activator queue time included)",
    ("service",),
)
SHED = prom.REGISTRY.counter(
    names.GATEWAY_SHED_TOTAL,
    "requests shed at the edge",
    ("service", "reason"),
)
RETRIES = prom.REGISTRY.counter(
    names.GATEWAY_RETRIES_TOTAL,
    "transparent re-dispatches after a backend failure",
    ("service",),
)
HEDGES = prom.REGISTRY.counter(
    names.GATEWAY_HEDGES_TOTAL,
    "hedged second requests dispatched",
    ("service",),
)
AFFINITY_ROUTED = prom.REGISTRY.counter(
    names.GATEWAY_AFFINITY_ROUTED_TOTAL,
    "requests routed by prefix/session affinity",
    ("service",),
)
STREAM_RESUMES = prom.REGISTRY.counter(
    names.GATEWAY_STREAM_RESUMES_TOTAL,
    "mid-stream failovers: SSE streams re-dispatched with a committed-"
    "token resume prefix, by outcome",
    ("service", "outcome"),
)

#: hop-by-hop headers never forwarded either direction
_HOP_HEADERS = {
    "host", "content-length", "transfer-encoding", "connection",
    "keep-alive", "upgrade", "proxy-authorization", "proxy-connection",
}

#: verbs safe to retry/hedge: reads, and the stateless inference verbs
_IDEMPOTENT_SUFFIXES = (":predict", "/infer")

#: upstream statuses that indicate backend (not request) trouble
_BACKEND_FAILURE_STATUSES = (502, 503, 504)


def _edge_status(status: int, headers=None) -> str:
    """Span status for an edge response: coherent sheds (429, 503 with
    Retry-After) end the trace as ``shed`` — tail-sampled like errors —
    while other 5xx are ``error``."""
    if status == 429 or (
        status == 503 and headers is not None and "Retry-After" in headers
    ):
        return "shed"
    if status >= 500:
        return "error"
    return "ok"


class _UpstreamError(Exception):
    def __init__(self, backend: Backend, cause: BaseException):
        super().__init__(f"{backend.url}: {cause}")
        self.backend = backend
        self.cause = cause


@dataclasses.dataclass
class GatewayConfig:
    name: str = "gateway"
    salt: str = "kft-canary"
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    failure_threshold: int = 3
    recovery_s: float = 5.0
    eject_threshold: int = 3
    queue_limit: int = 256
    activation_timeout_s: float = 30.0
    upstream_timeout_s: float = 120.0
    connect_timeout_s: float = 5.0
    retry_budget_ratio: float = 0.2
    retry_budget_floor: int = 3
    #: mid-stream failover: re-dispatch a dying SSE stream to a healthy
    #: peer with the committed-token prefix instead of surfacing a
    #: terminal error frame (bounded by maxAttempts + the retry budget)
    stream_resume: bool = True
    routes: list[ServiceRoute] = dataclasses.field(default_factory=list)
    #: (service, url, revision, role) tuples registered at startup;
    #: role is "both" | "prefill" | "decode" (disaggregated serving)
    backends: list[tuple[str, str, str, str]] = dataclasses.field(
        default_factory=list
    )
    #: tenant → {max_rps, burst, max_in_flight}
    tenants: dict[str, dict] = dataclasses.field(default_factory=dict)
    #: service → raw ``autoscaling:`` manifest section (camelCase KPA
    #: policy + replicaCommand); consumed by ``kft gateway run``, which
    #: wires a ServingAutoscaler + ReplicaFleet per entry
    autoscaling: dict[str, dict] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_manifest(cls, doc: dict) -> "GatewayConfig":
        """``kind: InferenceGateway`` manifest → config (KServe-style
        camelCase spec keys)."""
        if doc.get("kind", "InferenceGateway") != "InferenceGateway":
            raise ValueError(
                f"not an InferenceGateway manifest: {doc.get('kind')!r}"
            )
        spec = doc.get("spec", {})
        cfg = cls(name=doc.get("metadata", {}).get("name", "gateway"))
        for yaml_key, attr in (
            ("salt", "salt"),
            ("probeIntervalS", "probe_interval_s"),
            ("probeTimeoutS", "probe_timeout_s"),
            ("failureThreshold", "failure_threshold"),
            ("recoveryS", "recovery_s"),
            ("ejectThreshold", "eject_threshold"),
            ("queueLimit", "queue_limit"),
            ("activationTimeoutS", "activation_timeout_s"),
            ("upstreamTimeoutS", "upstream_timeout_s"),
            ("connectTimeoutS", "connect_timeout_s"),
            ("retryBudgetRatio", "retry_budget_ratio"),
            ("retryBudgetFloor", "retry_budget_floor"),
            ("streamResume", "stream_resume"),
        ):
            if yaml_key in spec:
                setattr(cfg, attr, type(getattr(cfg, attr))(spec[yaml_key]))
        for svc in spec.get("services", []):
            name = svc["name"]
            cfg.routes.append(
                ServiceRoute(
                    name=name,
                    hosts=tuple(svc.get("hosts", ())),
                    path_prefixes=tuple(svc.get("pathPrefixes", ())),
                    canary_percent=float(svc.get("canaryPercent", 0)),
                    affinity=svc.get("affinity", "none"),
                    affinity_prefix_tokens=int(
                        svc.get("affinityPrefixTokens", 16)
                    ),
                    hedge_ms=(
                        float(svc["hedgeMs"]) if "hedgeMs" in svc else None
                    ),
                    max_attempts=int(svc.get("maxAttempts", 3)),
                )
            )
            for be in svc.get("backends", []):
                if isinstance(be, str):
                    cfg.backends.append((name, be, "default", "both"))
                else:
                    cfg.backends.append(
                        (
                            name,
                            be["url"],
                            be.get("revision", "default"),
                            be.get("role", "both"),
                        )
                    )
            if "autoscaling" in svc:
                auto = dict(svc["autoscaling"])
                if not isinstance(auto.get("replicaCommand", []), list):
                    raise ValueError(
                        f"service {name!r}: autoscaling.replicaCommand "
                        "must be an argv list"
                    )
                cfg.autoscaling[name] = auto
        for tenant, pol in (spec.get("policy", {}).get("tenants", {})).items():
            cfg.tenants[tenant] = {
                "max_rps": pol.get("maxRps"),
                "burst": pol.get("burst"),
                "max_in_flight": pol.get("maxInFlight"),
                "priority": pol.get("priority", 0),
            }
        return cfg


class InferenceGateway:
    def __init__(
        self,
        config: GatewayConfig | None = None,
        *,
        http_port: int = 0,
        controller: Any = None,
        scale_up: Callable[[str], None] | None = None,
        policy: PolicyEngine | None = None,
    ):
        self.config = config or GatewayConfig()
        self.http_port = http_port
        #: optional InferenceServiceController whose state feeds the route
        #: table (resynced every probe tick)
        self.controller = controller
        self.activator = Activator(
            queue_limit=self.config.queue_limit,
            timeout_s=self.config.activation_timeout_s,
            scale_up=scale_up,
        )
        self.pool = BackendPool(
            breaker=BreakerConfig(
                failure_threshold=self.config.failure_threshold,
                recovery_s=self.config.recovery_s,
            ),
            probe_interval_s=self.config.probe_interval_s,
            probe_timeout_s=self.config.probe_timeout_s,
            eject_threshold=self.config.eject_threshold,
            on_ready=self.activator.notify,
        )
        self.table = RouteTable(salt=self.config.salt)
        for r in self.config.routes:
            self.table.upsert(r)
        for entry in self.config.backends:
            # pre-disagg configs built 3-tuples (service, url, revision);
            # a missing role means "both"
            service, url, revision = entry[0], entry[1], entry[2]
            role = entry[3] if len(entry) > 3 else "both"
            if self.table.get(service) is None:
                self.table.upsert(ServiceRoute(name=service))
            self.pool.add(service, url, revision=revision, role=role)
        if policy is not None:
            self.policy = policy
        else:
            self.policy = PolicyEngine()
            for tenant, p in self.config.tenants.items():
                self.policy.set(
                    tenant,
                    TenantPolicy(
                        bucket=(
                            TokenBucket(p["max_rps"], p.get("burst"))
                            if p.get("max_rps") is not None
                            else None
                        ),
                        max_in_flight=p.get("max_in_flight"),
                        priority=int(p.get("priority") or 0),
                    ),
                )
        self._budgets: dict[str, RetryBudget] = {}
        self._rings: dict[tuple[str, ...], HashRing] = {}
        self._session = None
        self._probe_task: asyncio.Task | None = None
        self._runner = None
        if self.controller is not None:
            self.table.update_from_controller(self.controller)

    # -- app ------------------------------------------------------------- #

    def build_app(self):
        from aiohttp import web

        app = web.Application(client_max_size=64 * 2**20)
        app.router.add_get("/gateway/healthz", self._healthz)
        app.router.add_get("/gateway/state", self._state)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_route("*", "/{tail:.*}", self._proxy)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession()
        self._probe_task = asyncio.create_task(self._probe_loop())

    async def _on_cleanup(self, app) -> None:
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except asyncio.CancelledError:
                pass
            self._probe_task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.probe_interval_s)
            if self.controller is not None:
                self.table.update_from_controller(self.controller)
            await self.pool.probe_all(self._session)

    async def _healthz(self, request):
        from aiohttp import web

        return web.json_response({"ok": True, "name": self.config.name})

    async def _metrics(self, request):
        from aiohttp import web

        return web.Response(text=prom.REGISTRY.expose())

    async def _state(self, request):
        from aiohttp import web

        return web.json_response(self.state_view())

    def state_view(self) -> dict:
        routes = self.table.routes()
        return {
            "name": self.config.name,
            "services": [
                {
                    **r.view(),
                    "ready_backends": self.pool.ready_count(r.name),
                    "queue_depth": self.activator.depth(r.name),
                    "backends": [
                        b.view() for b in self.pool.backends_of(r.name)
                    ],
                }
                for r in routes
            ],
            "policy": self.policy.view(),
            "activator": self.activator.view(),
        }

    # -- the request path ------------------------------------------------ #

    async def _proxy(self, request):
        from aiohttp import web

        t0 = time.perf_counter()
        resolved = self.table.resolve(
            request.headers.get("host"), request.path
        )
        if resolved is None:
            REQUESTS.labels(service="_unmatched", code="404").inc()
            raise web.HTTPNotFound(
                reason=f"no service routes {request.path!r}"
            )
        route, path = resolved
        service = route.name
        # root span for the whole edge decision: continues the client's
        # trace when a valid x-kft-trace rides in, mints one otherwise.
        # Every downstream hop (proxy attempt, dataplane, engine) parents
        # onto this id — ONE trace from edge to decode chunk.
        span = TRACER.span("route", ctx=ctx_from_headers(request.headers))
        if span:
            for k, v in route.trace_attrs().items():
                span.set_attr(k, v)
            span.set_attr("path", path)
            span.set_attr("method", request.method)
        tenant = request.headers.get(TENANT_HEADER, "default")
        try:
            self.policy.acquire(tenant)
        except RateLimited as e:
            SHED.labels(service=service, reason="rate_limit").inc()
            REQUESTS.labels(service=service, code="429").inc()
            if span:
                span.event("rate_limited", tenant=tenant)
                span.end("shed")
            raise web.HTTPTooManyRequests(
                reason=str(e), headers={"Retry-After": "1"}
            )
        except TooManyInFlight as e:
            SHED.labels(service=service, reason="inflight_cap").inc()
            REQUESTS.labels(service=service, code="429").inc()
            if span:
                span.event("inflight_cap", tenant=tenant)
                span.end("shed")
            raise web.HTTPTooManyRequests(reason=str(e))
        try:
            resp = await self._routed(request, route, path, span)
            REQUESTS.labels(service=service, code=str(resp.status)).inc()
            if span:
                span.set_attr("status", resp.status)
                span.end(_edge_status(resp.status, resp.headers))
            return resp
        except web.HTTPException as e:
            REQUESTS.labels(service=service, code=str(e.status)).inc()
            if span:
                span.set_attr("status", e.status)
                span.end(_edge_status(e.status, e.headers))
            raise
        except BaseException:
            if span:
                span.end("error")
            raise
        finally:
            self.policy.release(tenant)
            LATENCY.labels(service=service).observe(time.perf_counter() - t0)

    async def _routed(self, request, route: ServiceRoute, path: str, span=None):
        from aiohttp import web

        req_id = request.headers.get("x-request-id") or uuid.uuid4().hex
        body = await request.read() if request.can_read_body else b""
        revision = self.table.revision_for(route, req_id)
        affinity_key = None
        if route.affinity != "none":
            try:
                parsed = json.loads(body) if body else None
            except ValueError:
                parsed = None
            affinity_key = affinity_key_of(route, request.headers, parsed)
        fwd = {
            k: v
            for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        fwd["x-request-id"] = req_id
        # the absolute-deadline header is process-local (a monotonic
        # stamp): one arriving off the wire is meaningless-to-hostile —
        # never forward it, backends re-anchor from the ms budget
        fwd.pop(DEADLINE_ABS_HEADER, None)
        fwd.pop(DEADLINE_ABS_HEADER.title(), None)
        # never forward the client's raw trace header: each upstream
        # attempt stamps ITS OWN span id (see _attempt_once), so backend
        # spans parent onto the attempt that actually carried them
        fwd.pop(TRACE_HEADER, None)
        fwd.pop(TRACE_HEADER.title(), None)
        if span:
            fwd[TRACE_HEADER] = span.header()
        # the prefill-peer header is gateway-authoritative: a client (or
        # a compromised hop) must not be able to point a decode replica
        # at an arbitrary URL to pull KV from
        fwd.pop(PREFILL_PEER_HEADER, None)
        fwd.pop(PREFILL_PEER_HEADER.title(), None)
        # the resume header is gateway-authoritative: only the gateway may
        # assert a committed-token prefix (a client asserting one would
        # splice arbitrary tokens into its own billed budget)
        fwd.pop(RESUME_TOKENS_HEADER, None)
        fwd.pop(RESUME_TOKENS_HEADER.title(), None)
        if path.endswith("/generate") or path.endswith("/generate_stream"):
            # disaggregated dispatch: hand the decode replica its prefill
            # peer. None when the service runs colocated OR every prefill
            # replica is unhealthy — the decode replica then prefills
            # locally, so disagg degrades to colocated, never to an error.
            pb = self.pool.pick_prefill(route.name)
            if pb is not None:
                fwd[PREFILL_PEER_HEADER] = pb.url
                if span:
                    span.set_attr("prefill_peer", pb.url)
            # sampling seed, stamped deterministically from the request id
            # (client-supplied seeds are honored): every attempt — first
            # dispatch, retry, or mid-stream resume — carries the SAME
            # seed, so a temperature>0 stream resumed on another replica
            # draws the identical sampling stream
            seed = None
            raw_seed = request.headers.get(SEED_HEADER) or (
                request.headers.get(SEED_HEADER.title())
            )
            if raw_seed is not None:
                try:
                    seed = int(raw_seed) & 0x7FFFFFFF
                except ValueError:
                    seed = None
            if seed is None:
                seed = int.from_bytes(
                    hashlib.sha256(req_id.encode()).digest()[:4], "big"
                ) & 0x7FFFFFFF
            fwd.pop(SEED_HEADER.title(), None)
            fwd[SEED_HEADER] = str(seed)
        #: the end-to-end budget, anchored at edge arrival: queue time in
        #: the activator and retry rounds are charged against it. Only
        #: the WIRE header counts — an absolute stamp arriving off the
        #: wire is another process's clock (or an attacker's) and was
        #: already stripped from fwd above.
        deadline = deadline_from_headers(
            {DEADLINE_HEADER: request.headers[DEADLINE_HEADER]}
            if DEADLINE_HEADER in request.headers
            else None
        )
        # managed tenants get their policy priority stamped (gateway-
        # authoritative — a client cannot self-promote its shed order)
        tenant = request.headers.get(TENANT_HEADER, "default")
        prio = self.policy.priority_of(tenant)
        if prio is not None:
            fwd.pop(PRIORITY_HEADER.title(), None)
            fwd[PRIORITY_HEADER] = str(prio)
        is_stream = path.endswith("/generate_stream")
        idempotent = request.method == "GET" or any(
            path.endswith(s) for s in _IDEMPOTENT_SUFFIXES
        )
        budget = self._budgets.setdefault(route.name, RetryBudget(
            ratio=self.config.retry_budget_ratio,
            floor=self.config.retry_budget_floor,
        ))
        budget.on_request()

        parks = 0
        attempts = 0
        last_err: _UpstreamError | None = None
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # every hop downstream would shed it too — fail at the
                    # edge with the shed marker (503 + Retry-After), and
                    # never as a retryable backend failure
                    SHED.labels(service=route.name, reason="deadline").inc()
                    if span:
                        span.event("deadline_expired", stage="edge")
                    raise web.HTTPServiceUnavailable(
                        reason="request deadline expired at the gateway",
                        headers={"Retry-After": "1"},
                    )
                # rewrite the wire budget to what is LEFT: edge queue time
                # and earlier attempts are charged, so the backend's own
                # admission control sees the truth
                fwd[DEADLINE_HEADER.title()] = str(
                    max(1, int(remaining * 1e3))
                )
                fwd.pop(DEADLINE_HEADER, None)
            backend = self._select(route, revision, affinity_key)
            if backend is None:
                parks += 1
                if parks > 8:
                    break  # repeated wake-ups without capacity: shed below
                # cold-start parking is often the dominant edge latency —
                # it gets its own span so traces show WHERE the time went
                pspan = (
                    TRACER.span("activator.park", parent=span)
                    if span
                    else None
                )
                try:
                    await self.activator.wait(route.name, span=pspan)
                    if pspan:
                        pspan.end()
                except QueueOverflow as e:
                    if pspan:
                        pspan.end("shed")
                    SHED.labels(
                        service=route.name, reason="queue_full"
                    ).inc()
                    raise web.HTTPTooManyRequests(reason=str(e))
                except ActivationTimeout as e:
                    if pspan:
                        pspan.end("shed")
                    SHED.labels(
                        service=route.name, reason="activation_timeout"
                    ).inc()
                    raise web.HTTPServiceUnavailable(reason=str(e))
                continue
            try:
                if is_stream:
                    # connect-level stream failures retry like any other
                    # attempt (no response bytes have committed yet);
                    # mid-stream failures resume inside _proxy_stream —
                    # re-dispatched with the committed-token prefix,
                    # charged against the same retry budget
                    return await self._proxy_stream(
                        request, route, backend, path, fwd, body,
                        parent=span, budget=budget, deadline=deadline,
                    )
                return await self._attempt(
                    route, backend, request.method, path, fwd, body,
                    idempotent=idempotent, timeout_s=remaining, parent=span,
                )
            except _UpstreamError as e:
                last_err = e
                attempts += 1
                # streams only raise here on CONNECT failure (nothing has
                # committed to the client), so they are safe to re-dispatch
                if (
                    (idempotent or is_stream)
                    and attempts < route.max_attempts
                    and budget.try_spend()
                ):
                    RETRIES.labels(service=route.name).inc()
                    if span:
                        span.event(
                            "retry", attempt=attempts, backend=e.backend.url
                        )
                    continue
                break
        SHED.labels(service=route.name, reason="no_backend").inc()
        raise web.HTTPServiceUnavailable(
            reason=(
                f"no backend could serve {route.name!r}"
                + (f" (last error: {last_err})" if last_err else "")
            )
        )

    def _select(
        self, route: ServiceRoute, revision: str, affinity_key: str | None
    ) -> Backend | None:
        """Affinity first (closed-breaker replicas only), then
        least-outstanding; a canary decision with no live canary backends
        falls back to the default revision rather than shedding."""
        rev = revision
        if rev == "canary" and not self.pool.selectable(route.name, "canary"):
            rev = "default"
        if affinity_key is not None:
            b = self._affine_pick(route, rev, affinity_key)
            if b is not None:
                AFFINITY_ROUTED.labels(service=route.name).inc()
                return b
        b = self.pool.pick(route.name, rev)
        if b is None:
            b = self.pool.pick(route.name, None)
        return b

    def _affine_pick(
        self, route: ServiceRoute, revision: str, key: str
    ) -> Backend | None:
        cands = [
            b
            for b in self.pool.selectable(route.name, revision)
            if b.breaker.current_state() == "closed"
        ]
        if not cands:
            return None
        urls = tuple(sorted(b.url for b in cands))
        ring = self._rings.get(urls)
        if ring is None:
            if len(self._rings) > 128:  # membership churn: drop stale rings
                self._rings.clear()
            ring = self._rings[urls] = HashRing(urls)
        url = ring.pick(key)
        b = next(b for b in cands if b.url == url)
        if (
            route.affinity_max_outstanding is not None
            and b.outstanding >= route.affinity_max_outstanding
        ):
            return None  # affine replica saturated: spill to the balancer
        return b

    # -- one upstream attempt (with optional hedging) -------------------- #

    async def _attempt(
        self,
        route: ServiceRoute,
        backend: Backend,
        method: str,
        path: str,
        fwd: dict,
        body: bytes,
        *,
        idempotent: bool,
        timeout_s: float | None = None,
        parent=None,
    ):
        if (
            route.hedge_ms is not None
            and idempotent
            and len(self.pool.selectable(route.name)) > 1
        ):
            return await self._hedged(
                route, backend, method, path, fwd, body, timeout_s,
                parent=parent,
            )
        return await self._attempt_once(
            route, backend, method, path, fwd, body, timeout_s,
            parent=parent,
        )

    async def _hedged(
        self, route, primary, method, path, fwd, body, timeout_s=None,
        *, parent=None,
    ):
        """Race a second attempt dispatched ``hedge_ms`` after the first;
        first success wins, the loser is cancelled."""
        first = asyncio.ensure_future(
            self._attempt_once(
                route, primary, method, path, fwd, body, timeout_s,
                parent=parent, racing=True,
            )
        )
        done, _ = await asyncio.wait(
            {first}, timeout=route.hedge_ms / 1e3
        )
        if done:
            return first.result()  # raises _UpstreamError if it failed fast
        second_backend = self.pool.pick(route.name)
        if second_backend is None or second_backend is primary:
            return await first
        HEDGES.labels(service=route.name).inc()
        second = asyncio.ensure_future(
            self._attempt_once(
                route, second_backend, method, path, fwd, body, timeout_s,
                parent=parent, hedged=True, racing=True,
            )
        )
        pending = {first, second}
        result = None
        err: _UpstreamError | None = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                try:
                    result = t.result()
                except _UpstreamError as e:
                    err = e
            if result is not None:
                for t in pending:
                    t.cancel()
                # drain the loser so its span closes (as "cancelled")
                # before the trace's root span can finalize
                await asyncio.gather(*pending, return_exceptions=True)
                return result
        assert err is not None
        raise err

    async def _attempt_once(
        self, route, backend: Backend, method, path, fwd, body,
        timeout_s: float | None = None, *, parent=None, hedged: bool = False,
        racing: bool = False,
    ):
        import aiohttp
        from aiohttp import web

        span = TRACER.span("proxy", parent=parent) if parent else None
        if span:
            span.set_attr("backend", backend.url)
            span.set_attr("revision", backend.revision)
            if hedged:
                span.set_attr("hedge", True)
            # copy before stamping: hedged/retried attempts share fwd, and
            # each must carry ITS OWN span id so the backend's spans parent
            # onto the attempt that actually reached it
            fwd = dict(fwd)
            fwd[TRACE_HEADER] = span.header()
        total = self.config.upstream_timeout_s
        if timeout_s is not None:
            # a deadline-bearing request never waits on a backend longer
            # than its remaining budget
            total = min(total, max(timeout_s, 0.001))
        self.pool.acquire(backend)
        try:
            async with self._session.request(
                method,
                backend.url + path,
                data=body if method not in ("GET", "HEAD") else None,
                headers=fwd,
                timeout=aiohttp.ClientTimeout(
                    total=total,
                    sock_connect=self.config.connect_timeout_s,
                ),
            ) as upstream:
                payload = await upstream.read()
                status = upstream.status
                ctype = upstream.headers.get(
                    "Content-Type", "application/json"
                )
                retry_after = upstream.headers.get("Retry-After")
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            self.pool.record(backend, ok=False)
            if span:
                span.set_attr("error", str(e) or type(e).__name__)
                span.end("error")
            raise _UpstreamError(backend, e) from e
        except asyncio.CancelledError:
            # the hedge loser lands here mid-flight: its span must still
            # close, or the trace never finalizes for export
            if span:
                if racing:
                    span.set_attr("hedge_loser", True)
                span.end("cancelled")
            raise
        finally:
            self.pool.release(backend)
        if status == 503 and retry_after is not None:
            # coherent load shed (deadline-expired / admission-shed), NOT
            # backend death: pass it through with its Retry-After. No
            # retry (every replica would shed it identically — a retry
            # storm is how brownouts become blackouts) and no breaker
            # penalty (the replica answered rationally).
            self.pool.record(backend, ok=True)
            SHED.labels(service=route.name, reason="upstream_shed").inc()
            if span:
                span.set_attr("status", status)
                span.end("shed")
            return web.Response(
                body=payload, status=status,
                headers={"Content-Type": ctype, "Retry-After": retry_after},
            )
        if status in _BACKEND_FAILURE_STATUSES:
            self.pool.record(backend, ok=False)
            if span:
                span.set_attr("status", status)
                span.end("error")
            raise _UpstreamError(
                backend, RuntimeError(f"upstream returned {status}")
            )
        self.pool.record(backend, ok=True)
        if span:
            span.set_attr("status", status)
            span.end()
        return web.Response(
            body=payload, status=status, headers={"Content-Type": ctype}
        )

    # -- SSE passthrough + mid-stream failover ---------------------------- #

    # one definition of frame-splitting + payload parsing, shared with the
    # loadgen client (gateway/sse.py): the proxy and the harness measuring
    # it must agree on what a whole frame is
    _sse_payload = staticmethod(sse_payload)

    async def _pump_sse(
        self, upstream, resp, committed: list[int], *, rewrite: bool
    ) -> tuple[str, str | None]:
        """Forward one upstream's SSE stream to the client in WHOLE
        frames, tracking the generated-token prefix in ``committed``.
        Frame alignment is a correctness property on its own: the old
        raw-chunk passthrough could commit a torn half-frame to the
        client when the backend died mid-write, poisoning the client's
        SSE parser for every later frame.

        Returns ``("done", None)`` after a terminal frame reached the
        client, or ``("died", reason)`` on mid-stream death — socket
        error, EOF without a terminal frame (a SIGKILLed replica's
        socket often closes cleanly), or the ModelServer's ``resumable``
        error frame (watchdog restart poison). ``rewrite`` fixes up the
        terminal done-frame's ``n_tokens`` after a resume (the final
        backend only counts its own segment); un-resumed streams are
        byte-identical passthrough."""
        import aiohttp

        split = SSEFrameSplitter()
        try:
            async for chunk in upstream.content.iter_any():
                for frame in split.feed(chunk):
                    payload = self._sse_payload(frame)
                    if payload is None:
                        await resp.write(frame + b"\n\n")
                        continue
                    if payload.get("resumable"):
                        # suppressed: the generation is continuable — the
                        # caller re-dispatches with the committed prefix
                        return "died", str(
                            payload.get("error", "resumable upstream error")
                        )
                    if "token_ids" in payload:
                        committed.extend(
                            int(t) for t in payload["token_ids"]
                        )
                        await resp.write(frame + b"\n\n")
                        continue
                    if payload.get("done") and rewrite:
                        payload["n_tokens"] = len(committed)
                        await resp.write(
                            f"data: {json.dumps(payload)}\n\n".encode()
                        )
                        return "done", None
                    # terminal done/error frames (and anything else)
                    # forward verbatim; a non-resumable error frame is
                    # the backend's own verdict on the request
                    await resp.write(frame + b"\n\n")
                    if payload.get("done") or "error" in payload:
                        return "done", None
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return "died", str(e) or type(e).__name__
        # a torn trailing half-frame in split.pending is DROPPED, never
        # written — the resumed segment re-emits those tokens whole
        return "died", "upstream EOF before terminal frame"

    async def _proxy_stream(
        self, request, route: ServiceRoute, backend: Backend, path, fwd,
        body, *, parent=None, budget: RetryBudget | None = None,
        deadline: float | None = None,
    ):
        """Frame-aligned SSE proxy with transparent mid-stream failover.

        Upstream bytes are parsed into whole ``data:`` frames and the
        generated-token prefix the client has seen is tracked per stream.
        When the upstream dies mid-stream, the gateway re-dispatches the
        request to a healthy peer carrying the committed token ids
        (``x-kft-resume-tokens``) — the sampling seed was already stamped
        on the shared dispatch headers — and splices the continuation, so
        the client sees ONE unbroken stream. The resumed replica admits
        prompt+committed as a suffix-prefill (or a KV-span hit) and emits
        only tokens past the prefix; a ``stream.resume`` span lands under
        the original trace id next to the failed proxy span.

        Resumes are bounded by the route's ``max_attempts`` and spend the
        SAME retry budget as pre-stream retries; exhaustion (or no
        healthy peer) falls back to the pre-failover contract — one clean
        terminal error frame. A client disconnect at any point tears down
        the CURRENT upstream, first or resumed, so no engine row is
        orphaned on either replica."""
        import aiohttp
        from aiohttp import web

        span = TRACER.span("proxy", parent=parent) if parent else None
        if span:
            span.set_attr("backend", backend.url)
            span.set_attr("revision", backend.revision)
            span.set_attr("stream", True)
        hdrs = dict(fwd)
        if span:
            hdrs[TRACE_HEADER] = span.header()
        self.pool.acquire(backend)
        upstream = None
        try:
            try:
                upstream = await self._session.post(
                    backend.url + path,
                    data=body,
                    headers=hdrs,
                    timeout=aiohttp.ClientTimeout(
                        total=None,
                        sock_connect=self.config.connect_timeout_s,
                    ),
                )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                self.pool.record(backend, ok=False)
                if span:
                    span.set_attr("error", str(e) or type(e).__name__)
                    span.end("error")
                # nothing has committed to the client: _routed's retry
                # loop re-dispatches like any failed attempt
                raise _UpstreamError(backend, e) from e
            if upstream.status != 200:
                # pre-stream refusal (429 overload, 400, 501, deadline
                # shed): pass through. A 503 carrying Retry-After is a
                # coherent shed, not backend trouble — no breaker penalty.
                payload = await upstream.read()
                shed_503 = (
                    upstream.status == 503
                    and "Retry-After" in upstream.headers
                )
                if shed_503:
                    SHED.labels(
                        service=route.name, reason="upstream_shed"
                    ).inc()
                self.pool.record(
                    backend,
                    ok=shed_503
                    or upstream.status not in _BACKEND_FAILURE_STATUSES,
                )
                out_hdrs = {
                    "Content-Type": upstream.headers.get(
                        "Content-Type", "application/json"
                    )
                }
                if "Retry-After" in upstream.headers:
                    out_hdrs["Retry-After"] = upstream.headers["Retry-After"]
                if span:
                    span.set_attr("status", upstream.status)
                    span.end(
                        "shed"
                        if shed_503
                        else (
                            "error"
                            if upstream.status in _BACKEND_FAILURE_STATUSES
                            else "ok"
                        )
                    )
                return web.Response(
                    body=payload, status=upstream.status, headers=out_hdrs
                )
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                }
            )
            await resp.prepare(request)
            committed: list[int] = []
            resumes = 0
            while True:
                outcome, err = await self._pump_sse(
                    upstream, resp, committed, rewrite=resumes > 0
                )
                if outcome == "done":
                    self.pool.record(backend, ok=True)
                    if span:
                        span.set_attr("tokens", len(committed))
                        span.end()
                        span = None
                    if resumes:
                        STREAM_RESUMES.labels(
                            service=route.name, outcome="ok"
                        ).inc()
                    break
                # mid-stream death: the committed prefix is intact
                # (frame-aligned writes) — try to continue elsewhere
                self.pool.record(backend, ok=False)
                if span:
                    span.event("mid_stream_failure", error=err)
                    span.end("error")
                    span = None
                upstream.close()
                upstream = None
                self.pool.release(backend)
                dead, backend = backend, None
                while True:  # resume-dispatch rounds, bounded below
                    fail_reason = None
                    if not self.config.stream_resume:
                        fail_reason = "disabled"
                    elif resumes + 1 >= route.max_attempts or not (
                        budget is None or budget.try_spend()
                    ):
                        fail_reason = "budget_exhausted"
                    elif deadline is not None and (
                        deadline - time.monotonic() <= 0
                    ):
                        fail_reason = "failed"
                    if fail_reason is None:
                        # prefer any peer over the replica that just
                        # died (pick falls back to it when it is the
                        # only one — the watchdog may be restarting it)
                        nxt = self.pool.pick(
                            route.name, None, exclude=dead
                        )
                        if nxt is None:
                            fail_reason = "no_backend"
                    if fail_reason is not None:
                        break
                    resumes += 1
                    RETRIES.labels(service=route.name).inc()
                    span = (
                        TRACER.span("stream.resume", parent=parent)
                        if parent
                        else None
                    )
                    if span:
                        span.set_attr("backend", nxt.url)
                        span.set_attr("revision", nxt.revision)
                        span.set_attr("stream", True)
                        span.set_attr("resume", resumes)
                        span.set_attr("committed_tokens", len(committed))
                    hdrs = dict(fwd)
                    if span:
                        hdrs[TRACE_HEADER] = span.header()
                    if committed:
                        hdrs[RESUME_TOKENS_HEADER] = ",".join(
                            str(t) for t in committed
                        )
                    if deadline is not None:
                        hdrs[DEADLINE_HEADER.title()] = str(
                            max(1, int(
                                (deadline - time.monotonic()) * 1e3
                            ))
                        )
                        hdrs.pop(DEADLINE_HEADER, None)
                    self.pool.acquire(nxt)
                    backend = nxt
                    try:
                        upstream = await self._session.post(
                            nxt.url + path,
                            data=body,
                            headers=hdrs,
                            timeout=aiohttp.ClientTimeout(
                                total=None,
                                sock_connect=self.config.connect_timeout_s,
                            ),
                        )
                        if upstream.status != 200:
                            status = upstream.status
                            upstream.close()
                            upstream = None
                            raise RuntimeError(
                                f"resume dispatch returned {status}"
                            )
                    except (
                        aiohttp.ClientError,
                        asyncio.TimeoutError,
                        OSError,
                        RuntimeError,
                    ) as e:
                        self.pool.record(nxt, ok=False)
                        if span:
                            span.set_attr(
                                "error", str(e) or type(e).__name__
                            )
                            span.end("error")
                            span = None
                        if upstream is not None:
                            upstream.close()
                            upstream = None
                        self.pool.release(nxt)
                        backend = None
                        STREAM_RESUMES.labels(
                            service=route.name, outcome="failed"
                        ).inc()
                        # another dispatch round: it charges the budget
                        # again, so the whole affair stays bounded by
                        # max_attempts even if every peer refuses
                        continue
                    break  # resumed upstream is live
                if upstream is not None:
                    continue  # next _pump_sse round on the new upstream
                # no resume possible: the pre-failover contract — one
                # clean terminal error frame, never a torn socket
                if fail_reason != "disabled":
                    STREAM_RESUMES.labels(
                        service=route.name, outcome=fail_reason
                    ).inc()
                frame = json.dumps(
                    {"error": f"upstream failed mid-stream: {err}"}
                )
                await resp.write(f"data: {frame}\n\n".encode())
                break
            await resp.write_eof()
            if span:
                span.end()
            return resp
        finally:
            # satellite fix: after a resume there are N upstreams across
            # the stream's life — tear down the CURRENT one (a client
            # disconnect during failover must cancel the RESUMED
            # replica's engine row, not the dead replica's)
            if upstream is not None:
                upstream.close()  # hard close → backend sees the disconnect
            if backend is not None:
                self.pool.release(backend)
            if span is not None and span.end_time is None:
                # a client disconnect raised out of resp.write above:
                # close the span instead of leaking the trace open
                span.end("cancelled")

    # -- runtime --------------------------------------------------------- #

    async def start_async(self) -> None:
        from aiohttp import web

        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, "0.0.0.0", self.http_port)
        await site.start()
        self.http_port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001

    async def stop_async(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()  # fires _on_cleanup
            self._runner = None

    def run(self) -> None:
        """Blocking entrypoint (``kft gateway run``)."""

        async def main():
            await self.start_async()
            try:
                while True:
                    await asyncio.sleep(3600)
            finally:
                await self.stop_async()

        asyncio.run(main())
