"""Edge traffic policy: per-tenant rate limits, in-flight caps, retry budget.

Reference analogs: Istio local rate limiting + Envoy's retry budgets as
KServe deploys them, and the Kubeflow profile controller's per-namespace
quota posture (SURVEY.md §2.5). A tenant here is a profile namespace —
``PolicyEngine.from_profiles`` reads the serving fields off
``platform/profiles.py`` ``ResourceQuota`` so the SAME object that caps a
namespace's training chips caps its serving traffic.

- ``TokenBucket`` — classic rate/burst, injectable clock (tests never
  sleep); exhaustion ⇒ ``RateLimited`` ⇒ 429 with Retry-After;
- max-in-flight — concurrent requests per tenant; breach ⇒
  ``TooManyInFlight`` ⇒ 429;
- ``RetryBudget`` — transparent retries are bounded to a fraction of
  observed traffic (plus a small floor so cold gateways can still retry),
  so a dying backend cannot double the fleet's load via retry storms.

Event-loop confined: no threads, no locks.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable


class RateLimited(Exception):
    pass


class TooManyInFlight(Exception):
    pass


class TokenBucket:
    def __init__(
        self,
        rate: float,
        burst: float | None = None,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0:
            raise ValueError(f"token bucket rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, rate))
        self._clock = clock
        self._tokens = self.burst
        self._stamp = clock()

    def allow(self, n: float = 1.0) -> bool:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._stamp) * self.rate
        )
        self._stamp = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


@dataclasses.dataclass
class TenantPolicy:
    bucket: TokenBucket | None = None
    max_in_flight: int | None = None
    in_flight: int = 0
    #: shed order under sustained overload (higher = shed LAST): the
    #: gateway stamps this as ``x-kft-priority`` and the engine's
    #: admission control evicts the lowest-priority queued request first
    priority: int = 0


class PolicyEngine:
    """Admission at the edge, keyed by the ``x-kft-tenant`` header value
    (profile namespace). Tenants without a policy are unmanaged."""

    def __init__(self, policies: dict[str, TenantPolicy] | None = None):
        self._policies: dict[str, TenantPolicy] = dict(policies or {})

    @classmethod
    def from_profiles(
        cls,
        profiles: Any,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> "PolicyEngine":
        """One tenant policy per profile, from its quota's serving fields
        (``max_rps``/``burst``/``max_concurrent_requests``). Duck-typed
        against ``ProfileController.list()`` — no platform import."""
        policies: dict[str, TenantPolicy] = {}
        for p in profiles.list():
            q = p.quota
            rps = getattr(q, "max_rps", None)
            cap = getattr(q, "max_concurrent_requests", None)
            if rps is None and cap is None:
                continue
            policies[p.name] = TenantPolicy(
                bucket=(
                    TokenBucket(rps, getattr(q, "burst", None), clock=clock)
                    if rps is not None
                    else None
                ),
                max_in_flight=cap,
                priority=int(getattr(q, "priority", 0) or 0),
            )
        return cls(policies)

    def set(self, tenant: str, policy: TenantPolicy) -> None:
        self._policies[tenant] = policy

    def priority_of(self, tenant: str) -> int | None:
        """The tenant's shed priority, or None when unmanaged (the
        gateway only overwrites ``x-kft-priority`` for managed tenants —
        it is authoritative for them, a client cannot self-promote)."""
        pol = self._policies.get(tenant)
        return pol.priority if pol is not None else None

    def acquire(self, tenant: str) -> None:
        pol = self._policies.get(tenant)
        if pol is None:
            return
        # cap before bucket: a request rejected on concurrency must not
        # also burn a rate token the client never got to use
        if pol.max_in_flight is not None and pol.in_flight >= pol.max_in_flight:
            raise TooManyInFlight(
                f"tenant {tenant!r} at max in-flight ({pol.max_in_flight})"
            )
        if pol.bucket is not None and not pol.bucket.allow():
            raise RateLimited(f"tenant {tenant!r} over its request rate")
        pol.in_flight += 1

    def release(self, tenant: str) -> None:
        pol = self._policies.get(tenant)
        if pol is not None:
            pol.in_flight = max(0, pol.in_flight - 1)

    def view(self) -> dict:
        return {
            tenant: {
                "max_in_flight": pol.max_in_flight,
                "in_flight": pol.in_flight,
                "rate": pol.bucket.rate if pol.bucket else None,
                "priority": pol.priority,
            }
            for tenant, pol in sorted(self._policies.items())
        }


class RetryBudget:
    """Envoy-style retry budget: retries may be at most ``ratio`` of the
    requests seen so far, plus ``floor`` so the first failures are always
    retryable. Cumulative counters — cheap, deterministic, observable."""

    def __init__(self, *, ratio: float = 0.2, floor: int = 3):
        self.ratio = ratio
        self.floor = floor
        self.requests = 0
        self.retries = 0

    def on_request(self) -> None:
        self.requests += 1

    def try_spend(self) -> bool:
        if self.retries < self.floor + self.ratio * self.requests:
            self.retries += 1
            return True
        return False
