"""Inference gateway: the Istio-ingress/Knative-activator analog.

An L7 front door over N real ``ModelServer`` replicas — backend pool with
health probes + circuit breaking (``backends``), deterministic edge
routing with canary split and LM prefix affinity (``router``),
scale-from-zero request buffering (``activator``), per-tenant traffic
policy (``policy``), and the aiohttp proxy tying them together
(``server``). See README "Serving at the edge".
"""

from kubeflow_tpu.gateway.activator import (
    ActivationTimeout,
    Activator,
    QueueOverflow,
)
from kubeflow_tpu.gateway.backends import (
    Backend,
    BackendPool,
    BreakerConfig,
    CircuitBreaker,
)
from kubeflow_tpu.gateway.policy import (
    PolicyEngine,
    RateLimited,
    RetryBudget,
    TenantPolicy,
    TokenBucket,
    TooManyInFlight,
)
from kubeflow_tpu.gateway.router import (
    HashRing,
    RouteTable,
    ServiceRoute,
    affinity_key_of,
    canary_slot,
    pick_revision,
)
from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway

__all__ = [
    "ActivationTimeout",
    "Activator",
    "Backend",
    "BackendPool",
    "BreakerConfig",
    "CircuitBreaker",
    "GatewayConfig",
    "HashRing",
    "InferenceGateway",
    "PolicyEngine",
    "QueueOverflow",
    "RateLimited",
    "RetryBudget",
    "RouteTable",
    "ServiceRoute",
    "TenantPolicy",
    "TokenBucket",
    "TooManyInFlight",
    "affinity_key_of",
    "canary_slot",
    "pick_revision",
]
