"""SSE wire framing: ONE definition of whole-frame splitting.

The gateway's mid-stream failover (PR 18) made frame alignment a
correctness property: forwarding a torn half-frame to a client poisons its
SSE parser for every later frame, and committing tokens from a torn frame
desynchronizes the resume prefix. The load harness
(:mod:`kubeflow_tpu.loadgen.client`) accounts TTFT and token counts from
the very same frames, so both sides share this splitter — torn-frame
handling has exactly one definition, and a framing bug cannot hide by
disagreeing between the proxy and the thing measuring it.
"""

from __future__ import annotations

import json

__all__ = ["SSEFrameSplitter", "sse_payload"]


class SSEFrameSplitter:
    """Incremental ``\\n\\n``-delimited whole-frame splitter.

    ``feed(chunk)`` returns the WHOLE frames completed by that chunk
    (delimiter stripped); bytes after the last delimiter stay buffered in
    ``pending`` — the torn trailing half-frame a dying upstream leaves,
    which callers must drop, never forward or account.
    """

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> list[bytes]:
        self._buf += chunk
        *frames, self._buf = self._buf.split(b"\n\n")
        return frames

    @property
    def pending(self) -> bytes:
        return self._buf


def sse_payload(frame: bytes) -> dict | None:
    """The ``data:``-JSON payload of one whole SSE frame, or None for
    anything else (comments, other event types, unparseable JSON — all
    forwarded verbatim by the proxy, never interpreted)."""
    if not frame.startswith(b"data:"):
        return None
    try:
        payload = json.loads(frame[5:].strip())
    except ValueError:
        return None
    return payload if isinstance(payload, dict) else None
