"""Chaos overlays: compose a FaultPlan with a load run's timeline.

The chaos harness (PR 3/18) injects serving faults through the
platform's own seams; what a load run adds is a *window* — the overlay
arms each fault at a declared offset into the run and hands the reporter
the ``[start, end)`` interval, so the goodput dip is attributed to the
injected window instead of eyeballed. Serving faults only: a load run
has no trainer steps to key off, so ``at_s`` (offset from run start)
replaces ``at_step`` as the deterministic trigger.

The overlay resolves each fault's victim through a caller-supplied
``engines`` view (model name → live engine objects, harness-owned — the
same resolution :class:`~kubeflow_tpu.chaos.runner.ChaosRunner` uses for
its serving faults), and fires the existing injectors from
:mod:`kubeflow_tpu.chaos.injectors`; production code still carries zero
chaos branches.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable, Mapping, Sequence

from kubeflow_tpu.chaos import injectors
from kubeflow_tpu.chaos.plan import (
    DropKVShip,
    DropPrefixCache,
    Fault,
    FaultPlan,
    KillMidStream,
    SlowDecode,
    WedgeEngine,
)

__all__ = ["ChaosOverlay", "apply_overlay"]

SERVING_FAULTS = (
    WedgeEngine, SlowDecode, DropPrefixCache, DropKVShip, KillMidStream,
)


@dataclasses.dataclass(frozen=True)
class ChaosOverlay:
    """One fault plan armed ``at_s`` seconds into the run; the
    attribution window closes at ``at_s + window_s``."""

    plan: FaultPlan
    at_s: float
    window_s: float = 5.0

    def __post_init__(self) -> None:
        for f in self.plan.faults:
            if not isinstance(f, SERVING_FAULTS):
                raise ValueError(
                    f"{f.kind} is not a serving fault; load-run overlays "
                    "compose only with the engine-seam injectors"
                )

    @property
    def window(self) -> tuple[float, float]:
        return (self.at_s, self.at_s + self.window_s)

    @property
    def fault_kinds(self) -> tuple[str, ...]:
        return tuple(f.kind for f in self.plan.faults)


def _inject(fault: Fault, engine, *, victim_index: int = 0) -> None:
    if isinstance(fault, WedgeEngine):
        injectors.wedge_engine(engine, hold_s=fault.hold_s)
    elif isinstance(fault, SlowDecode):
        injectors.slow_decode(engine, delay_s=fault.delay_s)
    elif isinstance(fault, DropPrefixCache):
        injectors.drop_prefix_cache(engine)
    elif isinstance(fault, DropKVShip):
        injectors.drop_kv_ship(engine, count=fault.count)
    elif isinstance(fault, KillMidStream):
        # in-process harness replicas: poison the engine rather than
        # SIGKILL this very process (injectors.kill_mid_stream contract)
        from kubeflow_tpu.serve.watchdog import EngineRestarting

        injectors.kill_mid_stream(
            engine, after_tokens=fault.after_tokens,
            action=lambda eng: eng.poison(
                EngineRestarting("loadgen chaos: replica killed mid-stream")
            ),
        )
    else:  # pragma: no cover — guarded by __post_init__
        raise ValueError(f"unhandled fault kind {fault.kind}")


async def apply_overlay(
    overlay: ChaosOverlay,
    engines: Callable[[str], Sequence] | Mapping[str, Sequence],
    *,
    t0: float,
) -> list[str]:
    """Sleep until ``t0 + overlay.at_s`` (monotonic), then fire every
    fault in plan order. The victim is drawn deterministically from the
    plan seed over the model's CURRENT engines. Returns the injected
    kinds (for the report's ``chaos.faults``)."""
    import random

    delay = t0 + overlay.at_s - time.monotonic()
    if delay > 0:
        await asyncio.sleep(delay)
    rng = random.Random(f"{overlay.plan.seed}:overlay")
    fired: list[str] = []
    for fault in overlay.plan.faults:
        model = getattr(fault, "model", "")
        pool = (
            engines(model) if callable(engines)
            else engines.get(model, ())
        )
        pool = [e for e in pool if e is not None]
        if not pool:
            continue  # the victim scaled away before the window opened
        victim = pool[rng.randrange(len(pool))]
        _inject(fault, victim)
        fired.append(fault.kind)
    return fired
