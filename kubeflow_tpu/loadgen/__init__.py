"""Production load harness: open-loop traffic, SLO goodput, chaos overlays.

The serving-plane answer to "how do we KNOW it holds up": seeded
open-loop arrival processes (Poisson, bursty on-off, trace replay) drive
the real :class:`~kubeflow_tpu.gateway.server.InferenceGateway` + an
autoscaled :class:`~kubeflow_tpu.autoscale.fleet.ReplicaFleet` over
HTTP/SSE, a reporter folds server metrics and client truth into one
machine-readable goodput report, and chaos overlays compose the PR 3/18
fault plans with the run timeline so every goodput dip is attributed to
its injected window.

- :mod:`arrivals` — seeded schedules: same seed, same offsets, always;
- :mod:`workload` — prompt/output-length mixtures + per-tenant
  deadline/priority/adapter header mixes;
- :mod:`client` — the open-loop HTTP/SSE driver (gateway's own frame
  splitter; client-side outcome taxonomy);
- :mod:`reporter` — ``/metrics`` + ``/debug/traces`` → the
  ``BENCH_*.json``-compatible report;
- :mod:`chaos` — FaultPlan overlays armed at run offsets;
- :mod:`harness` — the CPU-runnable end-to-end assembly behind
  ``bench.py serving_load`` and ``kft loadgen``.
"""

from kubeflow_tpu.loadgen.arrivals import (
    OnOffArrivals,
    PoissonArrivals,
    ReplayArrivals,
    ReplayRequest,
)
from kubeflow_tpu.loadgen.chaos import ChaosOverlay, apply_overlay
from kubeflow_tpu.loadgen.client import (
    LoadClient,
    RequestResult,
    summarize_outcomes,
)
from kubeflow_tpu.loadgen.reporter import (
    build_report,
    goodput,
    histogram_quantile,
    scrape_metrics,
)
from kubeflow_tpu.loadgen.workload import RequestSpec, TenantSpec, WorkloadMix

__all__ = [
    "ChaosOverlay",
    "LoadClient",
    "OnOffArrivals",
    "PoissonArrivals",
    "ReplayArrivals",
    "ReplayRequest",
    "RequestResult",
    "RequestSpec",
    "TenantSpec",
    "WorkloadMix",
    "apply_overlay",
    "build_report",
    "goodput",
    "histogram_quantile",
    "scrape_metrics",
    "summarize_outcomes",
]
