"""Open-loop HTTP/SSE client: fires the schedule at the REAL gateway.

No mocked seams — requests go over the wire through the same
``/v2/models/{m}/generate_stream`` path production streams ride, and SSE
accounting (TTFT at the first whole ``token_ids`` frame, token counts,
terminal-frame detection) reuses the gateway's own frame splitter
(:mod:`kubeflow_tpu.gateway.sse`), so torn-frame handling has exactly one
definition between the proxy and the harness measuring it.

Outcome taxonomy (client truth, scored against each request's SLO):

- ``completed_in_slo`` — terminal ``done`` frame, within ``slo_ms`` (or
  no SLO configured);
- ``completed_late`` — completed, but past the SLO (a *violation* in the
  Knative goodput sense: the work was done, the promise was not kept);
- ``shed`` — a coherent load-shed: 503 + ``Retry-After`` or 429. The
  platform chose not to take the work; sheds are goodput losses but NOT
  failures;
- ``error`` — anything else (5xx, torn stream without a terminal frame,
  transport error). The zero-client-visible-failures invariant binds HERE.

Being open-loop, a request fires at its scheduled offset regardless of
how many are still in flight; the dispatch loop never awaits a response.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import time
from typing import Sequence

from kubeflow_tpu.gateway.sse import SSEFrameSplitter, sse_payload
from kubeflow_tpu.loadgen.workload import RequestSpec
from kubeflow_tpu.obs import names, prom

__all__ = ["RequestResult", "LoadClient", "summarize_outcomes"]

CLIENT_REQUESTS = prom.REGISTRY.counter(
    names.LOADGEN_REQUESTS_TOTAL,
    "loadgen client-side request verdicts",
    ("tenant", "outcome"),
)


@dataclasses.dataclass
class RequestResult:
    """Client-side truth for one fired request."""

    index: int
    tenant: str
    priority: int | None
    offset_s: float          # scheduled arrival offset
    outcome: str             # completed_in_slo|completed_late|shed|error
    status: int = 0
    ttft_ms: float | None = None
    e2e_ms: float = 0.0
    tokens: int = 0
    slo_ms: float | None = None
    error: str | None = None

    @property
    def failed(self) -> bool:
        return self.outcome == "error"


def summarize_outcomes(results: Sequence[RequestResult]) -> dict[str, int]:
    out = {
        "completed_in_slo": 0, "completed_late": 0, "shed": 0, "error": 0,
    }
    for r in results:
        out[r.outcome] = out.get(r.outcome, 0) + 1
    return out


class LoadClient:
    """Drives one arrival schedule against one gateway service."""

    def __init__(
        self,
        base_url: str,
        model: str,
        *,
        stream: bool = True,
        request_timeout_s: float = 180.0,
        connector_limit: int = 256,
    ):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.stream = stream
        self.request_timeout_s = request_timeout_s
        self.connector_limit = connector_limit

    async def run(
        self,
        schedule: Sequence[float],
        specs: Sequence[RequestSpec],
        *,
        on_dispatch=None,
    ) -> list[RequestResult]:
        """Fire ``specs[i]`` at ``t0 + schedule[i]``; returns results in
        spec order once every stream settles. ``on_dispatch(i, t_rel)``
        (optional) observes each dispatch — the chaos overlay keys its
        injection window off it."""
        import aiohttp

        if len(schedule) != len(specs):
            raise ValueError(
                f"schedule ({len(schedule)}) and specs ({len(specs)}) "
                "must align"
            )
        conn = aiohttp.TCPConnector(limit=self.connector_limit)
        timeout = aiohttp.ClientTimeout(total=self.request_timeout_s)
        results: list[RequestResult | None] = [None] * len(specs)
        async with aiohttp.ClientSession(
            connector=conn, timeout=timeout
        ) as session:
            t0 = time.monotonic()
            tasks = []
            for pos, (offset, spec) in enumerate(zip(schedule, specs)):
                delay = t0 + offset - time.monotonic()
                if delay > 0:
                    await asyncio.sleep(delay)
                if on_dispatch is not None:
                    on_dispatch(spec.index, time.monotonic() - t0)
                tasks.append(asyncio.ensure_future(
                    self._one(session, spec, offset, results, pos)
                ))
            if tasks:
                await asyncio.gather(*tasks)
        return [r for r in results if r is not None]

    # -- one request ------------------------------------------------------ #

    async def _one(self, session, spec: RequestSpec, offset: float,
                   results: list, pos: int) -> None:
        res = RequestResult(
            index=spec.index, tenant=spec.tenant, priority=spec.priority,
            offset_s=offset, outcome="error", slo_ms=spec.slo_ms,
        )
        start = time.monotonic()
        try:
            if self.stream:
                await self._stream_once(session, spec, res, start)
            else:
                await self._unary_once(session, spec, res, start)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — client truth, not a crash
            res.outcome = "error"
            res.error = f"{type(e).__name__}: {e}"
        res.e2e_ms = (time.monotonic() - start) * 1e3
        if res.outcome.startswith("completed"):
            late = res.slo_ms is not None and res.e2e_ms > res.slo_ms
            res.outcome = "completed_late" if late else "completed_in_slo"
        CLIENT_REQUESTS.labels(tenant=res.tenant, outcome=res.outcome).inc()
        results[pos] = res

    def _body(self, spec: RequestSpec) -> bytes:
        return json.dumps({
            "input_ids": list(spec.prompt_ids),
            "max_new_tokens": spec.max_new_tokens,
        }).encode()

    @staticmethod
    def _classify_refusal(res: RequestResult, status: int,
                          retry_after: str | None, body: str) -> None:
        if status == 429 or (status == 503 and retry_after is not None):
            # coherent shed: the platform declined rationally (rate
            # limit / overload / provably-late deadline)
            res.outcome = "shed"
        else:
            res.outcome = "error"
            res.error = f"HTTP {status}: {body[:200]}"

    async def _stream_once(self, session, spec, res, start) -> None:
        url = f"{self.base_url}/v2/models/{self.model}/generate_stream"
        headers = dict(spec.headers)
        headers["x-request-id"] = f"loadgen-{spec.index}"
        async with session.post(
            url, data=self._body(spec), headers=headers
        ) as resp:
            res.status = resp.status
            if resp.status != 200:
                self._classify_refusal(
                    res, resp.status, resp.headers.get("Retry-After"),
                    (await resp.read()).decode(errors="replace"),
                )
                return
            split = SSEFrameSplitter()
            terminal = False
            async for chunk in resp.content.iter_any():
                for frame in split.feed(chunk):
                    payload = sse_payload(frame)
                    if payload is None:
                        continue
                    if "token_ids" in payload:
                        if res.ttft_ms is None:
                            res.ttft_ms = (
                                (time.monotonic() - start) * 1e3
                            )
                        res.tokens += len(payload["token_ids"])
                        continue
                    if payload.get("done"):
                        res.outcome = "completed"
                        terminal = True
                        continue
                    if "error" in payload:
                        res.outcome = "error"
                        res.error = str(payload["error"])
                        terminal = True
            if not terminal:
                # EOF without a terminal frame — a torn stream IS a
                # client-visible failure; any torn half-frame bytes in
                # split.pending were never accounted
                res.outcome = "error"
                res.error = "stream EOF before terminal frame"

    async def _unary_once(self, session, spec, res, start) -> None:
        url = f"{self.base_url}/v2/models/{self.model}/generate"
        headers = dict(spec.headers)
        headers["x-request-id"] = f"loadgen-{spec.index}"
        async with session.post(
            url, data=self._body(spec), headers=headers
        ) as resp:
            res.status = resp.status
            body = await resp.read()
            if resp.status != 200:
                self._classify_refusal(
                    res, resp.status, resp.headers.get("Retry-After"),
                    body.decode(errors="replace"),
                )
                return
            try:
                res.tokens = len(json.loads(body).get("token_ids", ()))
            except ValueError:
                pass
            res.outcome = "completed"
