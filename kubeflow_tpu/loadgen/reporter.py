"""Report builder: server metrics + client truth → one machine-readable
goodput report.

The report is the deliverable of a load run — the ``BENCH_*.json``-
compatible trajectory anchor. Latency comes from the PR 15 server-side
histograms (``kft_server_ttft_ms``/``kft_server_tpot_ms``), quantiles
estimated with the standard Prometheus ``histogram_quantile`` bucket
interpolation; goodput comes from the CLIENT's outcome record (server
counters can't see a response that died on the wire); autoscale timing
comes from the fleet's read-only scale-event log; stream-resume and
prefix counters come straight off ``/metrics``. When a chaos overlay ran,
the report splits goodput inside vs outside the injected window, so the
dip is *attributed*, not merely present.

Schema: see ``BENCH_SCHEMA.md`` (kept next to the ``BENCH_*.json``
trajectory files it explains).
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from kubeflow_tpu.autoscale.signals import metric_sum, parse_prom_text
from kubeflow_tpu.loadgen.client import RequestResult, summarize_outcomes
from kubeflow_tpu.obs import names

__all__ = [
    "histogram_quantile",
    "goodput",
    "build_report",
    "scrape_metrics",
]


def _matches(labels: Mapping[str, str], match: Mapping[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in match.items())


def histogram_quantile(
    parsed: Mapping[str, list], name: str, q: float, **match: str
) -> float | None:
    """Prometheus-idiom quantile estimate from ``<name>_bucket`` samples:
    find the bucket the q-th observation falls in, interpolate linearly
    inside it (the +Inf bucket clamps to the last finite bound). Buckets
    with matching labels are summed first, so a per-model quantile and an
    all-models quantile use the same code path."""
    buckets: dict[float, float] = {}
    for labels, value in parsed.get(f"{name}_bucket", ()):
        rest = {k: v for k, v in labels.items() if k != "le"}
        if not _matches(rest, match):
            continue
        le = labels.get("le", "+Inf")
        bound = float("inf") if le == "+Inf" else float(le)
        buckets[bound] = buckets.get(bound, 0.0) + value
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets[bounds[-1]]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_count = 0.0, 0.0
    for bound in bounds:
        count = buckets[bound]
        if count >= rank:
            if bound == float("inf"):
                # can't interpolate into +Inf: clamp to last finite bound
                finite = [b for b in bounds if b != float("inf")]
                return finite[-1] if finite else None
            if count == prev_count:
                return bound
            frac = (rank - prev_count) / (count - prev_count)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_count = bound, count
    return bounds[-1]


def _hist_summary(
    parsed: Mapping[str, list], name: str, **match: str
) -> dict[str, Any]:
    count = metric_sum(parsed, f"{name}_count", **match)
    out = {
        "count": int(count),
        "p50": histogram_quantile(parsed, name, 0.50, **match),
        "p99": histogram_quantile(parsed, name, 0.99, **match),
    }
    if count:
        out["mean"] = metric_sum(parsed, f"{name}_sum", **match) / count
    return out


def _pct(xs: Sequence[float], q: float) -> float | None:
    if not xs:
        return None
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(q * len(ys)))]


def goodput(results: Sequence[RequestResult]) -> dict[str, Any]:
    """Knative-style SLO goodput over one result set: the fraction of
    OFFERED load completed within its SLO. Sheds and late completions
    both count against goodput (the platform either refused the work or
    broke the promise); only ``error`` counts as a failure."""
    outcomes = summarize_outcomes(results)
    n = len(results)
    return {
        "offered": n,
        **outcomes,
        "goodput": (outcomes["completed_in_slo"] / n) if n else None,
    }


def _grouped(results: Sequence[RequestResult], key) -> dict[str, Any]:
    groups: dict[str, list[RequestResult]] = {}
    for r in results:
        groups.setdefault(str(key(r)), []).append(r)
    return {k: goodput(v) for k, v in sorted(groups.items())}


def _scale_up_latency(
    events: Sequence[Mapping[str, Any]], t0: float
) -> dict[str, Any]:
    """1→N scale-up timing from the fleet's event log: offset (from run
    start ``t0``, monotonic) at which each replica count was FIRST
    reached, plus the latency from run start to the peak. Events before
    ``t0`` (initial provisioning, warmup) appear in the timeline but do
    not count as scale-up — the latency measured is the autoscaler's
    reaction to the run's load, not the harness's setup."""
    first_reach: dict[int, float] = {}
    peak = 0
    for ev in events:
        n = int(ev["replicas"])
        t = ev["t"] - t0
        if t < 0:
            continue
        peak = max(peak, n)
        if n not in first_reach and ev["direction"] == "up":
            first_reach[n] = t
    return {
        "replicas_peak": peak,
        "first_reached_s": {
            str(n): round(t, 3) for n, t in sorted(first_reach.items())
        },
        "scale_up_latency_s": (
            round(first_reach[peak], 3) if peak in first_reach else None
        ),
        "events": [
            {
                "t_s": round(ev["t"] - t0, 3),
                "replicas": ev["replicas"],
                "direction": ev["direction"],
            }
            for ev in events
        ],
    }


def build_report(
    *,
    results: Sequence[RequestResult],
    run: Mapping[str, Any],
    gateway_metrics: str | None = None,
    replica_metrics: Sequence[str] = (),
    baseline_metrics: str | None = None,
    traces: Mapping[str, Any] | None = None,
    fleet_events: Sequence[Mapping[str, Any]] = (),
    run_t0: float | None = None,
    chaos_window: tuple[float, float] | None = None,
    chaos_faults: Sequence[str] = (),
    extra: Mapping[str, Any] | None = None,
) -> dict[str, Any]:
    """Pure function from scraped text + client results to the report.

    ``gateway_metrics``/``replica_metrics`` are raw ``/metrics`` bodies
    (in-process harnesses share one registry, so the gateway body alone
    already carries the engine histograms; remote replicas add theirs).
    ``baseline_metrics`` is a pre-run scrape: counter-like samples
    (``*_total``/``_bucket``/``_sum``/``_count``) have their baseline
    value subtracted, so warmup traffic and prior runs in the same
    process drop out of the report. ``chaos_window`` is
    ``(start_s, end_s)`` offsets into the run.
    """
    merged: dict[str, list] = {}
    for text in ([gateway_metrics] if gateway_metrics else []) + list(
        replica_metrics
    ):
        for name, samples in parse_prom_text(text).items():
            # in-process replicas share the gateway registry: identical
            # (labels, value) samples are the SAME child scraped twice,
            # not two replicas — keep one copy
            seen = merged.setdefault(name, [])
            for s in samples:
                if s not in seen:
                    seen.append(s)
    if baseline_metrics:
        base: dict[tuple[str, frozenset], float] = {}
        for name, samples in parse_prom_text(baseline_metrics).items():
            for labels, value in samples:
                base[(name, frozenset(labels.items()))] = value
        counterish = ("_total", "_bucket", "_sum", "_count")
        for name, samples in merged.items():
            if not name.endswith(counterish):
                continue  # gauges carry state, not accumulation
            merged[name] = [
                (labels, max(
                    0.0,
                    value - base.get(
                        (name, frozenset(labels.items())), 0.0
                    ),
                ))
                for labels, value in samples
            ]

    ttft_client = [r.ttft_ms for r in results if r.ttft_ms is not None]
    latency = {
        "ttft_ms": _hist_summary(merged, names.SERVER_TTFT_MS),
        "tpot_ms": _hist_summary(merged, names.SERVER_TPOT_MS),
        "client_ttft_ms": {
            "count": len(ttft_client),
            "p50": _pct(ttft_client, 0.50),
            "p99": _pct(ttft_client, 0.99),
        },
        "client_e2e_ms": {
            "p50": _pct([r.e2e_ms for r in results], 0.50),
            "p99": _pct([r.e2e_ms for r in results], 0.99),
        },
    }

    report: dict[str, Any] = {
        "run": dict(run),
        "latency": latency,
        "goodput": {
            "overall": goodput(results),
            "per_tenant": _grouped(results, lambda r: r.tenant),
            "per_priority": _grouped(
                results,
                lambda r: r.priority if r.priority is not None else "none",
            ),
        },
        "server": {
            "requests_total": metric_sum(
                merged, names.GATEWAY_REQUESTS_TOTAL
            ),
            "shed_total": metric_sum(merged, names.GATEWAY_SHED_TOTAL),
            "retries_total": metric_sum(
                merged, names.GATEWAY_RETRIES_TOTAL
            ),
            "stream_resumes_ok": metric_sum(
                merged, names.GATEWAY_STREAM_RESUMES_TOTAL, outcome="ok"
            ),
            "stream_resumes_failed": metric_sum(
                merged, names.GATEWAY_STREAM_RESUMES_TOTAL,
                outcome="failed",
            ),
            "engine_deadline_expired": metric_sum(
                merged, names.ENGINE_DEADLINE_EXPIRED_TOTAL
            ),
            "engine_admission_shed": metric_sum(
                merged, names.ENGINE_ADMISSION_SHED_TOTAL
            ),
            "prefix_hits_total": metric_sum(
                merged, names.ENGINE_PREFIX_HITS_TOTAL
            ),
            "kv_transfers_total": metric_sum(
                merged, names.AUTOSCALER_KV_TRANSFERS_TOTAL
            ),
            "chaos_injected_total": metric_sum(
                merged, names.CHAOS_INJECTED_TOTAL
            ),
        },
    }
    if fleet_events and run_t0 is not None:
        report["autoscale"] = _scale_up_latency(fleet_events, run_t0)
    if traces is not None:
        report["traces"] = {
            "finished": traces.get("finished"),
            "kept": len(traces.get("traces", ())),
            "p99_ms": traces.get("p99_ms"),
        }
    if chaos_window is not None:
        a, b = chaos_window
        inside = [r for r in results if a <= r.offset_s < b]
        outside = [r for r in results if not (a <= r.offset_s < b)]
        gin, gout = goodput(inside), goodput(outside)
        report["chaos"] = {
            "faults": list(chaos_faults),
            "window_s": [round(a, 3), round(b, 3)],
            "in_window": gin,
            "outside_window": gout,
            # the attribution headline: how much goodput the injected
            # window cost relative to the rest of the run
            "goodput_dip": (
                round(gout["goodput"] - gin["goodput"], 4)
                if gin["goodput"] is not None
                and gout["goodput"] is not None else None
            ),
            "client_visible_failures": sum(r.failed for r in results),
        }
    if extra:
        report.update(extra)
    return report


async def scrape_metrics(url: str, *, timeout_s: float = 30.0) -> str:
    """GET one ``/metrics`` (or ``/debug/traces``) body."""
    import aiohttp

    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=timeout_s)
    ) as session:
        async with session.get(url) as resp:
            resp.raise_for_status()
            return await resp.text()
