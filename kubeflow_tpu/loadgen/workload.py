"""Workload mixtures: what each arrival actually asks the platform to do.

An arrival schedule says *when*; the mix says *what* — prompt length,
output budget, and the per-tenant wire headers (deadline, priority,
adapter) that drive the gateway's policy plane and the engine's
deadline-aware admission. Like the schedule, the whole plan is a value:
``WorkloadMix.plan(n)`` derives every draw from the mix seed alone, so a
re-run offers the identical request sequence and any goodput delta is the
platform's, not the generator's.

Tenants model the SLO shapes production mixes: an interactive tenant
with a tight deadline and high priority, a batch tenant with no deadline
riding standby capacity, each optionally pinned to a named adapter
(:data:`~kubeflow_tpu.obs.headers.ADAPTER_HEADER`). ``slo_ms`` is the
*accounting* SLO the reporter scores goodput against; ``deadline_ms`` is
what gets stamped on the wire (and so what the platform may shed against)
— by default they coincide.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Sequence

from kubeflow_tpu.obs.headers import (
    ADAPTER_HEADER,
    DEADLINE_HEADER,
    PRIORITY_HEADER,
    TENANT_HEADER,
)

__all__ = ["TenantSpec", "RequestSpec", "WorkloadMix"]


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One traffic class: selection weight, wire headers, accounting SLO."""

    name: str
    weight: float = 1.0
    priority: int | None = None
    deadline_ms: float | None = None
    adapter: str | None = None
    #: goodput SLO in ms (completed within → goodput); None falls back to
    #: ``deadline_ms``; both None → any completion counts
    slo_ms: float | None = None

    @property
    def effective_slo_ms(self) -> float | None:
        return self.slo_ms if self.slo_ms is not None else self.deadline_ms

    def headers(self) -> dict[str, str]:
        h = {TENANT_HEADER: self.name}
        if self.priority is not None:
            h[PRIORITY_HEADER] = str(self.priority)
        if self.deadline_ms is not None:
            h[DEADLINE_HEADER] = str(int(self.deadline_ms))
        if self.adapter is not None:
            h[ADAPTER_HEADER] = self.adapter
        return h


@dataclasses.dataclass(frozen=True)
class RequestSpec:
    """One fully-drawn request: everything the client needs to fire it."""

    index: int
    tenant: str
    prompt_ids: tuple[int, ...]
    max_new_tokens: int
    headers: tuple[tuple[str, str], ...]
    slo_ms: float | None
    priority: int | None


@dataclasses.dataclass(frozen=True)
class WorkloadMix:
    """Weighted prompt/output-length mixture over a tenant population."""

    prompt_lens: tuple[int, ...] = (8, 16, 32)
    prompt_weights: tuple[float, ...] | None = None
    output_lens: tuple[int, ...] = (4, 8, 16)
    output_weights: tuple[float, ...] | None = None
    tenants: tuple[TenantSpec, ...] = (TenantSpec("default"),)
    #: prompt token ids are drawn uniformly from [2, 2+vocab) — id 0/1
    #: stay clear of pad/EOS conventions in the bench models
    vocab: int = 80
    seed: int = 0

    def tenant_named(self, name: str) -> TenantSpec:
        for t in self.tenants:
            if t.name == name:
                return t
        raise KeyError(name)

    def plan(self, n: int) -> tuple[RequestSpec, ...]:
        """The first ``n`` requests of this mix — pure function of
        ``(mix, seed, n)``; a longer plan extends a shorter one."""
        rng = random.Random(f"{self.seed}:workload")
        weights = list(self.prompt_weights or [1.0] * len(self.prompt_lens))
        oweights = list(self.output_weights or [1.0] * len(self.output_lens))
        tweights = [t.weight for t in self.tenants]
        out: list[RequestSpec] = []
        for i in range(n):
            tenant = rng.choices(self.tenants, weights=tweights)[0]
            plen = rng.choices(self.prompt_lens, weights=weights)[0]
            out_len = rng.choices(self.output_lens, weights=oweights)[0]
            prompt = tuple(
                rng.randrange(2, 2 + self.vocab) for _ in range(plen)
            )
            out.append(RequestSpec(
                index=i,
                tenant=tenant.name,
                prompt_ids=prompt,
                max_new_tokens=out_len,
                headers=tuple(sorted(tenant.headers().items())),
                slo_ms=tenant.effective_slo_ms,
                priority=tenant.priority,
            ))
        return tuple(out)

    def plan_for_replay(
        self, requests: Sequence, *, cap_new_tokens: int | None = None
    ) -> tuple[RequestSpec, ...]:
        """Request specs shaped by a replay dump: prompt length and output
        budget come from each :class:`~.arrivals.ReplayRequest` (token IDS
        are re-drawn from the seed — a trace dump records lengths, not
        content), tenant headers still draw from this mix."""
        rng = random.Random(f"{self.seed}:replay")
        tweights = [t.weight for t in self.tenants]
        out: list[RequestSpec] = []
        for i, r in enumerate(requests):
            tenant = rng.choices(self.tenants, weights=tweights)[0]
            new = r.max_new_tokens or self.output_lens[0]
            if cap_new_tokens is not None:
                new = min(new, cap_new_tokens)
            prompt = tuple(
                rng.randrange(2, 2 + self.vocab)
                for _ in range(max(1, r.prompt_tokens))
            )
            out.append(RequestSpec(
                index=i,
                tenant=tenant.name,
                prompt_ids=prompt,
                max_new_tokens=new,
                headers=tuple(sorted(tenant.headers().items())),
                slo_ms=tenant.effective_slo_ms,
                priority=tenant.priority,
            ))
        return tuple(out)
