"""CPU-runnable serving-load harness: the REAL stack, in one process.

Assembles exactly the production serving plane — ``InferenceGateway``
edge (policy, retries, SSE failover), ``ServingAutoscaler`` +
``GatewaySignalSource`` + ``ReplicaFleet``, and in-process
``ModelServer`` replicas running the real ``LMEngine`` over a tiny
transformer — drives a seeded open-loop schedule through it over
HTTP/SSE, and returns the goodput report. No mocked seams: every request
crosses the wire twice and every metric the reporter reads is scraped
off ``/metrics`` like any Prometheus would.

This is what ``bench.py serving_load``, the smoke step, and the slow e2e
test share; they differ only in knobs (duration, chaos overlay, KPA
shape). CPU-only by construction — the bench anchor this provides is
what keeps the perf trajectory measurable when the TPU tunnel dies.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import time
from typing import Any

from kubeflow_tpu.loadgen.arrivals import OnOffArrivals, PoissonArrivals
from kubeflow_tpu.loadgen.chaos import ChaosOverlay, apply_overlay
from kubeflow_tpu.loadgen.client import LoadClient
from kubeflow_tpu.loadgen.reporter import build_report, scrape_metrics
from kubeflow_tpu.loadgen.workload import TenantSpec, WorkloadMix

__all__ = ["HarnessConfig", "run_serving_load", "default_mix"]


def default_mix(seed: int = 0) -> WorkloadMix:
    """The bench's standard two-class tenant population: an interactive
    tenant with a deadline and priority riding next to best-effort batch
    traffic pinned to an adapter — the mix the SLO-goodput story is
    about."""
    return WorkloadMix(
        prompt_lens=(6, 10, 16),
        output_lens=(4, 6, 8),
        tenants=(
            TenantSpec(
                "interactive", weight=2.0, priority=2,
                deadline_ms=30_000.0, slo_ms=30_000.0,
            ),
            TenantSpec(
                "batch", weight=1.0, priority=0, adapter="batch-v1",
            ),
        ),
        vocab=80,
        seed=seed,
    )


@dataclasses.dataclass
class HarnessConfig:
    seed: int = 0
    process: str = "poisson"          # poisson | onoff
    rate_rps: float = 6.0
    burst_rps: float = 12.0           # onoff only
    period_s: float = 4.0             # onoff only
    duration_s: float = 10.0
    mix: WorkloadMix | None = None
    model_name: str = "m"
    initial_replicas: int = 1
    max_replicas: int = 2
    min_replicas: int = 1
    kpa_target: float = 2.0
    scale_to_zero_grace_s: float = 1.2
    #: after the measured window: let the fleet drain to zero, then time
    #: one cold request through the activator (needs min_replicas=0)
    measure_cold_recovery: bool = False
    chaos: ChaosOverlay | None = None
    request_timeout_s: float = 180.0
    max_new_tokens_cap: int = 12      # model-level engine cap


def _schedule(cfg: HarnessConfig):
    if cfg.process == "poisson":
        return PoissonArrivals(
            rate_rps=cfg.rate_rps, duration_s=cfg.duration_s,
            seed=cfg.seed,
        ).schedule()
    if cfg.process == "onoff":
        return OnOffArrivals(
            base_rps=cfg.rate_rps, burst_rps=cfg.burst_rps,
            period_s=cfg.period_s, duration_s=cfg.duration_s,
            seed=cfg.seed,
        ).schedule()
    raise ValueError(f"unknown arrival process {cfg.process!r}")


async def run_serving_load(cfg: HarnessConfig) -> dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.autoscale import (
        GatewaySignalSource,
        KPAConfig,
        ReplicaFleet,
        ServingAutoscaler,
    )
    from kubeflow_tpu.gateway.router import ServiceRoute
    from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway
    from kubeflow_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )
    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    mix = cfg.mix or default_mix(cfg.seed)
    tcfg = TransformerConfig(
        vocab_size=89, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        causal=True, max_seq_len=256, attn_impl="reference",
        dtype=jnp.float32,
    )
    tlm = TransformerLM(tcfg)
    params = tlm.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    replicas: dict[str, LMEngineModel] = {}

    async def launch(index: int):
        m = LMEngineModel(
            cfg.model_name, None, config=tcfg, max_batch=4, chunk_steps=2,
            buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
            max_new_tokens=cfg.max_new_tokens_cap, eos_id=tcfg.vocab_size + 1,
            # min_wedge must exceed worst-case CPU compile stalls or the
            # watchdog false-trips during warmup; a chaos-wedged engine
            # recovers via the injector's hold_s expiring + gateway
            # retries/breaker, same as the smoke failover step
            watchdog_interval_s=0.1, watchdog_min_wedge_s=60.0,
            prefix_cache_entries=32,
        )
        m.load()
        m._params = jax.device_put(params)  # identical weights per replica
        m.engine.stop()
        m.engine = m._make_engine().start()
        ms = ModelServer([m], http_port=0)
        await ms.start_async()
        (site,) = ms._runner.sites
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}"
        replicas[url] = m

        async def stop():
            replicas.pop(url, None)
            m.unload()
            await ms.stop_async()

        return url, stop

    asc = ServingAutoscaler(tick_interval_s=0.15)
    gw = InferenceGateway(GatewayConfig(
        probe_interval_s=0.25, failure_threshold=2, recovery_s=1.0,
        activation_timeout_s=60.0, retry_budget_floor=100,
        routes=[ServiceRoute(name=cfg.model_name, max_attempts=4)],
    ), http_port=0, scale_up=asc.kick)
    fleet = ReplicaFleet(
        cfg.model_name, launch, pool=gw.pool, model=cfg.model_name,
    )
    source = GatewaySignalSource(gw, cfg.model_name)
    asc.add_service(cfg.model_name, KPAConfig(
        target=cfg.kpa_target, min_replicas=cfg.min_replicas,
        max_replicas=cfg.max_replicas, stable_window_s=3.0,
        panic_window_s=0.6, panic_threshold=1.5, max_scale_down_rate=2.0,
        scale_to_zero_grace_s=cfg.scale_to_zero_grace_s,
    ), source, fleet)

    schedule = _schedule(cfg)
    specs = mix.plan(len(schedule))
    client = LoadClient(
        "http://127.0.0.1:0", cfg.model_name,
        request_timeout_s=cfg.request_timeout_s,
    )

    try:
        await fleet.scale_to(cfg.initial_replicas)
        await gw.start_async()
        client.base_url = f"http://127.0.0.1:{gw.http_port}"

        # warm EVERY initial replica through its compiles OUTSIDE the
        # measured window, over the real streaming path (bare
        # engine.submit misses the stream programs) and WITH a seed
        # header — the gateway stamps x-kft-seed on every generate
        # request, and the seeded sampler is a separate compiled program
        # from the unseeded one. Requests go to the replica DIRECTLY,
        # with no trace header — untraced requests record nothing in the
        # TTFT/TPOT histograms (obs/trace.py contract). One request per
        # distinct (prompt_len, budget) shape in the plan; replicas the
        # autoscaler launches mid-run stay cold on purpose (their
        # compile stall IS scale-up latency).
        import aiohttp as _aiohttp

        from kubeflow_tpu.obs.headers import SEED_HEADER

        shapes: dict[tuple[int, int], Any] = {}
        for spec in specs:
            shapes.setdefault(
                (len(spec.prompt_ids), spec.max_new_tokens), spec
            )
        async with _aiohttp.ClientSession(
            timeout=_aiohttp.ClientTimeout(total=cfg.request_timeout_s)
        ) as warm_session:
            for url in list(replicas):
                for spec in shapes.values():
                    async with warm_session.post(
                        f"{url}/v2/models/{cfg.model_name}/generate_stream",
                        data=json.dumps({
                            "input_ids": list(spec.prompt_ids),
                            "max_new_tokens": min(
                                spec.max_new_tokens,
                                cfg.max_new_tokens_cap,
                            ),
                        }).encode(),
                        headers={SEED_HEADER: "1"},
                    ) as resp:
                        await resp.read()

        def engines(model: str):
            live = set(fleet.urls())
            return [
                m.engine for url, m in replicas.items()
                if url in live and m.name == model and m.engine is not None
            ]

        # baseline scrape: warmup traffic (and any earlier run in this
        # process) is subtracted out of the report's counters/histograms
        baseline = await scrape_metrics(client.base_url + "/metrics")

        asc.start()
        t0 = time.monotonic()
        chaos_task = None
        if cfg.chaos is not None:
            chaos_task = asyncio.ensure_future(
                apply_overlay(cfg.chaos, engines, t0=t0)
            )
        results = await client.run(schedule, specs)
        fired: list[str] = []
        if chaos_task is not None:
            fired = await chaos_task
        await asc.stop()

        gw_metrics = await scrape_metrics(client.base_url + "/metrics")
        # /debug/traces lives on the replica ModelServer (PR 15); any
        # live replica sees the whole in-process ring buffer
        traces = None
        if fleet.urls():
            traces = json.loads(await scrape_metrics(
                fleet.urls()[0] + "/debug/traces?limit=256"
            ))

        extra: dict[str, Any] = {}
        if cfg.measure_cold_recovery and cfg.min_replicas == 0:
            # drain: stable window empties, grace expires, replicas -> 0
            asc.start()
            deadline = time.monotonic() + 60
            while fleet.current() > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            await asc.stop()
            if fleet.current() == 0:
                # one cold request parks in the activator, kicks the
                # autoscaler, and times the 0->1 relaunch end to end
                asc.start()
                cold0 = time.monotonic()
                one = await client.run(
                    (0.0,), (dataclasses.replace(specs[0], index=0),)
                )
                await asc.stop()
                extra["cold_recovery"] = {
                    "recovery_s": round(time.monotonic() - cold0, 3),
                    "outcome": one[0].outcome,
                }

        return build_report(
            results=results,
            run={
                "bench": "serving_load",
                "seed": cfg.seed,
                "process": cfg.process,
                "rate_rps": cfg.rate_rps,
                "duration_s": cfg.duration_s,
                "offered_requests": len(schedule),
                "model": cfg.model_name,
                "replicas_initial": cfg.initial_replicas,
                "replicas_max": cfg.max_replicas,
                "tenants": [t.name for t in mix.tenants],
            },
            gateway_metrics=gw_metrics,
            baseline_metrics=baseline,
            traces=traces,
            fleet_events=list(fleet.events),
            run_t0=t0,
            chaos_window=(
                cfg.chaos.window if cfg.chaos is not None else None
            ),
            chaos_faults=fired,
            extra=extra,
        )
    finally:
        await asc.stop()
        await source.close()
        await fleet.close()
        await gw.stop_async()
