"""Manifest overlay/layering — the kustomize config plane.

Reference analog: the [manifests] repo (SURVEY.md §1 L8, §2.5 "Manifests"
row — UNVERIFIED, mount empty, §0): every Kubeflow deployment is
``kustomize build`` over bases + overlays (namePrefix, commonLabels,
patchesStrategicMerge, configMapGenerator). This module implements that
layering for OUR manifest dialect, so one base job/service definition
ships with per-environment overlays exactly like the reference's
``overlays/{dev,prod}`` trees.

Supported kustomization fields (the load-bearing core of kustomize):

- ``resources``: manifest files, directories of manifests, or nested
  kustomization directories (recursive bases — an overlay's resource can
  itself be an overlay).
- ``namePrefix`` / ``nameSuffix`` / ``namespace``
- ``commonLabels`` / ``commonAnnotations``
- ``patchesStrategicMerge``: inline dicts or files; deep-merges objects,
  merges lists of named objects by ``name`` (the strategic-merge
  patchMergeKey), replaces other lists; an explicit ``null`` deletes the
  key (JSON-merge-patch convention).
- ``patches`` with ``target`` selectors (kind/name match) — one patch
  aimed at a subset of resources.
- ``configMapGenerator``: literals → ConfigMap manifests.

``build()`` returns fully-resolved manifest dicts; ``parse()`` routes a
built manifest to its typed spec (JobSpec / InferenceServiceSpec /
ExperimentSpec) so ``build → parse → submit`` is the `kubectl apply -k`
path of this framework.
"""

from __future__ import annotations

import copy
import os
from typing import Any, Mapping

import yaml

KUSTOMIZATION_NAMES = ("kustomization.yaml", "kustomization.yml")

#: strategic-merge list key (kustomize's default patchMergeKey)
MERGE_KEY = "name"


# --------------------------------------------------------------------------- #
# strategic merge
# --------------------------------------------------------------------------- #


def strategic_merge(base: Any, patch: Any) -> Any:
    """kustomize-style strategic merge of ``patch`` onto ``base``."""
    if isinstance(patch, Mapping) and isinstance(base, Mapping):
        out = dict(base)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)  # null deletes (JSON merge patch)
            elif k in out:
                out[k] = strategic_merge(out[k], v)
            else:
                out[k] = copy.deepcopy(v)
        return out
    if isinstance(patch, list) and isinstance(base, list):
        # lists of named objects merge by MERGE_KEY; everything else replaces
        if all(isinstance(x, Mapping) and MERGE_KEY in x for x in base + patch):
            merged = {x[MERGE_KEY]: copy.deepcopy(x) for x in base}
            for p in patch:
                key = p[MERGE_KEY]
                if key in merged:
                    merged[key] = strategic_merge(merged[key], p)
                else:
                    merged[key] = copy.deepcopy(p)
            return list(merged.values())
        return copy.deepcopy(patch)
    return copy.deepcopy(patch)


def _matches(target: Mapping[str, Any], manifest: Mapping[str, Any]) -> bool:
    meta = manifest.get("metadata", {})
    for field, actual in (
        ("kind", manifest.get("kind")),
        ("name", meta.get("name")),
        ("namespace", meta.get("namespace")),
    ):
        want = target.get(field)
        if want is not None and want != actual:
            return False
    return True


# --------------------------------------------------------------------------- #
# kustomization loading
# --------------------------------------------------------------------------- #


def _load_yaml_docs(path: str) -> list[dict]:
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _is_kustomization_dir(path: str) -> bool:
    return os.path.isdir(path) and any(
        os.path.isfile(os.path.join(path, n)) for n in KUSTOMIZATION_NAMES
    )


def _load_resources(entry: str, base_dir: str) -> list[dict]:
    path = entry if os.path.isabs(entry) else os.path.join(base_dir, entry)
    if _is_kustomization_dir(path):
        return build(path)  # recursive base/overlay
    if os.path.isdir(path):
        out: list[dict] = []
        for name in sorted(os.listdir(path)):
            if name.endswith((".yaml", ".yml")) and name not in KUSTOMIZATION_NAMES:
                out.extend(_load_yaml_docs(os.path.join(path, name)))
        return out
    if os.path.isfile(path):
        return _load_yaml_docs(path)
    raise FileNotFoundError(f"resource {entry!r} not found under {base_dir!r}")


def build(source: str | Mapping[str, Any], base_dir: str | None = None) -> list[dict]:
    """``kustomize build``: resolve a kustomization (directory path,
    kustomization file path, or inline dict) into final manifests."""
    if isinstance(source, str):
        if _is_kustomization_dir(source):
            base_dir = source
            for n in KUSTOMIZATION_NAMES:
                p = os.path.join(source, n)
                if os.path.isfile(p):
                    kust = yaml.safe_load(open(p).read()) or {}
                    break
        elif os.path.isfile(source):
            base_dir = os.path.dirname(os.path.abspath(source))
            kust = yaml.safe_load(open(source).read()) or {}
        else:
            raise FileNotFoundError(source)
    else:
        kust = dict(source)
        base_dir = base_dir or os.getcwd()

    manifests: list[dict] = []
    for entry in kust.get("resources", []):
        if isinstance(entry, Mapping):  # inline resource
            manifests.append(copy.deepcopy(dict(entry)))
        else:
            manifests.extend(_load_resources(entry, base_dir))

    # configMapGenerator: literals → ConfigMap manifests
    for gen in kust.get("configMapGenerator", []):
        data = dict(gen.get("literals_map") or {})
        for lit in gen.get("literals", []):
            k, _, v = str(lit).partition("=")
            data[k] = v
        manifests.append(
            {
                "kind": "ConfigMap",
                "metadata": {"name": gen["name"]},
                "data": data,
            }
        )

    # patchesStrategicMerge: match by kind+name, merge
    for patch in kust.get("patchesStrategicMerge", []):
        if isinstance(patch, str):
            pdocs = _load_yaml_docs(
                patch if os.path.isabs(patch) else os.path.join(base_dir, patch)
            )
        else:
            pdocs = [patch]
        for pd in pdocs:
            target = {
                "kind": pd.get("kind"),
                "name": pd.get("metadata", {}).get("name"),
            }
            hit = False
            for i, m in enumerate(manifests):
                if _matches(target, m):
                    manifests[i] = strategic_merge(m, pd)
                    hit = True
            if not hit:
                raise ValueError(
                    f"patchesStrategicMerge target not found: {target}"
                )

    # targeted patches
    for p in kust.get("patches", []):
        patch = p.get("patch")
        if isinstance(patch, str):
            patch = yaml.safe_load(patch)
        target = p.get("target", {})
        hit = False
        for i, m in enumerate(manifests):
            if _matches(target, m):
                manifests[i] = strategic_merge(m, patch)
                hit = True
        if not hit:
            raise ValueError(f"patch target not found: {target}")

    # name/namespace/label/annotation transformers
    prefix = kust.get("namePrefix", "")
    suffix = kust.get("nameSuffix", "")
    namespace = kust.get("namespace")
    labels = kust.get("commonLabels", {})
    annotations = kust.get("commonAnnotations", {})
    for m in manifests:
        meta = m.setdefault("metadata", {})
        if prefix or suffix:
            meta["name"] = f"{prefix}{meta.get('name', '')}{suffix}"
        if namespace:
            meta["namespace"] = namespace
        if labels:
            meta["labels"] = {**meta.get("labels", {}), **labels}
        if annotations:
            meta["annotations"] = {
                **meta.get("annotations", {}), **annotations
            }
    return manifests


# --------------------------------------------------------------------------- #
# typed dispatch (the `kubectl apply -k` path)
# --------------------------------------------------------------------------- #

#: kinds → parser returning a typed spec this framework can submit
class UnsupportedKind(ValueError):
    """The manifest's ``kind`` has no parser here. Distinct from a
    malformed manifest OF a supported kind — CLI callers skip the former
    (kubectl semantics) but must SURFACE the latter, or an operator's
    typo'd graph/service silently vanishes from the deployment."""


def parse(manifest: Mapping[str, Any]) -> Any:
    kind = manifest.get("kind", "")
    if kind in ("JAXJob", "PyTorchJob", "TFJob", "MPIJob", "XGBoostJob",
                "PaddleJob"):
        from kubeflow_tpu.orchestrator.kinds import from_manifest

        return from_manifest(manifest)
    if kind == "InferenceService":
        from kubeflow_tpu.serve.spec import InferenceServiceSpec

        return InferenceServiceSpec.from_manifest(manifest)
    if kind == "InferenceGraph":
        from kubeflow_tpu.serve.graph import GraphSpec

        return GraphSpec.from_manifest(manifest)
    if kind == "Experiment":
        from kubeflow_tpu.tune.spec import ExperimentSpec

        return ExperimentSpec.from_dict(
            {"name": manifest.get("metadata", {}).get("name"),
             **manifest.get("spec", {})}
        )
    if kind in ("ClusterQueue", "LocalQueue"):
        from kubeflow_tpu.sched import queues as sched_queues

        return sched_queues.from_manifest(manifest)
    if kind == "PersistentVolumeClaim":
        from kubeflow_tpu.platform.volumes import VolumeSpec

        return VolumeSpec.from_manifest(manifest)
    if kind == "ConfigMap":
        return dict(manifest)
    raise UnsupportedKind(f"no parser for manifest kind {kind!r}")


def main(argv: list[str] | None = None) -> int:
    """``python -m kubeflow_tpu.platform.manifests <dir>`` — the
    ``kustomize build`` CLI: print resolved manifests as a YAML stream."""
    import argparse
    import sys

    p = argparse.ArgumentParser(description="kustomize-build analog")
    p.add_argument("path", help="kustomization directory or file")
    args = p.parse_args(argv)
    yaml.safe_dump_all(build(args.path), sys.stdout, sort_keys=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
