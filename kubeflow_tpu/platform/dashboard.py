"""Central dashboard: aggregated UI + CRUD API over every plane.

The reference's central dashboard is a web shell aggregating the component
UIs, and its CRUD web apps (jupyter / tensorboards) are the writable
frontends (SURVEY.md §2.5). Here both collapse into one server: a JSON API
plus a self-contained HTML single-page UI (``GET /``) that renders and
drives it — no build toolchain, works in any browser or through ``curl``.

Read API:
- ``GET /api/summary``      → counts per plane + fleet snapshot
- ``GET /api/jobs``         → job list (phase, kind, replicas, restarts)
- ``GET /api/jobs/{uid}/logs?replica=&index=`` → worker logs
- ``GET /api/queues``       → quota queues (nominal/used/borrowed, waits)
- ``GET /api/profiles``     → profiles with live quota usage
- ``GET /api/notebooks``    → notebook phases + idle times
- ``GET /api/tensorboards`` → board phases + urls
- ``GET /api/models``       → registered models with stage holders
- ``GET /api/models/{name}/versions`` → versions + lineage edges
- ``GET /api/autoscaler``   → serving-autoscaler state (KPA policy,
  desired vs current, panic mode, folded signals)
- ``GET /metrics``          → shared prom registry (autoscaler gauges,
  activator depths, gateway edge counters) in Prometheus text format

CRUD (the web-app analog):
- ``POST /api/jobs``              body = CRD manifest (any known kind)
- ``DELETE /api/jobs/{uid}``
- ``POST /api/notebooks``         {name, command?, culling_idle_seconds?}
- ``DELETE /api/notebooks/{name}``
- ``POST /api/tensorboards``      {name, logdir}
- ``DELETE /api/tensorboards/{name}``
"""

from __future__ import annotations

import json
import time

from kubeflow_tpu.obs.webhost import ThreadedAiohttpServer
from kubeflow_tpu.orchestrator.cluster import LocalCluster
from kubeflow_tpu.platform.notebooks import NotebookController
from kubeflow_tpu.platform.profiles import ProfileController, job_chips
from kubeflow_tpu.platform.tensorboards import TensorboardController


async def _json(data):
    from aiohttp import web

    return web.json_response(
        data, dumps=lambda d: json.dumps(d, default=str)
    )


class DashboardServer(ThreadedAiohttpServer):
    thread_name = "kft-dashboard"

    def __init__(
        self,
        cluster: LocalCluster,
        *,
        profiles: ProfileController | None = None,
        notebooks: NotebookController | None = None,
        tensorboards: TensorboardController | None = None,
        tune_db=None,       # tune.db.TrialDB → /api/experiments (Katib UI)
        lineage=None,       # pipelines.metadata.LineageStore → /api/pipelines
        pipeline_api=None,  # pipelines.api.PipelineAPIServer → DAG view
        volumes=None,       # platform.volumes.VolumeController → /api/volumes
        registry=None,      # registry.store.ModelStore → /api/models
        gateway=None,       # gateway.server.InferenceGateway → /api/gateway
        autoscaler=None,    # autoscale.ServingAutoscaler → /api/autoscaler
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        super().__init__(host=host, port=port)
        self.cluster = cluster
        self.profiles = profiles
        self.notebooks = notebooks
        self.tensorboards = tensorboards
        self.tune_db = tune_db
        self.lineage = lineage
        self.pipeline_api = pipeline_api
        self.volumes = volumes
        self.registry = registry
        self.gateway = gateway
        self.autoscaler = autoscaler

    # -- views ---------------------------------------------------------- #

    def jobs_view(self) -> list[dict]:
        out = []
        for uid, job in self.cluster.jobs.list():
            out.append(
                {
                    "uid": uid,
                    "name": job.spec.name,
                    "namespace": job.spec.namespace,
                    "kind": job.spec.kind,
                    "phase": job.status.phase,
                    "replicas": {
                        rt: r.replicas for rt, r in job.spec.replicas.items()
                    },
                    "chips": job_chips(job.spec),
                    "restarts": job.status.restart_count,
                }
            )
        return out

    def profiles_view(self) -> list[dict]:
        if self.profiles is None:
            return []
        out = []
        for p in self.profiles.list():
            usage = self.profiles.usage(p.name)
            out.append(
                {
                    "name": p.name,
                    "owner": p.owner,
                    "quota": {
                        "max_chips": p.quota.max_chips,
                        "max_jobs": p.quota.max_jobs,
                    },
                    "usage": usage,
                }
            )
        return out

    def notebooks_view(self) -> list[dict]:
        if self.notebooks is None:
            return []
        return [
            {
                "name": spec.name,
                "namespace": spec.namespace,
                "phase": status.phase,
                "idle_seconds": round(time.time() - status.last_activity, 1),
            }
            for spec, status in self.notebooks.statuses()
        ]

    def tensorboards_view(self) -> list[dict]:
        if self.tensorboards is None:
            return []
        return [
            {
                "name": spec.name,
                "namespace": spec.namespace,
                "phase": status.phase,
                "url": status.url,
                "logdir": spec.logdir,
            }
            for spec, status in self.tensorboards.statuses()
        ]

    def volumes_view(self) -> list[dict]:
        if self.volumes is None:
            return []
        return [
            {
                "name": spec.name,
                "namespace": spec.namespace,
                "phase": status.phase,
                "size_mb": spec.size_mb,
                "used_mb": used,
                "bound_to": sorted(status.bound_to),
            }
            for spec, status, used in self.volumes.statuses()
        ]

    def experiments_view(self) -> list[dict]:
        return [] if self.tune_db is None else self.tune_db.experiments()

    def experiment_trials_view(self, name: str) -> list[dict]:
        if self.tune_db is None:
            return []
        return [
            {
                "trial_id": t.assignment.trial_id,
                "parameters": t.assignment.parameters,
                "state": t.state.value,
                "metrics": t.metrics,
                "message": t.message,
            }
            for t in self.tune_db.load_trials(name)
        ]

    def queues_view(self) -> list[dict]:
        """Quota queues (the Kueue UI analog): per-ClusterQueue nominal vs
        used vs borrowed chips, pending depth, and admission-wait
        percentiles. Empty when the cluster runs without quota scheduling."""
        view = getattr(self.cluster.scheduler, "queues_view", None)
        return [] if view is None else view()

    def models_view(self) -> list[dict]:
        """Registered models with stage holders (the model-registry UI
        analog): name, latest, and which version sits in each stage."""
        if self.registry is None:
            return []
        return [
            {
                "name": m.name,
                "description": m.description,
                "latest": m.latest_version,
                "production": m.stages.get("production"),
                "staging": m.stages.get("staging"),
                "updated": m.updated,
            }
            for m in self.registry.list_models()
        ]

    def model_versions_view(self, name: str) -> list[dict]:
        if self.registry is None:
            return []
        return [
            {
                **v.to_dict(),
                "lineage": [
                    e.to_dict()
                    for e in self.registry.lineage_of(name, v.version)
                ],
            }
            for v in self.registry.list_versions(name)
        ]

    def gateway_view(self) -> dict:
        """Edge topology (the Istio/Knative console analog): per-service
        routes with canary split + affinity mode, live backend fitness
        (probe/breaker/outstanding), activator queue depths, tenant
        policy. Empty when no gateway is attached."""
        return {} if self.gateway is None else self.gateway.state_view()

    def autoscaler_view(self) -> dict:
        """Serving autoscaler state (autoscale/): per-service KPA policy,
        live desired vs current, panic mode, last folded signals. Empty
        when no autoscaler is attached."""
        return {} if self.autoscaler is None else self.autoscaler.view()

    def traces_view(self) -> dict:
        """Tail-sampled traces from THIS process's tracer (obs/trace.py).
        A dashboard colocated with the gateway/serving plane shows the
        full edge→engine span trees; a standalone dashboard shows only
        its own spans — cross-process aggregation stays on the operator
        (``kft trace dump`` against each replica)."""
        from kubeflow_tpu.obs.trace import TRACER

        return TRACER.snapshot()

    def pipelines_view(self) -> list[dict]:
        return [] if self.lineage is None else self.lineage.runs()

    def pipeline_tasks_view(self, run_id: str) -> list[dict]:
        return [] if self.lineage is None else self.lineage.executions(run_id)

    def pipeline_dag_view(self, run_id: str) -> dict:
        """DAG structure + live task states via the pipelines API server
        (which captured the spec at submit); {} when not wired or the run
        predates the API."""
        if self.pipeline_api is None:
            return {}
        try:
            return self.pipeline_api.run_dag(run_id)
        except KeyError:
            return {}

    def summary_view(self) -> dict:
        jobs = self.jobs_view()
        phases: dict[str, int] = {}
        for j in jobs:
            phases[j["phase"]] = phases.get(j["phase"], 0) + 1
        return {
            "jobs": {"total": len(jobs), "by_phase": phases},
            "profiles": len(self.profiles_view()),
            "notebooks": len(self.notebooks_view()),
            # count() not volumes_view(): the view walks every volume's
            # tree for usage; the summary poll only needs the integer
            "volumes": 0 if self.volumes is None else self.volumes.count(),
            "tensorboards": len(self.tensorboards_view()),
            "experiments": len(self.experiments_view()),
            "pipeline_runs": len(self.pipelines_view()),
            "models": len(self.models_view()),
            "fleet": {
                "slices": len(self.cluster.fleet.snapshot()),
                "total_chips": self.cluster.fleet.total_chips(),
                "free_chips": self.cluster.fleet.free_chips(),
            },
        }

    # -- server --------------------------------------------------------- #

    def _make_app(self):
        from aiohttp import web

        def handler(fn):
            async def h(request):
                return web.Response(
                    text=json.dumps(fn(), default=str),
                    content_type="application/json",
                )

            return h

        def guard(coro):
            async def h(request):
                try:
                    return await coro(request)
                except KeyError as e:
                    raise web.HTTPNotFound(reason=str(e))
                except (ValueError, TypeError) as e:
                    raise web.HTTPBadRequest(reason=str(e))

            return h

        # ---- CRUD: jobs ------------------------------------------------ #

        async def create_job(request):
            from kubeflow_tpu.orchestrator.spec import JobSpec
            from kubeflow_tpu.platform.manifests import parse

            manifest = await request.json()
            spec = parse(manifest)
            if not isinstance(spec, JobSpec):
                # parse() knows more kinds than are submittable here (PVC,
                # InferenceService…): a clean 400, not an AttributeError
                # 500 from cluster.submit
                raise ValueError(
                    f"manifest kind {manifest.get('kind')!r} is not a "
                    "runnable job"
                )
            uid = self.cluster.submit(spec)
            return web.json_response({"uid": uid, "name": spec.name})

        async def delete_job(request):
            uid = request.match_info["uid"]
            if self.cluster.get(uid) is None:
                raise KeyError(uid)
            self.cluster.delete(uid)
            return web.json_response({"deleted": uid})

        async def job_logs(request):
            uid = request.match_info["uid"]
            replica = request.query.get("replica", "worker")
            index = int(request.query.get("index", 0))
            return web.Response(text=self.cluster.logs(uid, replica, index))

        # ---- CRUD: notebooks (jupyter web-app analog) ------------------ #

        import re

        def valid_name(name) -> str:
            # names become job names and workdir path components; reject
            # anything that could escape a directory or break a shell/html
            # context before it enters the system (DNS-1123-ish)
            if not isinstance(name, str) or not re.fullmatch(
                r"[a-z0-9]([a-z0-9._-]{0,62}[a-z0-9])?", name
            ):
                raise ValueError(
                    f"invalid name {name!r}: want lowercase alphanumerics "
                    "with inner '.', '_' or '-', max 64 chars"
                )
            return name

        async def create_notebook(request):
            import sys

            from kubeflow_tpu.platform.notebooks import NotebookSpec

            if self.notebooks is None:
                raise ValueError("notebook controller not attached")
            body = await request.json()
            spec = NotebookSpec(
                name=valid_name(body["name"]),
                command=tuple(
                    body.get("command")
                    or (sys.executable, "-c", "import time; time.sleep(3600)")
                ),
                namespace=body.get("namespace", "default"),
                culling_idle_seconds=body.get("culling_idle_seconds"),
            )
            st = self.notebooks.create(spec)
            return web.json_response({"name": spec.name, "phase": st.phase})

        async def delete_notebook(request):
            if self.notebooks is None:
                raise ValueError("notebook controller not attached")
            self.notebooks.delete(request.match_info["name"])
            return web.json_response({"deleted": request.match_info["name"]})

        # ---- CRUD: volumes (PVC web app analog) ------------------------ #

        async def create_volume(request):
            from kubeflow_tpu.platform.volumes import VolumeSpec

            if self.volumes is None:
                raise ValueError("volume controller not attached")
            body = await request.json()
            spec = VolumeSpec(
                name=valid_name(body["name"]),
                namespace=body.get("namespace", "default"),
                size_mb=int(body.get("size_mb", 1024)),
            )
            # spec.validate() (inside create) DNS-1123-checks the
            # namespace too — it is a path component
            path = self.volumes.create(spec)
            return web.json_response({"name": spec.name, "path": path})

        async def delete_volume(request):
            if self.volumes is None:
                raise ValueError("volume controller not attached")
            ns = request.query.get("namespace", "default")
            self.volumes.delete(request.match_info["name"], ns)
            return web.json_response({"deleted": request.match_info["name"]})

        # ---- CRUD: tensorboards ---------------------------------------- #

        async def create_tensorboard(request):
            from kubeflow_tpu.platform.tensorboards import TensorboardSpec

            if self.tensorboards is None:
                raise ValueError("tensorboard controller not attached")
            body = await request.json()
            st = self.tensorboards.create(
                TensorboardSpec(
                    name=valid_name(body["name"]), logdir=body["logdir"]
                )
            )
            return web.json_response({"name": body["name"], "url": st.url})

        async def delete_tensorboard(request):
            if self.tensorboards is None:
                raise ValueError("tensorboard controller not attached")
            self.tensorboards.delete(request.match_info["name"])
            return web.json_response({"deleted": request.match_info["name"]})

        async def index(request):
            return web.Response(text=_INDEX_HTML, content_type="text/html")

        @web.middleware
        async def csrf_guard(request, handler):
            # State-changing endpoints submit jobs that EXECUTE COMMANDS, so
            # a hostile web page must not be able to drive them cross-site:
            # (a) require a JSON content type — text/plain form posts and
            # other no-preflight vehicles are rejected; (b) pin the Host
            # header to the bound address — blocks DNS-rebinding around the
            # loopback bind. Same-origin fetch() from the SPA passes both.
            if request.method in ("POST", "PUT", "DELETE"):
                ctype = request.headers.get("content-type", "")
                if request.method != "DELETE" and not ctype.startswith(
                    "application/json"
                ):
                    raise web.HTTPUnsupportedMediaType(
                        reason="state-changing requests must be application/json"
                    )
                raw_host = request.headers.get("host", "")
                if raw_host.startswith("["):  # IPv6 literal: [::1]:8080
                    host = raw_host.split("]")[0] + "]"
                else:
                    host = raw_host.rsplit(":", 1)[0]
                allowed = {self.host, "localhost", "127.0.0.1", "[::1]"}
                # a wildcard bind can't pin one hostname; the operator opted
                # out of the loopback posture, so skip the pin (the JSON
                # content-type requirement still blocks no-preflight CSRF)
                if self.host not in ("0.0.0.0", "::") and host not in allowed:
                    raise web.HTTPForbidden(reason=f"bad host {host!r}")
            return await handler(request)

        async def metrics(request):
            from kubeflow_tpu.obs import prom

            # the shared registry: autoscaler recommendation gauges,
            # activator depths, gateway edge counters — one scrape point
            # for operators fronting the whole control plane
            return web.Response(text=prom.REGISTRY.expose())

        app = web.Application(middlewares=[csrf_guard])
        app.router.add_get("/", index)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/api/summary", handler(self.summary_view))
        app.router.add_get("/api/jobs", handler(self.jobs_view))
        app.router.add_get("/api/queues", handler(self.queues_view))
        app.router.add_get("/api/gateway", handler(self.gateway_view))
        app.router.add_get("/api/autoscaler", handler(self.autoscaler_view))
        app.router.add_get("/api/traces", handler(self.traces_view))
        app.router.add_get("/api/profiles", handler(self.profiles_view))
        app.router.add_get("/api/notebooks", handler(self.notebooks_view))
        app.router.add_get("/api/tensorboards", handler(self.tensorboards_view))
        app.router.add_get("/api/experiments", handler(self.experiments_view))
        app.router.add_get(
            "/api/experiments/{name}/trials",
            guard(
                lambda r: _json(
                    self.experiment_trials_view(r.match_info["name"])
                )
            ),
        )
        app.router.add_get("/api/models", handler(self.models_view))
        app.router.add_get(
            "/api/models/{name:.+}/versions",
            guard(
                lambda r: _json(
                    self.model_versions_view(r.match_info["name"])
                )
            ),
        )
        app.router.add_get("/api/pipelines", handler(self.pipelines_view))
        app.router.add_get(
            "/api/pipelines/{run_id}/tasks",
            guard(
                lambda r: _json(
                    self.pipeline_tasks_view(r.match_info["run_id"])
                )
            ),
        )
        app.router.add_get(
            "/api/pipelines/{run_id}/dag",
            guard(
                lambda r: _json(
                    self.pipeline_dag_view(r.match_info["run_id"])
                )
            ),
        )
        app.router.add_post("/api/jobs", guard(create_job))
        app.router.add_delete("/api/jobs/{uid}", guard(delete_job))
        app.router.add_get("/api/jobs/{uid}/logs", guard(job_logs))
        app.router.add_post("/api/notebooks", guard(create_notebook))
        app.router.add_delete("/api/notebooks/{name}", guard(delete_notebook))
        app.router.add_get("/api/volumes", handler(self.volumes_view))
        app.router.add_post("/api/volumes", guard(create_volume))
        app.router.add_delete("/api/volumes/{name}", guard(delete_volume))
        app.router.add_post("/api/tensorboards", guard(create_tensorboard))
        app.router.add_delete(
            "/api/tensorboards/{name}", guard(delete_tensorboard)
        )
        return app


#: Self-contained SPA: fetches the JSON APIs, renders tables, drives CRUD.
#: Vanilla HTML+JS on purpose — the reference's Angular/TS frontends need a
#: build pipeline; a control-plane UI needs none (SURVEY.md §2.5).
_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>kubeflow-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1f2328}
 header{background:#1a2b4c;color:#fff;padding:10px 18px;display:flex;gap:18px;align-items:baseline}
 header h1{font-size:16px;margin:0}
 nav button{background:none;border:none;color:#cdd6e4;font-size:14px;cursor:pointer;padding:4px 8px}
 nav button.on{color:#fff;border-bottom:2px solid #6cf}
 main{padding:16px 18px;max-width:1100px}
 table{border-collapse:collapse;width:100%;background:#fff;font-size:13px}
 th,td{text-align:left;padding:6px 10px;border-bottom:1px solid #e4e7ec}
 th{background:#eef1f5;font-weight:600}
 .pill{padding:1px 8px;border-radius:10px;font-size:12px;background:#e4e7ec}
 .Succeeded{background:#d7f5dd}.Running{background:#d7e9f9}
 .Failed,.FailedToLoad{background:#fadcd9}.Pending{background:#faf0d2}
 .cards{display:flex;gap:12px;margin-bottom:16px;flex-wrap:wrap}
 .card{background:#fff;border:1px solid #e4e7ec;border-radius:8px;padding:10px 16px;min-width:110px}
 .card b{font-size:22px;display:block}
 .bar{margin:10px 0}
 input,select{padding:4px 6px;margin-right:6px}
 button.act{cursor:pointer;padding:3px 10px}
 pre{background:#101418;color:#d6e2f0;padding:10px;overflow:auto;max-height:320px}
</style></head><body>
<header><h1>kubeflow-tpu</h1><nav id="nav"></nav></header>
<main id="main"></main>
<script>
const tabs=["summary","jobs","queues","gateway","traces","experiments","pipelines","models","notebooks","volumes","tensorboards","profiles"];
let tab="summary";
const $=(h)=>{const d=document.createElement("div");d.innerHTML=h;return d};
const esc=(s)=>String(s).replace(/[&<>"]/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
async function j(url,opt){const r=await fetch(url,opt);if(!r.ok)throw new Error(await r.text());
 const ct=r.headers.get("content-type")||"";return ct.includes("json")?r.json():r.text()}
function nav(){document.getElementById("nav").innerHTML=tabs.map(t=>
 `<button class="${t===tab?'on':''}" onclick="go('${t}')">${t}</button>`).join("")}
function go(t){tab=t;render()}
function pill(p){return raw(`<span class="pill ${esc(p)}">${esc(p)}</span>`)}
// escape by DEFAULT: server data (job/notebook names…) is untrusted in the
// browser; only values wrapped in raw() render as HTML
function raw(h){return {__html:h}}
function cell(v){return v&&v.__html!==undefined?v.__html:esc(v??"")}
// strict percent-encoding: encodeURIComponent leaves !'()* alone, and a
// bare ' would break out of single-quoted onclick JS (stored XSS)
function uenc(s){return esc(encodeURIComponent(s).replace(/[!'()*]/g,
 c=>"%"+c.charCodeAt(0).toString(16)))}
function table(rows,cols,actions){if(!rows.length)return "<p>none</p>";
 return `<table><tr>${cols.map(c=>`<th>${esc(c)}</th>`).join("")}${actions?"<th></th>":""}</tr>`+
 rows.map(r=>`<tr>${cols.map(c=>`<td>${cell(r[c])}</td>`).join("")}${actions?`<td>${actions(r)}</td>`:""}</tr>`).join("")+"</table>"}
async function render(){nav();const m=document.getElementById("main");m.textContent="loading…";
 try{
 if(tab==="summary"){const s=await j("/api/summary");
  m.innerHTML=`<div class="cards">
   <div class="card"><b>${s.jobs.total}</b>jobs</div>
   <div class="card"><b>${s.fleet.free_chips}/${s.fleet.total_chips}</b>free chips</div>
   <div class="card"><b>${s.fleet.slices}</b>slices</div>
   <div class="card"><b>${s.notebooks}</b>notebooks</div>
   <div class="card"><b>${s.tensorboards}</b>tensorboards</div></div>
   <h3>jobs by phase</h3>`+table(Object.entries(s.jobs.by_phase).map(([k,v])=>({phase:pill(k),count:v})),["phase","count"])}
 if(tab==="jobs"){const rows=(await j("/api/jobs")).map(r=>({...r,phase:pill(r.phase),
   replicas:JSON.stringify(r.replicas)}));
  m.innerHTML=`<div class="bar"><i>POST /api/jobs with a CRD manifest to submit</i></div>`+
   table(rows,["name","kind","phase","chips","restarts","uid"],
    r=>`<button class="act" onclick="logs('${uenc(r.uid)}')">logs</button>
        <button class="act" onclick="del('/api/jobs/${uenc(r.uid)}')">delete</button>`)+`<pre id="logs" hidden></pre>`}
 if(tab==="queues"){const chips=(d)=>Object.entries(d||{}).map(([g,c])=>`${g}:${c}`).join(" ")||"—";
  const rows=(await j("/api/queues")).map(r=>({name:r.name,cohort:r.cohort||"—",
   nominal:chips(r.nominal),used:chips(r.usage),borrowed:chips(r.borrowed),
   limit:r.borrowing_limit??"∞",pending:r.pending,admitted:r.admitted,
   "wait p50/p95":r.wait_p50_s==null?"—":`${r.wait_p50_s.toFixed(2)}s / ${r.wait_p95_s.toFixed(2)}s`,
   localqueues:(r.local_queues||[]).join(", ")||"—"}));
  m.innerHTML=`<div class="bar"><i>ClusterQueues: nominal quota, live usage, cohort borrowing, admission wait</i></div>`+
   table(rows,["name","cohort","nominal","used","borrowed","limit","pending","admitted","wait p50/p95","localqueues"])}
 if(tab==="gateway"){const g=await j("/api/gateway");
  if(!g.services||!g.services.length){m.innerHTML="<p>no gateway attached</p>"}else{
  const svc=g.services.map(s=>({name:s.name,canary:`${s.canary_percent}%`,affinity:s.affinity,
   ready:s.ready_backends,queued:s.queue_depth,hosts:(s.hosts||[]).join(", ")||"—"}));
  const bes=g.services.flatMap(s=>(s.backends||[]).map(b=>({service:s.name,url:b.url,
   revision:b.revision,state:pill(b.state),probe:b.probe_ok?"ok":"ejected",
   breaker:pill(b.breaker),outstanding:b.outstanding})));
  m.innerHTML=`<div class="bar"><i>edge routes, backend fitness, activator queues</i></div>`+
   `<h3>services</h3>`+table(svc,["name","canary","affinity","ready","queued","hosts"])+
   `<h3>backends</h3>`+table(bes,["service","url","revision","state","probe","breaker","outstanding"])}}
 if(tab==="traces"){const t=await j("/api/traces");window._traces=t.traces||[];
  const rows=window._traces.map(tr=>{const root=(tr.spans||[]).find(s=>!s.parent_span_id)||tr.spans[0]||{};
   return {trace_id:raw(`<a href="#" onclick="spans('${uenc(tr.trace_id)}');return false"><code>${esc(tr.trace_id.slice(0,16))}…</code></a>`),
    root:root.name||"—",kept:pill(tr.kept||"—"),spans:(tr.spans||[]).length,
    ms:tr.duration_ms==null?"—":tr.duration_ms.toFixed(1)}});
  m.innerHTML=`<div class="cards"><div class="card"><b>${t.finished??0}</b>finished</div>
   <div class="card"><b>${t.live??0}</b>live</div>
   <div class="card"><b>${t.p99_ms==null?"—":t.p99_ms.toFixed(1)}</b>p99 ms</div></div>
   <div class="bar"><i>tail-sampled: errors/sheds kept 100%, plus ≥p99-slow and 1-in-16 samples</i></div>`+
   table(rows,["trace_id","root","kept","spans","ms"])+`<pre id="detail" hidden></pre>`}
 if(tab==="experiments"){const rows=(await j("/api/experiments")).map(r=>({...r,
   name:raw(`<a href="#" onclick="trials('${uenc(r.name)}');return false">${esc(r.name)}</a>`)}));
  m.innerHTML=table(rows,["name","trials","succeeded","failed","running"])+`<pre id="detail" hidden></pre>`}
 if(tab==="pipelines"){const rows=(await j("/api/pipelines")).map(r=>({...r,state:pill(r.state),
   run_id:raw(`<a href="#" onclick="tasks('${uenc(r.run_id)}');return false">${esc(r.run_id)}</a>`)}));
  m.innerHTML=table(rows,["run_id","state","tasks","succeeded","failed","cache_hits"])+
   `<div id="dag" hidden style="background:#fff;border:1px solid #e4e7ec;margin-top:10px;overflow:auto"></div><pre id="detail" hidden></pre>`}
 if(tab==="models"){const rows=(await j("/api/models")).map(r=>({...r,
   name:raw(`<a href="#" onclick="versions('${uenc(r.name)}');return false">${esc(r.name)}</a>`),
   production:r.production??"—",staging:r.staging??"—"}));
  m.innerHTML=table(rows,["name","latest","production","staging","description"])+`<pre id="detail" hidden></pre>`}
 if(tab==="notebooks"){const rows=(await j("/api/notebooks")).map(r=>({...r,phase:pill(r.phase)}));
  m.innerHTML=`<div class="bar"><input id="nb" placeholder="name">
    <button class="act" onclick="mknb()">create notebook</button></div>`+
   table(rows,["name","namespace","phase","idle_seconds"],
    r=>`<button class="act" onclick="del('/api/notebooks/${uenc(r.name)}')">delete</button>`)}
 if(tab==="volumes"){const rows=(await j("/api/volumes")).map(r=>({...r,phase:pill(r.phase),
   bound_to:r.bound_to.join(", ")}));
  m.innerHTML=`<div class="bar"><input id="vn" placeholder="name"><input id="vs" placeholder="size MB" size="7">
    <button class="act" onclick="mkvol()">create volume</button></div>`+
   table(rows,["name","namespace","phase","size_mb","used_mb","bound_to"],
    r=>`<button class="act" onclick="del('/api/volumes/${uenc(r.name)}')">delete</button>`)}
 if(tab==="tensorboards"){const rows=(await j("/api/tensorboards")).map(r=>({...r,phase:pill(r.phase),
   url:raw(`<a href="${esc(r.url)}">${esc(r.url)}</a>`)}));
  m.innerHTML=`<div class="bar"><input id="tbn" placeholder="name"><input id="tbl" placeholder="logdir">
    <button class="act" onclick="mktb()">create tensorboard</button></div>`+
   table(rows,["name","phase","url","logdir"],
    r=>`<button class="act" onclick="del('/api/tensorboards/${uenc(r.name)}')">delete</button>`)}
 if(tab==="profiles"){const rows=(await j("/api/profiles")).map(r=>({name:r.name,owner:r.owner,
   quota:JSON.stringify(r.quota),usage:JSON.stringify(r.usage)}));
  m.innerHTML=table(rows,["name","owner","quota","usage"])}
 }catch(e){m.innerHTML=`<pre>${esc(e.message||e)}</pre>`}}
async function del(url){await j(url,{method:"DELETE"});render()}
async function logs(uid){const p=document.getElementById("logs");p.hidden=false;
 p.textContent=await j(`/api/jobs/${uid}/logs`)}
async function trials(name){const p=document.getElementById("detail");p.hidden=false;
 p.textContent=JSON.stringify(await j(`/api/experiments/${name}/trials`),null,1)}
async function versions(name){const p=document.getElementById("detail");p.hidden=false;
 p.textContent=JSON.stringify(await j(`/api/models/${name}/versions`),null,1)}
function spans(tid){const p=document.getElementById("detail");p.hidden=false;
 const tr=(window._traces||[]).find(t=>encodeURIComponent(t.trace_id)===tid||t.trace_id===decodeURIComponent(tid));
 p.textContent=tr?JSON.stringify(tr,null,1):"trace gone"}
async function tasks(run){const p=document.getElementById("detail");p.hidden=false;
 const g=document.getElementById("dag");
 try{const dag=await j(`/api/pipelines/${run}/dag`);
  if(dag&&dag.tasks&&dag.tasks.length){g.hidden=false;g.innerHTML=drawDag(dag.tasks)}
  else g.hidden=true}catch(e){g.hidden=true}
 p.textContent=JSON.stringify(await j(`/api/pipelines/${run}/tasks`),null,1)}
// layered DAG render: depth = longest dependency path, one column per
// depth, SVG boxes colored by task state (the KFP run-graph analog)
function drawDag(ts){const byName={};ts.forEach(t=>byName[t.name]=t);
 const depth={};const d=(n)=>{if(depth[n]!==undefined)return depth[n];
  const t=byName[n];if(!t)return 0;
  depth[n]=t.deps.length?1+Math.max(...t.deps.map(d)):0;return depth[n]};
 ts.forEach(t=>d(t.name));
 const layers={};ts.forEach(t=>{(layers[depth[t.name]]=layers[depth[t.name]]||[]).push(t)});
 const W=150,H=44,GX=70,GY=16,pos={};
 Object.keys(layers).sort((a,b)=>a-b).forEach(ly=>layers[ly].forEach((t,i)=>
  pos[t.name]={x:ly*(W+GX)+12,y:i*(H+GY)+12}));
 const xs=Object.values(pos),maxX=Math.max(...xs.map(p=>p.x))+W+12,
  maxY=Math.max(...xs.map(p=>p.y))+H+12;
 const fill={SUCCEEDED:"#d7f5dd",RUNNING:"#d7e9f9",FAILED:"#fadcd9",
  SKIPPED:"#e4e7ec",PENDING:"#faf0d2"};
 let s=`<svg width="${maxX}" height="${maxY}" xmlns="http://www.w3.org/2000/svg">`+
  `<defs><marker id="arr" markerWidth="8" markerHeight="8" refX="7" refY="3" orient="auto">`+
  `<path d="M0 0 L7 3 L0 6 z" fill="#8a94a6"/></marker></defs>`;
 ts.forEach(t=>t.deps.forEach(dep=>{const a=pos[dep],b=pos[t.name];if(!a||!b)return;
  s+=`<path d="M${a.x+W} ${a.y+H/2} C ${a.x+W+GX/2} ${a.y+H/2}, ${b.x-GX/2} ${b.y+H/2}, ${b.x-2} ${b.y+H/2}" stroke="#8a94a6" fill="none" marker-end="url(#arr)"/>`}));
 ts.forEach(t=>{const p=pos[t.name];
  s+=`<rect x="${p.x}" y="${p.y}" width="${W}" height="${H}" rx="6" fill="${fill[t.state]||"#fff"}" stroke="#8a94a6"/>`+
  `<text x="${p.x+8}" y="${p.y+18}" font-size="12" font-weight="600">${esc(t.name)}${t.cache_hit?" ⚡":""}</text>`+
  `<text x="${p.x+8}" y="${p.y+34}" font-size="10" fill="#444">${esc(t.state)}</text>`});
 return s+"</svg>"}
async function mknb(){await j("/api/notebooks",{method:"POST",
 headers:{"content-type":"application/json"},
 body:JSON.stringify({name:document.getElementById("nb").value})});render()}
async function mkvol(){await j("/api/volumes",{method:"POST",
 headers:{"content-type":"application/json"},
 body:JSON.stringify({name:document.getElementById("vn").value,
  size_mb:parseInt(document.getElementById("vs").value||"1024")})});render()}
async function mktb(){await j("/api/tensorboards",{method:"POST",
 headers:{"content-type":"application/json"},
 body:JSON.stringify({name:document.getElementById("tbn").value,
  logdir:document.getElementById("tbl").value})});render()}
setInterval(()=>{if(!document.hidden)render()},5000);
render();
</script></body></html>
"""

