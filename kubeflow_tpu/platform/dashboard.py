"""Central dashboard: one aggregated status API over every plane.

The reference's central dashboard is a web shell aggregating the component
UIs (SURVEY.md §2.5). The TPU control plane's equivalent is the data half:
a JSON API (aiohttp on a daemon thread, the serving plane's stack) that
aggregates jobs, profiles/quotas, notebooks, and tensorboards so any
frontend — or ``curl`` — can see the whole platform at once.

- ``GET /api/summary``      → counts per plane + fleet snapshot
- ``GET /api/jobs``         → job list (phase, kind, replicas, restarts)
- ``GET /api/profiles``     → profiles with live quota usage
- ``GET /api/notebooks``    → notebook phases + idle times
- ``GET /api/tensorboards`` → board phases + urls
"""

from __future__ import annotations

import json
import time

from kubeflow_tpu.obs.webhost import ThreadedAiohttpServer
from kubeflow_tpu.orchestrator.cluster import LocalCluster
from kubeflow_tpu.platform.notebooks import NotebookController
from kubeflow_tpu.platform.profiles import ProfileController, job_chips
from kubeflow_tpu.platform.tensorboards import TensorboardController


class DashboardServer(ThreadedAiohttpServer):
    thread_name = "kft-dashboard"

    def __init__(
        self,
        cluster: LocalCluster,
        *,
        profiles: ProfileController | None = None,
        notebooks: NotebookController | None = None,
        tensorboards: TensorboardController | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        super().__init__(host=host, port=port)
        self.cluster = cluster
        self.profiles = profiles
        self.notebooks = notebooks
        self.tensorboards = tensorboards

    # -- views ---------------------------------------------------------- #

    def jobs_view(self) -> list[dict]:
        out = []
        for uid, job in self.cluster.jobs.list():
            out.append(
                {
                    "uid": uid,
                    "name": job.spec.name,
                    "namespace": job.spec.namespace,
                    "kind": job.spec.kind,
                    "phase": job.status.phase,
                    "replicas": {
                        rt: r.replicas for rt, r in job.spec.replicas.items()
                    },
                    "chips": job_chips(job.spec),
                    "restarts": job.status.restart_count,
                }
            )
        return out

    def profiles_view(self) -> list[dict]:
        if self.profiles is None:
            return []
        out = []
        for p in self.profiles.list():
            usage = self.profiles.usage(p.name)
            out.append(
                {
                    "name": p.name,
                    "owner": p.owner,
                    "quota": {
                        "max_chips": p.quota.max_chips,
                        "max_jobs": p.quota.max_jobs,
                    },
                    "usage": usage,
                }
            )
        return out

    def notebooks_view(self) -> list[dict]:
        if self.notebooks is None:
            return []
        return [
            {
                "name": spec.name,
                "namespace": spec.namespace,
                "phase": status.phase,
                "idle_seconds": round(time.time() - status.last_activity, 1),
            }
            for spec, status in self.notebooks.statuses()
        ]

    def tensorboards_view(self) -> list[dict]:
        if self.tensorboards is None:
            return []
        return [
            {
                "name": spec.name,
                "namespace": spec.namespace,
                "phase": status.phase,
                "url": status.url,
                "logdir": spec.logdir,
            }
            for spec, status in self.tensorboards.statuses()
        ]

    def summary_view(self) -> dict:
        jobs = self.jobs_view()
        phases: dict[str, int] = {}
        for j in jobs:
            phases[j["phase"]] = phases.get(j["phase"], 0) + 1
        return {
            "jobs": {"total": len(jobs), "by_phase": phases},
            "profiles": len(self.profiles_view()),
            "notebooks": len(self.notebooks_view()),
            "tensorboards": len(self.tensorboards_view()),
            "fleet": {
                "slices": len(self.cluster.fleet.snapshot()),
                "total_chips": self.cluster.fleet.total_chips(),
                "free_chips": self.cluster.fleet.free_chips(),
            },
        }

    # -- server --------------------------------------------------------- #

    def _make_app(self):
        from aiohttp import web

        def handler(fn):
            async def h(request):
                return web.Response(
                    text=json.dumps(fn(), default=str),
                    content_type="application/json",
                )

            return h

        app = web.Application()
        app.router.add_get("/api/summary", handler(self.summary_view))
        app.router.add_get("/api/jobs", handler(self.jobs_view))
        app.router.add_get("/api/profiles", handler(self.profiles_view))
        app.router.add_get("/api/notebooks", handler(self.notebooks_view))
        app.router.add_get("/api/tensorboards", handler(self.tensorboards_view))
        return app

