"""TensorBoard controller: serve training logdirs on demand.

The reference's tensorboard-controller turns a ``Tensorboard`` CR into a
Deployment serving logs from a PVC/GCS path (SURVEY.md §2.5; upstream
analog [kubeflow/kubeflow] components/tensorboard-controller/ —
UNVERIFIED, SURVEY.md §0). Here a TensorboardSpec becomes a one-replica
restart-Always job serving the logdir over HTTP. The default payload is
``kubeflow_tpu.platform.logserver`` (this image's ``tensorboard.main`` CLI
cannot start — see that module); ``command`` overrides it for images where
real TensorBoard works, with ``{logdir}``/``{port}`` placeholders.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from pathlib import Path

from kubeflow_tpu.orchestrator.cluster import LocalCluster
from kubeflow_tpu.orchestrator.envwire import free_port
from kubeflow_tpu.orchestrator.spec import (
    JobSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
)


@dataclasses.dataclass(frozen=True)
class TensorboardSpec:
    name: str
    logdir: str
    namespace: str = "default"
    port: int = 0  # 0 → allocate
    #: override the server command; "{logdir}" and "{port}" are substituted
    command: tuple[str, ...] | None = None


@dataclasses.dataclass
class TensorboardStatus:
    phase: str = "Pending"
    job_uid: str | None = None
    port: int = 0
    restarts: int = 0
    created: float = dataclasses.field(default_factory=time.time)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


class TensorboardController:
    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster
        self._lock = threading.RLock()
        self._boards: dict[tuple[str, str], tuple[TensorboardSpec, TensorboardStatus]] = {}

    def create(self, spec: TensorboardSpec) -> TensorboardStatus:
        with self._lock:
            return self._create_locked(spec)

    def _create_locked(self, spec: TensorboardSpec) -> TensorboardStatus:
        key = (spec.namespace, spec.name)
        if key in self._boards:
            raise ValueError(f"tensorboard {spec.name!r} already exists")
        port = spec.port or free_port()
        env: dict[str, str] = {}
        if spec.command is not None:
            command = tuple(
                c.format(logdir=spec.logdir, port=port) for c in spec.command
            )
        else:
            command = (
                sys.executable, "-m", "kubeflow_tpu.platform.logserver",
                "--logdir", spec.logdir,
                "--port", str(port),
                "--host", "127.0.0.1",
            )
            # the payload imports this package; the worker's cwd is its job
            # workdir, so put our install root on the child's path
            import kubeflow_tpu

            pkg_root = str(Path(kubeflow_tpu.__file__).resolve().parent.parent)
            existing = os.environ.get("PYTHONPATH", "")
            env["PYTHONPATH"] = (
                f"{pkg_root}:{existing}" if existing else pkg_root
            )
        job = JobSpec(
            name=f"tensorboard-{spec.name}",
            namespace=spec.namespace,
            labels={"kubeflow-tpu/tensorboard": spec.name},
            replicas={
                "server": ReplicaSpec(
                    replicas=1,
                    command=command,
                    env=env,
                    restart_policy=RestartPolicy.ALWAYS,
                )
            },
            run_policy=RunPolicy(backoff_limit=1_000_000),
        )
        status = TensorboardStatus(port=port)
        status.job_uid = self.cluster.submit(job)
        self._boards[key] = (spec, status)
        return status

    def get(self, name: str, namespace: str = "default") -> TensorboardStatus:
        with self._lock:
            spec, status = self._boards[(namespace, name)]
        job = self.cluster.get(status.job_uid) if status.job_uid else None
        if job is not None:
            worker = self.cluster.workers.get(f"{status.job_uid}/server-0")
            status.restarts = worker.restarts if worker else 0
            if job.status.finished:
                status.phase = "Failed"
            elif status.restarts >= 3:
                # restart-Always masks a broken payload as Running forever;
                # surface the crash loop instead.
                status.phase = "CrashLooping"
            else:
                status.phase = job.status.phase
        return status

    def list(self, namespace: str = "default") -> list[TensorboardSpec]:
        with self._lock:
            return [
                s for (ns, _), (s, _) in self._boards.items() if ns == namespace
            ]

    def statuses(self) -> list[tuple[TensorboardSpec, TensorboardStatus]]:
        """Refreshed (spec, status) snapshot across all namespaces."""
        with self._lock:
            return [
                (s, self.get(name, ns))
                for (ns, name), (s, _) in list(self._boards.items())
            ]

    def delete(self, name: str, namespace: str = "default") -> None:
        with self._lock:
            entry = self._boards.pop((namespace, name), None)
        if entry and entry[1].job_uid:
            self.cluster.delete(entry[1].job_uid)
