"""PodDefaults: label-selected defaults injected at admission.

The reference's admission-webhook component injects secrets/env/tolerations
into pods whose labels match a ``PodDefault`` selector (SURVEY.md §2.5;
upstream analog [kubeflow/kubeflow] components/admission-webhook/ —
UNVERIFIED, SURVEY.md §0). Here a ``PodDefault`` is a mutator on the
admission chain: jobs whose labels match the selector get env/labels merged
into every replica — explicit values on the job always win.
"""

from __future__ import annotations

import dataclasses

from kubeflow_tpu.orchestrator.spec import JobSpec


@dataclasses.dataclass(frozen=True)
class PodDefault:
    name: str
    #: all selector pairs must be present in the job's labels
    selector: dict[str, str] = dataclasses.field(default_factory=dict)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)

    def matches(self, spec: JobSpec) -> bool:
        return all(spec.labels.get(k) == v for k, v in self.selector.items())

    def __call__(self, spec: JobSpec) -> JobSpec:
        """Mutator: merge defaults under the job's own settings. Pure — the
        caller's spec object is never modified (a retried submit must not
        see a silently altered spec)."""
        if not self.matches(spec):
            return spec
        replicas = {}
        for rtype, r in spec.replicas.items():
            merged = {**self.env, **r.env}  # job env wins
            replicas[rtype] = (
                dataclasses.replace(r, env=merged) if merged != dict(r.env) else r
            )
        labels = dict(spec.labels)
        for k, v in self.labels.items():
            labels.setdefault(k, v)
        return dataclasses.replace(spec, replicas=replicas, labels=labels)
