"""Notebook controller: interactive workspaces with idle culling.

The reference's notebook controller reconciles a ``Notebook`` CR into a
StatefulSet + Service running Jupyter/VSCode, and its culling option stops
idle servers (SURVEY.md §2.5; upstream analog [kubeflow/kubeflow]
components/notebook-controller/ — UNVERIFIED, SURVEY.md §0). The TPU
control plane maps a notebook to a single-replica, restart-Always JAXJob —
an interactive process gang member with chips if requested — plus the
culling loop: activity is reported via ``touch()`` (the web-app "last
activity" probe analog) or the process's own heartbeat file, and a
notebook idle past ``culling_idle_seconds`` has its job deleted. ``wake()``
resubmits a culled notebook — scale-to-zero semantics for workspaces.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from kubeflow_tpu.obs import heartbeat as hb
from kubeflow_tpu.orchestrator.cluster import LocalCluster
from kubeflow_tpu.orchestrator.spec import (
    JobSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    TPURequest,
)


@dataclasses.dataclass(frozen=True)
class NotebookSpec:
    name: str
    command: tuple[str, ...]
    namespace: str = "default"
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    tpu: TPURequest = dataclasses.field(default_factory=TPURequest)
    #: None disables culling
    culling_idle_seconds: float | None = None


@dataclasses.dataclass
class NotebookStatus:
    phase: str = "Pending"  # Pending | Running | Culled | Failed
    job_uid: str | None = None
    #: time.monotonic() stamp — idle culling is duration math and must
    #: survive wall-clock jumps (same contract as obs.heartbeat)
    last_activity: float = dataclasses.field(default_factory=time.monotonic)
    culled_at: float | None = None


class NotebookController:
    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster
        # RLock: any thread (app, dashboard) may call into the controller;
        # reconcile iterates + mutates, so all access serializes here.
        self._lock = threading.RLock()
        self._notebooks: dict[tuple[str, str], tuple[NotebookSpec, NotebookStatus]] = {}

    # -- CRUD ----------------------------------------------------------- #

    def create(self, spec: NotebookSpec) -> NotebookStatus:
        with self._lock:
            key = (spec.namespace, spec.name)
            if key in self._notebooks:
                raise ValueError(f"notebook {spec.name!r} already exists")
            status = NotebookStatus()
            self._notebooks[key] = (spec, status)
            self._start(spec, status)
            return status

    def get(self, name: str, namespace: str = "default") -> NotebookStatus:
        with self._lock:
            self.reconcile()
            return self._notebooks[(namespace, name)][1]

    def list(self, namespace: str = "default") -> list[NotebookSpec]:
        with self._lock:
            return [
                s for (ns, _), (s, _) in self._notebooks.items() if ns == namespace
            ]

    def statuses(self) -> list[tuple[NotebookSpec, NotebookStatus]]:
        """Reconciled (spec, status) snapshot across all namespaces."""
        with self._lock:
            self.reconcile()
            return [(s, st) for (s, st) in self._notebooks.values()]

    def delete(self, name: str, namespace: str = "default") -> None:
        with self._lock:
            entry = self._notebooks.pop((namespace, name), None)
        if entry and entry[1].job_uid:
            self.cluster.delete(entry[1].job_uid)

    # -- activity + culling --------------------------------------------- #

    def touch(self, name: str, namespace: str = "default") -> None:
        """Record user activity (the web app's probe analog)."""
        with self._lock:
            status = self._notebooks[(namespace, name)][1]
            status.last_activity = time.monotonic()

    def wake(self, name: str, namespace: str = "default") -> NotebookStatus:
        """Re-start a culled notebook."""
        with self._lock:
            spec, status = self._notebooks[(namespace, name)]
            if status.phase != "Culled":
                return status
            status.last_activity = time.monotonic()
            status.culled_at = None
            self._start(spec, status)
            return status

    def reconcile(self, now: float | None = None) -> None:
        """Refresh phases; cull notebooks idle past their deadline.
        ``now`` is a ``time.monotonic()`` reading (beat stamps share it)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._reconcile_locked(now)

    def _reconcile_locked(self, now: float) -> None:
        for (ns, name), (spec, status) in list(self._notebooks.items()):
            if status.phase == "Culled" or status.job_uid is None:
                continue
            job = self.cluster.get(status.job_uid)
            if job is None:
                status.phase = "Failed"
                continue
            phase = job.status.phase
            status.phase = {
                "Running": "Running",
                "Failed": "Failed",
            }.get(phase, "Pending" if not job.status.finished else "Failed")

            # activity: explicit touches OR the process's own heartbeat
            beat = hb.read_heartbeat(
                hb.heartbeat_path(
                    self.cluster.launcher.workdir(status.job_uid), "notebook", 0
                )
            )
            if beat is not None:
                status.last_activity = max(status.last_activity, beat.time)

            idle = spec.culling_idle_seconds
            if (
                idle is not None
                and status.phase == "Running"
                and now - status.last_activity > idle
            ):
                self.cluster.delete(status.job_uid)
                status.phase = "Culled"
                status.culled_at = now
                status.job_uid = None

    # ------------------------------------------------------------------ #

    def _start(self, spec: NotebookSpec, status: NotebookStatus) -> None:
        job = JobSpec(
            name=f"notebook-{spec.name}",
            namespace=spec.namespace,
            labels={"kubeflow-tpu/notebook": spec.name},
            replicas={
                "notebook": ReplicaSpec(
                    replicas=1,
                    command=spec.command,
                    env=dict(spec.env),
                    restart_policy=RestartPolicy.ALWAYS,  # workspaces respawn
                    tpu=spec.tpu,
                )
            },
            # interactive: effectively unbounded restarts, no TTL surprise
            run_policy=RunPolicy(
                backoff_limit=1_000_000,
                scheduling=SchedulingPolicy(gang=True),
            ),
        )
        status.job_uid = self.cluster.submit(job)
        status.phase = "Pending"
