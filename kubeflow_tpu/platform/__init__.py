"""Platform plane: multi-tenancy, workspaces, defaults, dashboard.

Minimal TPU-native equivalents of the reference's platform components
(SURVEY.md §2.5): Profile controller (namespace + quota + access), Notebook
controller (interactive jobs with idle culling), TensorBoard controller,
PodDefaults admission mutator, and the central-dashboard aggregation API.
"""

from kubeflow_tpu.platform.dashboard import DashboardServer
from kubeflow_tpu.platform.notebooks import (
    NotebookController,
    NotebookSpec,
    NotebookStatus,
)
from kubeflow_tpu.platform.poddefaults import PodDefault
from kubeflow_tpu.platform.profiles import (
    Profile,
    ProfileController,
    ResourceQuota,
    job_chips,
)
from kubeflow_tpu.platform.tensorboards import (
    TensorboardController,
    TensorboardSpec,
    TensorboardStatus,
)

__all__ = [
    "DashboardServer",
    "NotebookController",
    "NotebookSpec",
    "NotebookStatus",
    "PodDefault",
    "Profile",
    "ProfileController",
    "ResourceQuota",
    "TensorboardController",
    "TensorboardSpec",
    "TensorboardStatus",
    "job_chips",
]
