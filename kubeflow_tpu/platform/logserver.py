"""Lightweight training-log viewer — the TensorBoard payload that works.

This image's ``tensorboard.main`` CLI cannot start (its ``pkg_resources``
import is gone on py3.12), so the TensorBoard controller's default payload
is this first-party server instead: it serves every run under a logdir over
HTTP, reading BOTH metric formats the framework writes (SURVEY.md §5.5) —
``metrics.jsonl`` from ``kubeflow_tpu.train.metrics.MetricWriter`` and
TFEvents files (via tensorboard's event_accumulator, which still imports
cleanly) — plus a listing of ``jax.profiler`` trace captures.

- ``GET /``                   → minimal HTML index of runs
- ``GET /api/runs``           → run names (dirs holding metrics/events)
- ``GET /api/scalars?run=X``  → {metric: [[step, wall_time, value], ...]}
- ``GET /api/profiles``       → captured profile trace directories
- ``GET /healthz``            → liveness

Run: ``python -m kubeflow_tpu.platform.logserver --logdir DIR --port N``.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def find_runs(logdir: Path, max_depth: int = 4) -> list[str]:
    """Directories (relative to logdir; '.' = root) containing scalar data."""
    runs: set[str] = set()

    def scan(d: Path, depth: int) -> None:
        try:
            entries = list(d.iterdir())
        except OSError:
            return
        has_data = any(
            e.name == "metrics.jsonl" or e.name.startswith("events.out.tfevents")
            for e in entries
            if e.is_file()
        )
        if has_data:
            runs.add(str(d.relative_to(logdir)) or ".")
        if depth < max_depth:
            for e in entries:
                if e.is_dir():
                    scan(e, depth + 1)

    scan(logdir, 0)
    return sorted(runs)


def read_scalars(run_dir: Path) -> dict[str, list[list[float]]]:
    """Merged scalar streams: metric name → [[step, wall_time, value]…]."""
    out: dict[str, list[list[float]]] = {}

    jsonl = run_dir / "metrics.jsonl"
    if jsonl.exists():
        for line in jsonl.read_text().splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            step = rec.get("step")
            if not isinstance(step, (int, float)):
                continue  # summary/partial records carry no step
            t = rec.get("time", 0.0)
            for k, v in rec.items():
                if k in ("step", "time") or not isinstance(v, (int, float)):
                    continue
                out.setdefault(k, []).append([float(step), float(t), float(v)])

    if any(f.name.startswith("events.out.tfevents") for f in run_dir.iterdir()):
        try:
            from tensorboard.backend.event_processing.event_accumulator import (
                EventAccumulator,
            )

            acc = EventAccumulator(str(run_dir))
            acc.Reload()
            for tag in acc.Tags().get("scalars", ()):
                out.setdefault(tag, []).extend(
                    [[float(e.step), float(e.wall_time), float(e.value)]
                     for e in acc.Scalars(tag)]
                )
        except Exception:  # noqa: BLE001 — events are best-effort extra
            pass

    for series in out.values():
        series.sort(key=lambda rec: rec[0])
    return out


def find_profiles(logdir: Path) -> list[str]:
    """jax.profiler capture dirs (the plugins/profile layout)."""
    return sorted(
        str(p.parent.relative_to(logdir))
        for p in logdir.rglob("plugins/profile")
        if p.is_dir()
    )


_INDEX_HTML = """<!doctype html>
<title>kubeflow-tpu logs</title>
<h1>kubeflow-tpu log server</h1>
<p>logdir: <code>{logdir}</code></p>
<h2>runs</h2>
<ul>{runs}</ul>
<h2>profile captures</h2>
<ul>{profiles}</ul>
"""


def make_app(logdir: Path):
    from aiohttp import web

    async def index(request):
        import html
        from urllib.parse import quote

        runs = "".join(
            f'<li><a href="/api/scalars?run={quote(r)}">{html.escape(r)}</a></li>'
            for r in find_runs(logdir)
        )
        profiles = "".join(
            f"<li>{html.escape(p)}</li>" for p in find_profiles(logdir)
        )
        return web.Response(
            text=_INDEX_HTML.format(
                logdir=logdir, runs=runs or "<li>(none)</li>",
                profiles=profiles or "<li>(none)</li>",
            ),
            content_type="text/html",
        )

    async def runs(request):
        return web.json_response(find_runs(logdir))

    async def scalars(request):
        run = request.query.get("run", ".")
        run_dir = (logdir / run).resolve()
        if not run_dir.is_relative_to(logdir.resolve()):
            return web.json_response({"error": "run escapes logdir"}, status=400)
        if not run_dir.is_dir():
            return web.json_response({"error": f"no run {run!r}"}, status=404)
        return web.json_response(read_scalars(run_dir))

    async def profiles(request):
        return web.json_response(find_profiles(logdir))

    async def healthz(request):
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/api/runs", runs)
    app.router.add_get("/api/scalars", scalars)
    app.router.add_get("/api/profiles", profiles)
    app.router.add_get("/healthz", healthz)
    return app


def main(argv: list[str] | None = None) -> int:
    from aiohttp import web

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--logdir", required=True)
    p.add_argument("--port", type=int, default=6006)
    p.add_argument("--host", default="127.0.0.1")
    args = p.parse_args(argv)
    logdir = Path(args.logdir)
    logdir.mkdir(parents=True, exist_ok=True)
    web.run_app(
        make_app(logdir), host=args.host, port=args.port, print=None
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
