"""Volumes: durable named workspaces — the PVC + volumes web app analog.

Reference analog (SURVEY.md §2.5 CRUD-web-apps row: the volumes app
creates/lists/deletes PersistentVolumeClaims for notebooks and jobs —
UNVERIFIED, mount empty, §0). Without a storage provisioner, a volume is
a managed directory under one root with a soft capacity quota: creation
is atomic, usage is measured (the PVC "requested vs used" columns),
deletion refuses while any notebook or job references the volume (the
`kubernetes.io/pvc-protection` finalizer analog), and a `mount()` hands
a consumer the path + env wiring (``KFT_VOLUME_<NAME>``) so processes
find their volumes the same way containers find mount paths.
"""

from __future__ import annotations

import dataclasses
import os
import re
import shutil
import threading
import time

_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")  # DNS-1123


@dataclasses.dataclass(frozen=True)
class VolumeSpec:
    name: str
    namespace: str = "default"
    size_mb: int = 1024            # soft quota, enforced at usage checks

    def validate(self) -> None:
        # BOTH name and namespace become path components under the managed
        # root — DNS-1123 validation is also the path-traversal guard
        # ('../../x' must never reach os.path.join)
        if not _NAME_RE.match(self.name):
            raise ValueError(
                f"volume name {self.name!r} must be DNS-1123 (lowercase "
                "alphanumerics and '-')"
            )
        if not _NAME_RE.match(self.namespace):
            raise ValueError(
                f"volume namespace {self.namespace!r} must be DNS-1123"
            )
        if self.size_mb < 1:
            raise ValueError(f"size_mb must be >= 1, got {self.size_mb}")

    @classmethod
    def from_manifest(cls, doc) -> "VolumeSpec":
        """Accepts the PVC manifest shape 1:1: metadata.name/namespace +
        spec.resources.requests.storage ('1Gi', '512Mi')."""
        meta = doc.get("metadata", {})
        storage = (
            doc.get("spec", {})
            .get("resources", {})
            .get("requests", {})
            .get("storage", "1Gi")
        )
        m = re.fullmatch(r"(\d+)(Gi|Mi)", str(storage))
        if not m:
            raise ValueError(
                f"unsupported storage quantity {storage!r} (use NGi/NMi)"
            )
        size_mb = int(m.group(1)) * (1024 if m.group(2) == "Gi" else 1)
        spec = cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            size_mb=size_mb,
        )
        spec.validate()
        return spec


@dataclasses.dataclass
class VolumeStatus:
    phase: str = "Bound"           # PVCs here bind immediately
    created_at: float = dataclasses.field(default_factory=time.time)
    #: consumers holding the volume (notebook/job names) — deletion
    #: protection while non-empty
    bound_to: set[str] = dataclasses.field(default_factory=set)


class VolumeController:
    """CRUD + mount wiring over one managed root directory."""

    _META = ".kft-volume.json"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.RLock()
        self._volumes: dict[tuple[str, str], tuple[VolumeSpec, VolumeStatus]] = {}
        self._recover()

    def _recover(self) -> None:
        """Volumes are DURABLE directories; re-register what survives a
        process restart (each carries its spec in a meta file). Runs under
        the lock: __init__ is the only caller today, but ``_volumes`` is
        lock-guarded state and recovery must stay safe if it ever runs
        against a live controller (e.g. a future re-scan verb)."""
        with self._lock:
            self._recover_locked()

    def _recover_locked(self) -> None:
        import json

        for ns in sorted(os.listdir(self.root)):
            ns_dir = os.path.join(self.root, ns)
            if not os.path.isdir(ns_dir):
                continue
            for name in sorted(os.listdir(ns_dir)):
                meta = os.path.join(ns_dir, name, self._META)
                if not os.path.isfile(meta):
                    continue
                try:
                    with open(meta) as f:
                        doc = json.load(f)
                    spec = VolumeSpec(
                        name=name, namespace=ns,
                        size_mb=int(doc.get("size_mb", 1024)),
                    )
                    spec.validate()
                except (OSError, ValueError, TypeError):
                    continue  # corrupt meta: leave the dir, don't serve it
                self._volumes[(ns, name)] = (spec, VolumeStatus())

    # -- CRUD ----------------------------------------------------------- #

    def create(self, spec: VolumeSpec) -> str:
        import json

        spec.validate()
        key = (spec.namespace, spec.name)
        with self._lock:
            if key in self._volumes:
                raise ValueError(
                    f"volume {spec.namespace}/{spec.name} already exists"
                )
            path = self.path(spec.namespace, spec.name)
            try:
                os.makedirs(path, exist_ok=False)
            except FileExistsError:
                # a directory without a registered volume (pre-restart
                # leftover with corrupt meta): surface as the same
                # already-exists contract, not a 500
                raise ValueError(
                    f"volume {spec.namespace}/{spec.name} already exists "
                    "on disk"
                ) from None
            with open(os.path.join(path, self._META), "w") as f:
                json.dump({"size_mb": spec.size_mb}, f)
            self._volumes[key] = (spec, VolumeStatus())
            return path

    def path(self, namespace: str, name: str) -> str:
        # belt-and-braces beyond validate(): never join a traversal
        if not _NAME_RE.match(namespace) or not _NAME_RE.match(name):
            raise ValueError(f"bad volume path {namespace!r}/{name!r}")
        return os.path.join(self.root, namespace, name)

    def count(self) -> int:
        with self._lock:
            return len(self._volumes)

    def get(self, name: str, namespace: str = "default") -> VolumeSpec:
        with self._lock:
            if (namespace, name) not in self._volumes:
                raise KeyError(f"volume {namespace}/{name} not found")
            return self._volumes[(namespace, name)][0]

    def delete(self, name: str, namespace: str = "default") -> None:
        with self._lock:
            key = (namespace, name)
            if key not in self._volumes:
                raise KeyError(f"volume {namespace}/{name} not found")
            _, status = self._volumes[key]
            if status.bound_to:
                # pvc-protection finalizer analog: in-use volumes refuse
                raise ValueError(
                    f"volume {namespace}/{name} is in use by "
                    f"{sorted(status.bound_to)}"
                )
            del self._volumes[key]
            shutil.rmtree(self.path(namespace, name), ignore_errors=True)

    def usage_mb(self, name: str, namespace: str = "default") -> float:
        path = self.path(namespace, name)
        total = 0
        for r, _, files in os.walk(path):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(r, f))
                except OSError:
                    pass
        return total / 2**20

    def statuses(self) -> list[tuple[VolumeSpec, VolumeStatus, float]]:
        with self._lock:
            items = list(self._volumes.values())
        return [
            (spec, status, round(self.usage_mb(spec.name, spec.namespace), 3))
            for spec, status in items
        ]

    # -- mounting -------------------------------------------------------- #

    def mount(
        self, name: str, consumer: str, namespace: str = "default"
    ) -> tuple[str, dict[str, str]]:
        """Bind the volume to ``consumer``; returns (path, env) where env
        carries ``KFT_VOLUME_<NAME>=path`` — the mount-path contract jobs
        and notebooks read. Quota: mounting fails once usage exceeds the
        requested size (the provisioner's out-of-space analog)."""
        with self._lock:
            spec = self.get(name, namespace)
            _, status = self._volumes[(namespace, name)]
            if self.usage_mb(name, namespace) > spec.size_mb:
                raise ValueError(
                    f"volume {namespace}/{name} over quota "
                    f"({spec.size_mb} MB)"
                )
            status.bound_to.add(consumer)
            path = self.path(namespace, name)
            env_name = "KFT_VOLUME_" + name.upper().replace("-", "_")
            return path, {env_name: path}

    def unmount(
        self, name: str, consumer: str, namespace: str = "default"
    ) -> None:
        with self._lock:
            if (namespace, name) in self._volumes:
                self._volumes[(namespace, name)][1].bound_to.discard(consumer)
