"""Profiles: multi-tenancy — namespaces, access rules, resource quotas.

The reference's profile controller turns a ``Profile`` CR into a namespace
+ RBAC + Istio authz + resource quotas (SURVEY.md §2.5; upstream analog
[kubeflow/kubeflow] components/profile-controller/ — UNVERIFIED, SURVEY.md
§0). The TPU control plane keeps the same contract without a cluster: a
profile OWNS a namespace, lists who may act in it, and carries a chip/job
quota enforced at admission time — the `google.com/tpu` ResourceQuota
analog, counted against live (non-finished) jobs.
"""

from __future__ import annotations

import dataclasses
import time

from kubeflow_tpu.orchestrator.cluster import LocalCluster
from kubeflow_tpu.orchestrator.spec import JobSpec
from kubeflow_tpu.orchestrator.webhooks import AdmissionError


@dataclasses.dataclass(frozen=True)
class ResourceQuota:
    """Per-namespace ceilings; None = unlimited.

    The serving fields are read by the inference gateway's
    ``PolicyEngine.from_profiles`` (gateway/policy.py): the same profile
    that caps a tenant's training chips caps its edge traffic — the Istio
    authz + local-rate-limit half of the reference's profile contract.
    """

    max_chips: int | None = None
    max_jobs: int | None = None
    #: serving: sustained requests/second at the gateway (token bucket)
    max_rps: float | None = None
    #: serving: token-bucket burst size (default: max(1, max_rps))
    burst: int | None = None
    #: serving: concurrent in-flight requests at the gateway
    max_concurrent_requests: int | None = None
    #: serving: overload shed order (higher = shed LAST); stamped by the
    #: gateway as x-kft-priority and honored by engine admission control
    priority: int = 0


@dataclasses.dataclass
class Profile:
    name: str  # doubles as the namespace, as in the reference
    owner: str
    contributors: list[str] = dataclasses.field(default_factory=list)
    quota: ResourceQuota = dataclasses.field(default_factory=ResourceQuota)
    created: float = dataclasses.field(default_factory=time.time)

    def can_act(self, user: str) -> bool:
        return user == self.owner or user in self.contributors


def job_chips(spec: JobSpec) -> int:
    return sum(r.replicas * r.tpu.chips for r in spec.replicas.values())


class ProfileController:
    """Holds profiles and enforces their quotas on the cluster's jobs.

    Register with ``install()``; admission then rejects any job whose
    namespace has a profile and would exceed its quota. Namespaces without
    a profile are unmanaged (admitted freely) unless ``strict``.
    """

    def __init__(self, cluster: LocalCluster, *, strict: bool = False):
        self.cluster = cluster
        self.strict = strict
        self._profiles: dict[str, Profile] = {}

    # -- CRUD ----------------------------------------------------------- #

    def create(self, profile: Profile) -> Profile:
        if profile.name in self._profiles:
            raise ValueError(f"profile {profile.name!r} already exists")
        self._profiles[profile.name] = profile
        return profile

    def get(self, name: str) -> Profile | None:
        return self._profiles.get(name)

    def list(self) -> list[Profile]:
        return list(self._profiles.values())

    def delete(self, name: str) -> None:
        self._profiles.pop(name, None)

    # -- enforcement ---------------------------------------------------- #

    def install(self) -> None:
        self.cluster.admission.add_validator(self.validate)

    def usage(self, namespace: str) -> dict[str, int]:
        """Live (non-finished) chips and jobs in the namespace."""
        chips = jobs = 0
        for _, job in self.cluster.jobs.list():
            if job.spec.namespace != namespace or job.status.finished:
                continue
            jobs += 1
            chips += job_chips(job.spec)
        return {"chips": chips, "jobs": jobs}

    def validate(self, spec: JobSpec) -> None:
        profile = self._profiles.get(spec.namespace)
        if profile is None:
            if self.strict:
                raise AdmissionError(
                    f"namespace {spec.namespace!r} has no profile "
                    "(strict multi-tenancy)"
                )
            return
        used = self.usage(spec.namespace)
        q = profile.quota
        want = job_chips(spec)
        if q.max_chips is not None and used["chips"] + want > q.max_chips:
            raise AdmissionError(
                f"quota exceeded in {spec.namespace!r}: job wants {want} "
                f"chips, {used['chips']}/{q.max_chips} in use"
            )
        if q.max_jobs is not None and used["jobs"] + 1 > q.max_jobs:
            raise AdmissionError(
                f"quota exceeded in {spec.namespace!r}: "
                f"{used['jobs']}/{q.max_jobs} jobs already live"
            )
