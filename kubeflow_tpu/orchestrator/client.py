"""TrainingClient: the Python SDK surface.

API-compatible in spirit with the reference SDK's ``TrainingClient``
(SURVEY.md §2.1 "Python SDK" row; upstream analog [training-operator]
sdk/python/kubeflow/training/api/training_client.py — UNVERIFIED,
SURVEY.md §0): create/get/wait/logs/delete, plus a high-level ``train()``
that builds the JAXJob for a python entrypoint.
"""

from __future__ import annotations

import sys
import time
from typing import Mapping, Sequence

from kubeflow_tpu.orchestrator.cluster import LocalCluster
from kubeflow_tpu.orchestrator.spec import (
    JobConditionType,
    JobSpec,
    JobStatus,
    ReplicaSpec,
    RunPolicy,
    TPURequest,
)


class TrainingClient:
    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster
        self._by_name: dict[tuple[str, str], str] = {}  # (ns, name) → uid

    # ------------------------------------------------------------------ #

    def create_job(self, spec: JobSpec) -> str:
        key = (spec.namespace, spec.name)
        if key in self._by_name and self.cluster.get(self._by_name[key]):
            raise ValueError(
                f"job {spec.name!r} already exists in {spec.namespace!r}"
            )
        uid = self.cluster.submit(spec)
        self._by_name[key] = uid
        return uid

    def train(
        self,
        name: str,
        *,
        module: str,
        args: Sequence[str] = (),
        num_workers: int = 1,
        chips_per_worker: int = 0,
        env: Mapping[str, str] | None = None,
        run_policy: RunPolicy | None = None,
    ) -> str:
        """High-level API: launch ``python -m module`` as an SPMD gang —
        the ``TrainingClient.train()`` fine-tune-analog."""
        spec = JobSpec(
            name=name,
            replicas={
                "worker": ReplicaSpec(
                    replicas=num_workers,
                    command=(sys.executable, "-m", module, *args),
                    env=dict(env or {}),
                    tpu=TPURequest(chips=chips_per_worker),
                )
            },
            run_policy=run_policy or RunPolicy(),
        )
        return self.create_job(spec)

    # ------------------------------------------------------------------ #

    def _uid(self, name: str, namespace: str = "default") -> str:
        uid = self._by_name.get((namespace, name))
        if uid is None:
            job = self.cluster.find(name, namespace)
            if job is None:
                raise KeyError(f"job {name!r} not found in {namespace!r}")
            uid = job.spec.uid
        return uid

    def get_job_status(self, name: str, namespace: str = "default") -> JobStatus:
        status = self.cluster.status(self._uid(name, namespace))
        if status is None:
            raise KeyError(f"job {name!r} not found in {namespace!r}")
        return status

    def wait_for_job_conditions(
        self,
        name: str,
        namespace: str = "default",
        *,
        conditions: set[JobConditionType] = frozenset(
            {JobConditionType.SUCCEEDED}
        ),
        timeout: float = 300.0,
    ) -> JobStatus:
        uid = self._uid(name, namespace)
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.cluster.get(uid)
            if job is None:
                raise KeyError(f"job {name!r} disappeared")
            for c in job.status.conditions:
                if c.type in conditions and c.status:
                    return job.status
            if job.status.finished:
                raise RuntimeError(
                    f"job {name!r} finished as {job.status.phase} "
                    f"while waiting for {sorted(c.value for c in conditions)}: "
                    f"{job.status.condition().message}"
                )
            time.sleep(0.05)
        raise TimeoutError(f"job {name!r}: conditions not met in {timeout}s")

    def get_job_logs(
        self, name: str, namespace: str = "default",
        replica_type: str = "worker", index: int = 0,
    ) -> str:
        return self.cluster.logs(self._uid(name, namespace), replica_type, index)

    def delete_job(self, name: str, namespace: str = "default") -> None:
        self.cluster.delete(self._uid(name, namespace))

    def list_jobs(self, namespace: str = "default") -> list[JobSpec]:
        return [
            j.spec
            for _, j in self.cluster.jobs.list()
            if j.spec.namespace == namespace
        ]
