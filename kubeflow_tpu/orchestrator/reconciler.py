"""The JAXJob reconciler: desired vs actual gang state.

One engine serving every job kind, like the reference's shared common
reconciler that all five operators delegate to (SURVEY.md §2.1 "Common job
reconciler"; upstream analog [training-operator]
pkg/controller.v1/common/{job,pod,status}.go — UNVERIFIED, SURVEY.md §0).

Condition flow: Created → Queued → Running → (Restarting → Running)* →
Succeeded | Failed, with RunPolicy enforcement (backoff limit with
exponential delay, active deadline, TTL-after-finished, cleanPodPolicy) and
per-replica RestartPolicy incl. ExitCode semantics.

TPU-native divergence (deliberate): worker failure restarts the WHOLE gang,
not just the failed pod. JAX SPMD worlds are static — the coordinator aborts
every peer when one dies (SURVEY.md §5.3) — so single-pod restart as in the
reference would thrash. Restart-the-gang + checkpoint-restore is the
elasticity model.
"""

from __future__ import annotations

import dataclasses
import logging
import signal
import time

from kubeflow_tpu.obs import names, prom
from kubeflow_tpu.orchestrator import envwire
from kubeflow_tpu.orchestrator.gang import GangScheduler, PodGroup
from kubeflow_tpu.orchestrator.launcher import ProcessLauncher
from kubeflow_tpu.orchestrator.spec import (
    CleanPodPolicy,
    JobConditionType as CT,
    JobSpec,
    JobStatus,
    SuccessPolicy,
    WorkerPhase,
    WorkerStatus,
    worker_key,
)
from kubeflow_tpu.orchestrator.store import ObjectStore

logger = logging.getLogger(__name__)

GANG_RESTARTS = prom.REGISTRY.counter(
    names.GANG_RESTARTS_TOTAL,
    "gang restarts triggered by worker failures",
)
GANG_REQUEUES = prom.REGISTRY.counter(
    names.GANG_REQUEUES_TOTAL,
    "gangs sent back to the scheduler queue after losing placement",
    labels=("reason",),
)
JOBS_FINISHED = prom.REGISTRY.counter(
    names.JOBS_FINISHED_TOTAL, "jobs reaching a terminal condition",
    labels=("condition", "reason"),
)


@dataclasses.dataclass
class JobObject:
    """What the job store holds: spec + status + controller bookkeeping."""

    spec: JobSpec
    status: JobStatus = dataclasses.field(default_factory=JobStatus)
    coordinator_port: int = 0
    #: "{rtype}-{index}" → per-worker service port for this gang attempt
    #: (TF_CONFIG cluster spec, torch MASTER_PORT, paddle endpoints).
    service_ports: dict[str, int] = dataclasses.field(default_factory=dict)
    next_restart_at: float = 0.0
    deletion_requested: bool = False
    #: pending elastic resize target for the scalable group (None = none).
    resize_to: int | None = None
    #: SIGTERM-to-SIGKILL deadline while the quota scheduler preempts this
    #: gang (None = no preemption in flight).
    preempt_deadline: float | None = None


class JobController:
    """Synchronous reconcile logic; the cluster loop calls ``sync_all``."""

    def __init__(
        self,
        jobs: ObjectStore,
        workers: ObjectStore,
        scheduler: GangScheduler,
        launcher: ProcessLauncher,
        wiring: envwire.WiringConfig,
        *,
        restart_backoff_base: float = 1.0,
        kill_wait_seconds: float = 5.0,
        supervisor=None,
    ):
        self.jobs = jobs
        self.workers = workers
        self.scheduler = scheduler
        self.launcher = launcher
        self.wiring = wiring
        self.restart_backoff_base = restart_backoff_base
        self.kill_wait_seconds = kill_wait_seconds
        #: HeartbeatSupervisor to detach when an attempt is torn down
        #: (requeue paths); optional so envtest-style setups stay light.
        self.supervisor = supervisor

    # ------------------------------------------------------------------ #

    def sync_all(self) -> None:
        self.scheduler.try_schedule()
        for uid, _ in self.jobs.list():
            try:
                self.sync_job(uid)
            except Exception:  # noqa: BLE001 — a bad job must not wedge the loop
                logger.exception("reconcile failed for job %s", uid)

    def sync_job(self, uid: str) -> None:
        job: JobObject | None = self.jobs.get(uid)
        if job is None:
            return
        spec, status = job.spec, job.status

        if job.deletion_requested:
            self._cleanup(job, kill_all=True)
            self._delete_records(uid)
            return

        if job.resize_to is not None and not status.finished:
            self._apply_resize(job)
            job = self.jobs.get(uid)
            if job is None:
                return
            spec, status = job.spec, job.status

        if status.finished:
            self._maybe_ttl(job)
            return

        if status.push(CT.CREATED, reason="JobCreated"):
            self.jobs.update(uid, job)

        # -- active deadline ------------------------------------------- #
        deadline = spec.run_policy.active_deadline_seconds
        if (
            deadline is not None
            and status.start_time is not None
            and time.time() - status.start_time > deadline
        ):
            self._finish(job, CT.FAILED, "DeadlineExceeded",
                         f"active deadline {deadline}s exceeded")
            return

        # -- desired worker set ---------------------------------------- #
        desired = self._ensure_workers(spec)

        # -- gang admission -------------------------------------------- #
        claims = self.scheduler.claims_for(uid)
        if claims is None:
            self._enqueue_gang(job, desired)
            self.scheduler.try_schedule()
            for g in self.scheduler.timed_out():
                j: JobObject | None = self.jobs.get(g.job_uid)
                if j is not None and not j.status.finished:
                    self._finish(
                        j, CT.FAILED, "Unschedulable",
                        "gang scheduling timeout: fleet cannot place the gang",
                    )
            job = self.jobs.get(uid)
            if job is None or job.status.finished:
                return
            claims = self.scheduler.claims_for(uid)
            if claims is None:
                if job.status.push(CT.QUEUED, reason="GangPending"):
                    self.jobs.update(uid, job)
                return
        status = job.status

        # -- scheduler-initiated preemption ------------------------------ #
        # Either the quota scheduler holds an intent against this gang, or
        # a drive is already in flight (deadline stamped) — the preemptor
        # may have vanished mid-drive, but a SIGTERMed gang must still be
        # requeued, not mistaken for a crash that burns backoff budget.
        requested = getattr(self.scheduler, "preemption_requested", None)
        if job.preempt_deadline is not None or (
            requested is not None and requested(uid)
        ):
            self._drive_preemption(job)
            return

        # -- slice loss: placement evaporated under a held gang ---------- #
        lost = sorted(
            {
                c.slice_id
                for c in claims.values()
                if not self.scheduler.fleet.has_slice(c.slice_id)
            }
        )
        if lost:
            self._requeue_gang(job, lost)
            return

        # -- placement + launch ---------------------------------------- #
        for w in desired:
            if w.phase is WorkerPhase.PENDING:
                claim = claims.get(w.key)
                self.workers.mutate(
                    w.key,
                    lambda ws, c=claim: _assign(ws, c),
                )
        if job.coordinator_port == 0:
            job.coordinator_port = envwire.free_port()
            job.service_ports = {
                f"{w.replica_type}-{w.index}": envwire.free_port()
                for w in desired
            }
            self.jobs.update(uid, job)

        if time.time() >= job.next_restart_at:
            for _, w in self.workers.list(prefix=f"{uid}/"):
                if w.phase is WorkerPhase.SCHEDULED:
                    self._launch(job, w)

        # -- aggregate ------------------------------------------------- #
        ws = [w for _, w in self.workers.list(prefix=f"{uid}/")]
        dirty = self._update_replica_statuses(job, ws)
        running = [w for w in ws if w.phase is WorkerPhase.RUNNING]
        failed = [w for w in ws if w.phase is WorkerPhase.FAILED]
        succeeded = [w for w in ws if w.phase is WorkerPhase.SUCCEEDED]

        if running and status.start_time is None:
            status.start_time = time.time()
            dirty = True
        if len(running) == len(ws):
            dirty |= status.push(CT.RUNNING, reason="AllWorkersRunning")

        # -- success --------------------------------------------------- #
        policy = spec.run_policy.success_policy
        if policy is SuccessPolicy.ALL_WORKERS and len(succeeded) == len(ws):
            self._finish(job, CT.SUCCEEDED, "AllWorkersSucceeded",
                         "every gang member exited 0")
            return
        if policy is SuccessPolicy.RANK0:
            rank0 = self._rank0_worker(spec, ws)
            if rank0 is not None and rank0.phase is WorkerPhase.SUCCEEDED:
                self._finish(job, CT.SUCCEEDED, "Rank0Succeeded",
                             "coordinator replica exited 0")
                return

        # -- failure / gang restart ------------------------------------ #
        if failed:
            self._handle_failures(job, ws, failed)
            return

        # Emit a watch event only on a real transition — an unconditional
        # update would wake our own loop and busy-spin the controller.
        if dirty:
            self.jobs.update(uid, job)

    # ------------------------------------------------------------------ #

    def _ensure_workers(self, spec: JobSpec) -> list[WorkerStatus]:
        out = []
        for rtype, rspec in spec.replicas.items():
            for i in range(rspec.replicas):
                key = worker_key(spec.uid, rtype, i)
                w = self.workers.get(key)
                if w is None:
                    w = WorkerStatus(
                        job_uid=spec.uid, replica_type=rtype, index=i
                    )
                    self.workers.create(key, w)
                out.append(w)
        return out

    def _enqueue_gang(self, job: JobObject, desired: list[WorkerStatus]) -> None:
        spec = job.spec
        sched = spec.run_policy.scheduling
        requests = []
        for w in desired:
            tpu = spec.replicas[w.replica_type].tpu
            requests.append((w.key, tpu.chips, tpu.topology, tpu.generation))
        self.scheduler.enqueue(
            PodGroup(
                job_uid=spec.uid,
                requests=requests,
                queue=sched.queue,
                priority=sched.priority,
                timeout_seconds=sched.timeout_seconds,
            )
        )

    def _launch(self, job: JobObject, w: WorkerStatus) -> None:
        spec = job.spec
        rspec = spec.replicas[w.replica_type]
        env = envwire.build_worker_env(
            spec,
            w.replica_type,
            w.index,
            coordinator_port=job.coordinator_port,
            service_ports=job.service_ports,
            wiring=self.wiring,
            workdir=str(self.launcher.workdir(spec.uid)),
            attempt=w.restarts,
        )
        self.launcher.start(w, rspec.command, env)

    def _handle_failures(
        self, job: JobObject, ws: list[WorkerStatus], failed: list[WorkerStatus]
    ) -> None:
        spec, status = job.spec, job.status
        nonretryable = [
            w
            for w in failed
            if not spec.replicas[w.replica_type].restart_policy.should_restart(
                w.exit_code if w.exit_code is not None else 1
            )
        ]
        if nonretryable:
            w = nonretryable[0]
            self._finish(
                job, CT.FAILED, "NonRetryableExit",
                f"{w.key} exited {w.exit_code} "
                f"(policy {spec.replicas[w.replica_type].restart_policy.value})",
            )
            return
        if status.restart_count >= spec.run_policy.backoff_limit:
            self._finish(
                job, CT.FAILED, "BackoffLimitExceeded",
                f"restarted {status.restart_count}x "
                f"(limit {spec.run_policy.backoff_limit})",
            )
            return

        # Gang restart: kill survivors, re-schedule everyone.
        GANG_RESTARTS.inc()
        status.restart_count += 1
        status.push(
            CT.RESTARTING, reason="GangRestart",
            message=f"{failed[0].key} exited {failed[0].exit_code}; "
                    f"restart {status.restart_count}/{spec.run_policy.backoff_limit}",
        )
        job.next_restart_at = time.time() + self.restart_backoff_base * (
            2 ** (status.restart_count - 1)
        )
        # New ports per attempt: the old processes may still hold the
        # previous ones while dying.
        job.coordinator_port = envwire.free_port()
        job.service_ports = {k: envwire.free_port() for k in job.service_ports}
        self.jobs.update(job.spec.uid, job)

        for w in ws:
            if w.phase is WorkerPhase.RUNNING:
                self.launcher.kill(w.key)
        self._wait_dead(ws)
        for w in ws:
            self.workers.mutate(w.key, _reset_for_restart)

    def _drive_preemption(self, job: JobObject) -> None:
        """Evict a gang the quota scheduler chose as a victim, through the
        graceful path preemption-tolerant training already understands:
        SIGTERM (the trainer force-checkpoints and exits 143) → grace →
        SIGKILL stragglers → claims released and the gang requeued
        ``Queued`` with ``reason=Preempted``. Deliberately NOT a failure:
        like slice loss, eviction is the platform's doing, so it burns
        neither ``backoff_limit`` budget nor ``restart_count`` — the victim
        resumes from its forced checkpoint when capacity returns."""
        spec, status = job.spec, job.status
        uid = spec.uid
        ws = [w for _, w in self.workers.list(prefix=f"{uid}/")]

        if job.preempt_deadline is None:
            grace = getattr(
                self.scheduler, "preemption_grace_seconds", 5.0
            )
            status.push(
                CT.RESTARTING, reason="Preempting",
                message="quota reclaimed; checkpointing before requeue",
            )
            job.preempt_deadline = time.time() + grace
            self.jobs.update(uid, job)
            logger.warning(
                "job %s preempted by the quota scheduler; SIGTERM "
                "(grace %.1fs)", spec.name, grace,
            )
            for w in ws:
                if w.phase is WorkerPhase.RUNNING:
                    self.launcher.kill(w.key, signal.SIGTERM)
            return

        alive = [w for w in ws if self.launcher.alive(w.key)]
        if alive:
            if time.time() >= job.preempt_deadline:
                for w in alive:  # outlived the checkpoint grace
                    self.launcher.kill(w.key)
            return  # resync passes poll until every process is down

        # Every process is down: release placement and requeue the gang.
        GANG_REQUEUES.labels(reason="Preempted").inc()
        status.push(
            CT.RESTARTING, reason="Preempted",
            message="gang preempted; requeued awaiting quota",
        )
        job.preempt_deadline = None
        # new ports per attempt, like every other gang teardown
        job.coordinator_port = 0
        job.service_ports = {}
        self.jobs.update(uid, job)
        self.scheduler.cancel(uid)  # claims freed; preemption intent cleared
        self._detach_attempt(job, ws)
        for w in ws:
            self.workers.mutate(w.key, _reset_for_preempt)
        logger.warning("job %s preemption complete: gang requeued", spec.name)

    def _detach_attempt(self, job: JobObject, ws: list[WorkerStatus]) -> None:
        """Fully detach a torn-down attempt before its gang goes back to
        Queued: drop heartbeat files and supervisor watch state. Without
        this, a stale beat/progress clock from the dead attempt could fire
        ``progress_timeout`` against a job that is intentionally queued, and
        chaos step-observation would read the old attempt's progress."""
        # lazy: obs.heartbeat imports orchestrator.envwire (cycle otherwise)
        from kubeflow_tpu.obs.heartbeat import heartbeat_path

        for w in ws:
            heartbeat_path(
                self.launcher.workdir(job.spec.uid), w.replica_type, w.index
            ).unlink(missing_ok=True)
        if self.supervisor is not None:
            self.supervisor.forget_job(job.spec.uid)

    def _requeue_gang(self, job: JobObject, lost: list[str]) -> None:
        """A claimed slice vanished (preemption/maintenance — the JobSet
        failure-policy "recreate" case): kill the survivors, release every
        claim, and send the whole gang back through gang admission. The
        job waits as Queued until capacity returns, then relaunches and
        resumes from checkpoint. Deliberately NOT a failure: slice loss is
        infra, so it burns neither ``backoff_limit`` budget nor
        ``restart_count`` (same contract as ``scale``)."""
        spec, status = job.spec, job.status
        GANG_REQUEUES.labels(reason="SliceLost").inc()
        status.push(
            CT.RESTARTING, reason="SliceLost",
            message=f"slice(s) {', '.join(lost)} lost; gang requeued",
        )
        # new ports per attempt, like a failure restart: dying processes
        # may still hold the old ones
        job.coordinator_port = 0
        job.service_ports = {}
        self.jobs.update(spec.uid, job)
        logger.warning(
            "job %s lost slice(s) %s: requeueing gang", spec.name, lost
        )

        ws = [w for _, w in self.workers.list(prefix=f"{spec.uid}/")]
        for w in ws:
            if w.phase is WorkerPhase.RUNNING:
                self.launcher.kill(w.key)
        self._wait_dead(ws)
        # claims released (release() tolerates the missing slice), queue
        # entry dropped — the next sync re-enqueues from desired state
        self.scheduler.cancel(spec.uid)
        self._detach_attempt(job, ws)
        for w in ws:
            self.workers.mutate(w.key, _reset_for_requeue)

    def scale(self, uid: str, replicas: int) -> int:
        """Resize an elastic job's scalable replica group — the HPA-driven
        path of the reference's ElasticPolicy, restart-shaped for SPMD
        (SURVEY.md §2.6 "Elastic DP"): the gang re-forms at the new world
        size and training resumes from checkpoint onto the reshaped mesh.
        Returns the (clamped) size actually applied.

        This only records the target and flags the resize; the reconcile
        loop performs the spec mutation and kill/reset mechanics
        (``_apply_resize``) so they can't race its own passes — mutating
        ``spec.replicas`` here could make a sync already past the resize
        check launch claim-less extra workers, and a worker killed outside
        the loop could be misread as a crash that burns backoff budget.
        """
        job: JobObject | None = self.jobs.get(uid)
        if job is None:
            raise KeyError(f"job {uid} not found")
        if job.spec.elastic is None:
            raise ValueError(f"job {job.spec.name} has no elastic policy")
        if job.status.finished:
            raise ValueError(f"job {job.spec.name} already finished")
        policy = job.spec.elastic
        replicas = policy.clamp(replicas)
        rtype = policy.replica_type
        changed = False

        # Read-modify-write under the store lock: the reconcile thread's
        # _apply_resize clears resize_to under the same lock, so a target
        # recorded here can never be clobbered by an in-flight teardown.
        def _record(j: JobObject) -> None:
            nonlocal changed
            current = (
                j.resize_to
                if j.resize_to is not None
                else j.spec.replicas[rtype].replicas
            )
            if replicas == current:
                return
            # Not a failure: scaling doesn't consume backoff budget.
            j.status.push(
                CT.RESTARTING, reason="Scaled",
                message=f"{rtype} resizing to {replicas}; gang re-forming",
            )
            j.resize_to = replicas
            changed = True

        self.jobs.mutate(uid, _record)
        if changed:
            logger.info(
                "job %s scaling %s to %d replicas", job.spec.name, rtype, replicas
            )
        return replicas

    def _apply_resize(self, job: JobObject) -> None:
        """Reconcile-loop half of ``scale``: apply the new size to the spec,
        tear the old gang down, and drop every record so the next sync
        rebuilds the desired set at the new size with fresh attempt counters
        and gang claims."""
        from kubeflow_tpu.obs.heartbeat import heartbeat_path

        uid = job.spec.uid
        rtype = job.spec.elastic.replica_type
        # Capture the target once: scale() may record a NEWER target while
        # the teardown below (_wait_dead can take seconds) is in flight.
        target = job.resize_to
        job.spec.replicas[rtype] = dataclasses.replace(
            job.spec.replicas[rtype], replicas=target
        )
        ws = [w for _, w in self.workers.list(prefix=f"{uid}/")]
        for w in ws:
            if w.phase is WorkerPhase.RUNNING:
                self.launcher.kill(w.key)
        self._wait_dead(ws)
        # Heartbeat files must go too — attempt counters restart at 0, so a
        # stale pre-scale beat would otherwise read as a hung new attempt.
        for w in ws:
            self.workers.delete(w.key)
            heartbeat_path(
                self.launcher.workdir(uid), w.replica_type, w.index
            ).unlink(missing_ok=True)
        self.scheduler.cancel(uid)

        def _finish(j: JobObject) -> None:
            # Clear only if no newer target was recorded mid-teardown —
            # otherwise leave it set so the next sync applies the new size
            # (the gang is already down; its kills become no-ops).
            if j.resize_to == target:
                j.resize_to = None
            # Force full rewiring at the new size on the next sync.
            j.coordinator_port = 0
            j.service_ports = {}

        self.jobs.mutate(uid, _finish)

    def _rank0_worker(
        self, spec: JobSpec, ws: list[WorkerStatus]
    ) -> WorkerStatus | None:
        ranks = spec.global_ranks()
        for w in ws:
            if ranks.get((w.replica_type, w.index)) == 0:
                return w
        return None

    def _update_replica_statuses(
        self, job: JobObject, ws: list[WorkerStatus]
    ) -> bool:
        """Recompute aggregate counts; True if they changed."""
        agg: dict[str, dict[str, int]] = {}
        for w in ws:
            a = agg.setdefault(
                w.replica_type, {"active": 0, "succeeded": 0, "failed": 0}
            )
            if w.phase is WorkerPhase.RUNNING:
                a["active"] += 1
            elif w.phase is WorkerPhase.SUCCEEDED:
                a["succeeded"] += 1
            elif w.phase is WorkerPhase.FAILED:
                a["failed"] += 1
        changed = agg != job.status.replica_statuses
        job.status.replica_statuses = agg
        return changed

    # ------------------------------------------------------------------ #

    def _finish(
        self, job: JobObject, ctype: CT, reason: str, message: str
    ) -> None:
        job.status.push(ctype, reason=reason, message=message)
        JOBS_FINISHED.labels(condition=ctype.value, reason=reason).inc()
        job.status.completion_time = time.time()
        self._cleanup(
            job,
            kill_all=job.spec.run_policy.clean_pod_policy
            is not CleanPodPolicy.NONE,
        )
        self.jobs.update(job.spec.uid, job)
        logger.info(
            "job %s finished: %s (%s) %s",
            job.spec.name, ctype.value, reason, message,
        )

    def _cleanup(self, job: JobObject, *, kill_all: bool) -> None:
        uid = job.spec.uid
        if kill_all:
            for key, w in self.workers.list(prefix=f"{uid}/"):
                if w.phase is WorkerPhase.RUNNING:
                    self.launcher.kill(key)
        self.scheduler.cancel(uid)

    def _maybe_ttl(self, job: JobObject) -> None:
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is None or job.status.completion_time is None:
            return
        if time.time() - job.status.completion_time >= ttl:
            self._delete_records(job.spec.uid)

    def _delete_records(self, uid: str) -> None:
        for key, w in self.workers.list(prefix=f"{uid}/"):
            if w.phase is WorkerPhase.RUNNING:
                self.launcher.kill(key)
            self.workers.delete(key)
        self.scheduler.cancel(uid)
        self.jobs.delete(uid)

    def _wait_dead(self, ws: list[WorkerStatus]) -> None:
        deadline = time.time() + self.kill_wait_seconds
        while time.time() < deadline:
            if not any(self.launcher.alive(w.key) for w in ws):
                return
            time.sleep(0.02)
        logger.warning("some workers still alive after kill wait")


def _assign(w: WorkerStatus, claim) -> None:
    w.phase = WorkerPhase.SCHEDULED
    w.slice_id = claim.slice_id if claim else None


def _reset_for_restart(w: WorkerStatus) -> None:
    w.phase = WorkerPhase.SCHEDULED
    w.restarts += 1
    w.exit_code = None
    w.pid = None
    w.message = "awaiting gang restart"


def _reset_for_requeue(w: WorkerStatus) -> None:
    # PENDING, not SCHEDULED: the old claims are gone, so the worker must
    # flow through gang admission + placement again before launch.
    w.phase = WorkerPhase.PENDING
    w.restarts += 1
    w.exit_code = None
    w.pid = None
    w.slice_id = None
    w.message = "awaiting requeue after slice loss"


def _reset_for_preempt(w: WorkerStatus) -> None:
    w.phase = WorkerPhase.PENDING
    w.restarts += 1
    w.exit_code = None
    w.pid = None
    w.slice_id = None
    w.message = "awaiting requeue after preemption"
