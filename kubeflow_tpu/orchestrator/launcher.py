"""Process gang launcher — the kubelet analog.

Runs gang members as local subprocesses with per-attempt log files, watches
exits on monitor threads, and reports phase transitions into the worker
store. The reconciler never talks to processes directly; it sees only
``WorkerStatus`` records — the same pod-status contract the reference
controllers consume (SURVEY.md §3.1 "node/kubelet boundary").
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
from pathlib import Path

from kubeflow_tpu.orchestrator.spec import WorkerPhase, WorkerStatus
from kubeflow_tpu.orchestrator.store import ObjectStore

logger = logging.getLogger(__name__)


class ProcessLauncher:
    def __init__(self, worker_store: ObjectStore, base_dir: str | os.PathLike):
        self.workers = worker_store
        self.base_dir = Path(base_dir)
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}

    # ------------------------------------------------------------------ #

    def log_path(self, job_uid: str, rtype: str, index: int, attempt: int) -> Path:
        d = self.base_dir / f"job-{job_uid}" / f"{rtype}-{index}"
        d.mkdir(parents=True, exist_ok=True)
        return d / f"attempt-{attempt}.log"

    def workdir(self, job_uid: str) -> Path:
        d = self.base_dir / f"job-{job_uid}" / "work"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def start(
        self,
        worker: WorkerStatus,
        command: tuple[str, ...],
        env: dict[str, str],
    ) -> None:
        """Spawn one member; updates the store to RUNNING with the pid."""
        key = worker.key
        attempt = worker.restarts
        log_file = self.log_path(
            worker.job_uid, worker.replica_type, worker.index, attempt
        )
        with self._lock:
            with open(log_file, "ab") as f:
                proc = subprocess.Popen(
                    list(command),
                    env=env,
                    stdout=f,
                    stderr=subprocess.STDOUT,
                    cwd=str(self.workdir(worker.job_uid)),
                    start_new_session=True,  # isolate signals per worker
                )
            self._procs[key] = proc

        def _set_running(w: WorkerStatus) -> None:
            w.phase = WorkerPhase.RUNNING
            w.pid = proc.pid
            w.exit_code = None
            w.message = f"attempt {attempt}"

        self.workers.mutate(key, _set_running)
        threading.Thread(
            target=self._monitor, args=(key, proc), daemon=True
        ).start()
        logger.info("started %s pid=%d attempt=%d", key, proc.pid, attempt)

    def _monitor(self, key: str, proc: subprocess.Popen) -> None:
        code = proc.wait()
        if code < 0:
            # Popen reports signal death as -N; normalize to the container
            # convention 128+N that RestartPolicy.EXIT_CODE keys off
            # (SIGKILL → 137), matching the reference's semantics.
            code = 128 - code

        def _finish(w: WorkerStatus) -> None:
            if w.pid != proc.pid:
                return  # superseded by a restart; stale monitor
            w.exit_code = code
            w.phase = (
                WorkerPhase.SUCCEEDED if code == 0 else WorkerPhase.FAILED
            )
            w.message = f"exit code {code}"

        try:
            self.workers.mutate(key, _finish)
        except KeyError:
            pass  # worker record deleted (job TTL'd) while process ran
        with self._lock:
            if self._procs.get(key) is proc:
                del self._procs[key]

    # ------------------------------------------------------------------ #

    def kill(self, key: str, sig: int = signal.SIGKILL) -> bool:
        """Kill a member's process group. The monitor thread records the
        resulting phase (Failed, exit 128+sig) — matching pod-kill
        observability in the reference."""
        with self._lock:
            proc = self._procs.get(key)
        if proc is None or proc.poll() is not None:
            return False
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        return True

    def alive(self, key: str) -> bool:
        with self._lock:
            proc = self._procs.get(key)
        return proc is not None and proc.poll() is None

    def shutdown(self) -> None:
        with self._lock:
            keys = list(self._procs)
        for k in keys:
            self.kill(k)
