"""The JAXJob control plane.

Re-imagines the reference's five Go CRD controllers (SURVEY.md §2.1) as one
Python reconciler engine over an in-process object store:

- ``spec``       — JobSpec / ReplicaSpec / RunPolicy / conditions (the CRD
                   schema, with training-operator semantics).
- ``store``      — namespaced object store with watches (the apiserver/etcd
                   analog; swappable for a real K8s backend later).
- ``resources``  — simulated TPU fleet: slice pools with ICI topology.
- ``gang``       — all-or-nothing topology-aware gang scheduler (the
                   Volcano/coscheduling PodGroup analog).
- ``envwire``    — per-worker env construction (the setPodEnv/TF_CONFIG
                   analog, emitting the jax.distributed contract).
- ``launcher``   — subprocess gang launcher (the kubelet analog).
- ``reconciler`` — the controller loop: desired vs actual workers, restart
                   policies, backoff, deadlines, TTL, conditions.
- ``cluster``    — LocalCluster: store+scheduler+launcher+controller wired
                   together and run on background threads.
- ``client``     — TrainingClient: the Python SDK surface.
"""

from kubeflow_tpu.orchestrator.spec import (  # noqa: F401
    CleanPodPolicy,
    ElasticPolicy,
    JobCondition,
    JobConditionType,
    JobSpec,
    JobStatus,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    SuccessPolicy,
    TPURequest,
)
from kubeflow_tpu.orchestrator.cluster import LocalCluster  # noqa: F401
from kubeflow_tpu.orchestrator.client import TrainingClient  # noqa: F401
