"""Job-kind compatibility: the five reference CRDs on one reconciler.

The reference ships five Go controllers (PyTorchJob/TFJob/MPIJob/
XGBoostJob/PaddleJob) that all delegate to one common engine and differ
only in (a) the CRD manifest shape and (b) the rendezvous env each kind's
framework expects (SURVEY.md §2.1, §2.7). This module is both halves for
the TPU control plane:

- ``from_manifest`` / ``to_manifest``: K8s-style CRD manifests ⇄ JobSpec,
  so reference job YAML translates 1:1 (SURVEY.md §5.6). Accelerator claims
  map ``google.com/tpu`` + ``cloud.google.com/gke-tpu-topology`` (and, for
  migration convenience, ``nvidia.com/gpu`` → chips).
- ``kind_env``: per-kind rendezvous wiring — the ``SetClusterSpec`` /
  ``setPodEnv`` / TF_CONFIG-builder / hostfile analogs (upstream
  [training-operator] pkg/controller.v1/{pytorch/envvar,tensorflow}
  — UNVERIFIED, SURVEY.md §0):

  | kind       | env contract emitted                                     |
  |------------|----------------------------------------------------------|
  | JAXJob     | (none extra — the jax.distributed contract is universal) |
  | PyTorchJob | MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK/LOCAL_RANK + PET_* |
  | TFJob      | TF_CONFIG JSON {cluster:{type:[host:port…]},task:{type,index}} |
  | MPIJob     | hostfile in the job workdir + OMPI_MCA_orte_default_hostfile |
  | XGBoostJob | DMLC_TRACKER_URI/PORT, DMLC_TASK_ID, DMLC_NUM_WORKER     |
  | PaddleJob  | PADDLE_TRAINER_ENDPOINTS/CURRENT_ENDPOINT/TRAINER_ID/NUM |

Every kind ALSO gets the jax.distributed contract, so a payload may use
either stack; torch (CPU) is present in this image, making PyTorchJob-on-
gloo a genuinely runnable path (BASELINE config 1's exact backend).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Mapping

from kubeflow_tpu.orchestrator.spec import (
    CleanPodPolicy,
    ElasticPolicy,
    JobSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    TPURequest,
)

#: kind → the manifest key holding its replica specs
REPLICA_SPEC_KEYS: dict[str, str] = {
    "JAXJob": "jaxReplicaSpecs",
    "PyTorchJob": "pytorchReplicaSpecs",
    "TFJob": "tfReplicaSpecs",
    "MPIJob": "mpiReplicaSpecs",
    "XGBoostJob": "xgbReplicaSpecs",
    "PaddleJob": "paddleReplicaSpecs",
}
KINDS = tuple(REPLICA_SPEC_KEYS)

#: GKE accelerator label values → TPURequest.generation
_ACCEL_GENERATIONS = {
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v4-podslice": "v4",
    "tpu-v6e-slice": "v6e",
}
_GENERATION_ACCELS = {v: k for k, v in _ACCEL_GENERATIONS.items()}


# --------------------------------------------------------------------- #
# manifest → JobSpec
# --------------------------------------------------------------------- #


def from_manifest(manifest: Mapping[str, Any]) -> JobSpec:
    """Translate a reference-style CRD manifest into a JobSpec."""
    kind = manifest.get("kind", "JAXJob")
    if kind not in REPLICA_SPEC_KEYS:
        raise ValueError(f"unknown job kind {kind!r}; expected one of {KINDS}")
    meta = manifest.get("metadata", {})
    spec = manifest.get("spec", {})
    rkey = REPLICA_SPEC_KEYS[kind]
    replica_specs = spec.get(rkey) or spec.get("replicaSpecs")
    if not replica_specs:
        raise ValueError(f"manifest has no {rkey}")

    replicas = {
        rtype.lower(): _replica_from_manifest(rspec)
        for rtype, rspec in replica_specs.items()
    }
    elastic = None
    ep = spec.get("elasticPolicy")
    if ep:
        rtype = ep.get("replicaType", "worker").lower()
        if rtype not in replicas:
            # reference elastic always targets Worker; when a job has no
            # 'worker' group, the scalable group is the non-coordinator one
            # (last in rank order).
            from kubeflow_tpu.orchestrator.spec import COORDINATOR_TYPES

            order = sorted(replicas, key=lambda n: n in COORDINATOR_TYPES)
            rtype = order[0]
        elastic = ElasticPolicy(
            replica_type=rtype,
            min_replicas=int(ep.get("minReplicas", 1)),
            max_replicas=(
                int(ep["maxReplicas"]) if ep.get("maxReplicas") is not None else None
            ),
            heartbeat_timeout_seconds=ep.get("heartbeatTimeoutSeconds"),
            heartbeat_grace_seconds=float(ep.get("heartbeatGraceSeconds", 30.0)),
            progress_timeout_seconds=ep.get("progressTimeoutSeconds"),
            supervised_replica_types=(
                tuple(t.lower() for t in ep["supervisedReplicaTypes"])
                if ep.get("supervisedReplicaTypes") is not None
                else None
            ),
        )

    job = JobSpec(
        name=meta.get("name", "job"),
        replicas=replicas,
        run_policy=_run_policy_from_manifest(spec.get("runPolicy", {})),
        elastic=elastic,
        namespace=meta.get("namespace", "default"),
        labels=dict(meta.get("labels", {})),
        kind=kind,
    )
    if "uid" in meta:
        job.uid = meta["uid"]
    return job


def _replica_from_manifest(rspec: Mapping[str, Any]) -> ReplicaSpec:
    template = rspec.get("template", {})
    pod = template.get("spec", {})
    containers = pod.get("containers", [])
    if not containers:
        raise ValueError("replica template has no containers")
    c = containers[0]
    command = tuple(c.get("command", ())) + tuple(c.get("args", ()))
    env = {e["name"]: str(e.get("value", "")) for e in c.get("env", ())}

    limits = c.get("resources", {}).get("limits", {})
    selector = pod.get("nodeSelector", {})
    chips = int(limits.get("google.com/tpu", limits.get("nvidia.com/gpu", 0)))
    topology = selector.get("cloud.google.com/gke-tpu-topology")
    accel = selector.get("cloud.google.com/gke-tpu-accelerator", "")
    generation = _ACCEL_GENERATIONS.get(accel, "v5e")

    return ReplicaSpec(
        replicas=int(rspec.get("replicas", 1)),
        command=command,
        env=env,
        restart_policy=RestartPolicy(rspec.get("restartPolicy", "OnFailure")),
        tpu=TPURequest(chips=chips, topology=topology, generation=generation),
    )


def _run_policy_from_manifest(rp: Mapping[str, Any]) -> RunPolicy:
    sched = rp.get("schedulingPolicy", {}) or {}
    return RunPolicy(
        backoff_limit=int(rp.get("backoffLimit", 3)),
        active_deadline_seconds=rp.get("activeDeadlineSeconds"),
        ttl_seconds_after_finished=rp.get("ttlSecondsAfterFinished"),
        clean_pod_policy=CleanPodPolicy(rp.get("cleanPodPolicy", "Running")),
        scheduling=SchedulingPolicy(
            gang=True,
            min_available=sched.get("minAvailable"),
            queue=sched.get("queue", "default"),
            priority=int(sched.get("priorityValue", 0)),
            timeout_seconds=sched.get("scheduleTimeoutSeconds"),
        ),
    )


# --------------------------------------------------------------------- #
# JobSpec → manifest (round-trip / export)
# --------------------------------------------------------------------- #


def to_manifest(job: JobSpec) -> dict:
    rkey = REPLICA_SPEC_KEYS[job.kind]
    replica_specs = {}
    for rtype, r in job.replicas.items():
        selector = {}
        limits = {}
        if r.tpu.chips:
            limits["google.com/tpu"] = r.tpu.chips
            selector["cloud.google.com/gke-tpu-accelerator"] = (
                _GENERATION_ACCELS.get(r.tpu.generation, "tpu-v5-lite-podslice")
            )
        if r.tpu.topology:
            selector["cloud.google.com/gke-tpu-topology"] = r.tpu.topology
        container: dict[str, Any] = {
            "name": job.kind.lower().replace("job", ""),
            "command": list(r.command),
            "env": [{"name": k, "value": v} for k, v in r.env.items()],
        }
        if limits:
            container["resources"] = {"limits": limits}
        pod: dict[str, Any] = {"containers": [container]}
        if selector:
            pod["nodeSelector"] = selector
        replica_specs[rtype.capitalize()] = {
            "replicas": r.replicas,
            "restartPolicy": r.restart_policy.value,
            "template": {"spec": pod},
        }

    rp = job.run_policy
    manifest: dict[str, Any] = {
        "apiVersion": "kubeflow.org/v1",
        "kind": job.kind,
        "metadata": {
            "name": job.name,
            "namespace": job.namespace,
            "labels": dict(job.labels),
            "uid": job.uid,
        },
        "spec": {
            rkey: replica_specs,
            "runPolicy": {
                "backoffLimit": rp.backoff_limit,
                "activeDeadlineSeconds": rp.active_deadline_seconds,
                "ttlSecondsAfterFinished": rp.ttl_seconds_after_finished,
                "cleanPodPolicy": rp.clean_pod_policy.value,
                "schedulingPolicy": {
                    "minAvailable": rp.scheduling.min_available,
                    "queue": rp.scheduling.queue,
                    "priorityValue": rp.scheduling.priority,
                    "scheduleTimeoutSeconds": rp.scheduling.timeout_seconds,
                },
            },
        },
    }
    if job.elastic is not None:
        manifest["spec"]["elasticPolicy"] = {
            "replicaType": job.elastic.replica_type.capitalize(),
            "minReplicas": job.elastic.min_replicas,
            "maxReplicas": job.elastic.max_replicas,
            "heartbeatTimeoutSeconds": job.elastic.heartbeat_timeout_seconds,
            "heartbeatGraceSeconds": job.elastic.heartbeat_grace_seconds,
            "progressTimeoutSeconds": job.elastic.progress_timeout_seconds,
            "supervisedReplicaTypes": (
                [t.capitalize() for t in job.elastic.supervised_replica_types]
                if job.elastic.supervised_replica_types is not None
                else None
            ),
        }
    return manifest


# --------------------------------------------------------------------- #
# per-kind rendezvous env (the SetClusterSpec / TF_CONFIG analog)
# --------------------------------------------------------------------- #


def kind_env(
    job: JobSpec,
    rtype: str,
    index: int,
    *,
    host: str,
    service_ports: Mapping[str, int],
    workdir: str,
) -> dict[str, str]:
    """Extra env for this worker per the job's kind. ``service_ports`` maps
    ``"{rtype}-{index}"`` → this gang attempt's per-worker port."""
    if job.kind == "JAXJob":
        return {}  # the universal jax.distributed contract suffices

    ranks = job.global_ranks()
    rank = ranks[(rtype, index)]
    world = job.total_replicas

    # The rank-0 worker's dedicated service port doubles as the framework
    # rendezvous port (c10d store / rabit tracker) — a real allocated port,
    # never a guessed offset off the jax coordinator's. Resolved lazily so
    # kinds that never use it (MPIJob hostfile path) work with empty
    # service_ports.
    def master_port() -> int:
        rank0_type = job.replica_order()[0]
        key = f"{rank0_type}-0"
        if key not in service_ports:
            raise KeyError(
                f"{job.kind} rendezvous needs service_ports[{key!r}] "
                "(the rank-0 worker's allocated port); pass service_ports "
                "to build_worker_env for this kind"
            )
        return service_ports[key]

    if job.kind == "PyTorchJob":
        port = str(master_port())
        return {
            "MASTER_ADDR": host,
            "MASTER_PORT": port,
            "WORLD_SIZE": str(world),
            "RANK": str(rank),
            "LOCAL_RANK": "0",
            # torchrun/elastic (PET = PyTorch Elastic Training) surface
            "PET_NNODES": str(world),
            "PET_NODE_RANK": str(rank),
            "PET_NPROC_PER_NODE": "1",
            "PET_MASTER_ADDR": host,
            "PET_MASTER_PORT": port,
        }

    if job.kind == "TFJob":
        cluster: dict[str, list[str]] = {}
        for rt in job.replica_order():
            cluster[rt] = [
                f"{host}:{service_ports[f'{rt}-{i}']}"
                for i in range(job.replicas[rt].replicas)
            ]
        tf_config = {
            "cluster": cluster,
            "task": {"type": rtype, "index": index},
        }
        return {"TF_CONFIG": json.dumps(tf_config)}

    if job.kind == "MPIJob":
        # Launcher-side hostfile, as the MPIJob controller's ConfigMap; in
        # the local gang every slot is this host. Rewritten (atomically)
        # every wiring pass: an elastic resize changes the slot count, so a
        # keep-if-exists file would advertise the old world size.
        hostfile = Path(workdir) / "hostfile"
        lines = [
            f"{host} slots=1"
            for rt in job.replica_order()
            if rt != "launcher"
            for _ in range(job.replicas[rt].replicas)
        ]
        tmp = hostfile.with_suffix(f".tmp-{rtype}-{index}")
        tmp.write_text("\n".join(lines) + "\n")
        tmp.replace(hostfile)
        return {
            "OMPI_MCA_orte_default_hostfile": str(hostfile),
            "OMPI_ALLOW_RUN_AS_ROOT": "1",
            "OMPI_ALLOW_RUN_AS_ROOT_CONFIRM": "1",
        }

    if job.kind == "XGBoostJob":
        # rabit tracker on the coordinator replica (SURVEY.md §2.1 "DMLC_*").
        # Upstream xgboost-operator contract: DMLC_NUM_WORKER counts every
        # replica (master included) so global-rank task ids stay in
        # 0..NUM_WORKER-1, and the master group's role is 'master' (the
        # ps-lite 'server' role is a different dmlc convention).
        return {
            "DMLC_TRACKER_URI": host,
            "DMLC_TRACKER_PORT": str(master_port()),
            "DMLC_TASK_ID": str(rank),
            "DMLC_NUM_WORKER": str(world),
            "DMLC_ROLE": "master" if rtype == "master" else "worker",
        }

    if job.kind == "PaddleJob":
        endpoints = [
            f"{host}:{service_ports[f'{rt}-{i}']}"
            for rt in job.replica_order()
            for i in range(job.replicas[rt].replicas)
        ]
        return {
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{service_ports[f'{rtype}-{index}']}",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
        }

    raise AssertionError(f"unhandled kind {job.kind!r}")  # guarded in JobSpec
