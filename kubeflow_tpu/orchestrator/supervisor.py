"""Heartbeat supervisor: hung-worker detection for elastic jobs.

Failure detection in the reference stack is three-layered: process exit
(kubelet), liveness probes, and the rendezvous layer's peer-loss abort
(SURVEY.md §5.3). The launcher's monitor threads cover exits; the
``jax.distributed`` coordinator covers peer loss once the world is up. The
remaining hole — a worker that is alive but wedged (deadlocked collective,
stuck host IO, hung before ``initialize``) — is covered here, the liveness
probe analog:

every supervisor pass, for each Running worker of the *elastic replica
group* of a job whose ``ElasticPolicy`` arms a timeout, read the worker's
heartbeat file (``kubeflow_tpu.obs.heartbeat``). Kill on any of:

- ``heartbeat_timeout_seconds``: newest beat of the current attempt is
  older than the timeout (process gone sick without exiting);
- startup grace expired with no beat at all (never came up);
- ``progress_timeout_seconds``: beats keep arriving but the stamped *step*
  has not advanced — the main thread is wedged (deadlocked collective)
  while the writer's background thread keeps the file fresh. Beat age
  alone cannot catch this; step progress can.

The launcher observes exit 137, and the normal gang-restart +
checkpoint-restore machinery does the rest; the supervisor never touches
job state directly. Only groups in ``ElasticPolicy.supervised_types()``
are watched (default: the elastic group) — other groups (an MPI launcher)
may legitimately never beat; add "master" there when the coordinator is a
trainer that beats (PyTorchJob-style).
"""

from __future__ import annotations

import logging
import time

from kubeflow_tpu.obs import heartbeat as hb
from kubeflow_tpu.obs import names, prom
from kubeflow_tpu.orchestrator.launcher import ProcessLauncher
from kubeflow_tpu.orchestrator.spec import WorkerPhase, WorkerStatus
from kubeflow_tpu.orchestrator.store import ObjectStore

logger = logging.getLogger(__name__)

KILLS = prom.REGISTRY.counter(
    names.SUPERVISOR_KILLS_TOTAL,
    "workers killed by the heartbeat supervisor",
    labels=("reason",),
)


class HeartbeatSupervisor:
    def __init__(
        self,
        jobs: ObjectStore,
        workers: ObjectStore,
        launcher: ProcessLauncher,
    ):
        self.jobs = jobs
        self.workers = workers
        self.launcher = launcher
        #: (worker key, attempt, pid) → first time we saw it Running; grace
        #: is measured from here so slow starts aren't executions. The pid
        #: is part of the identity: an elastic resize recreates workers with
        #: attempt 0, and without it the new process would inherit the old
        #: one's clock and be killed mid-startup.
        self._running_since: dict[tuple[str, int, int | None], float] = {}
        #: same tag → (last observed heartbeat step, when it last advanced)
        self._progress: dict[tuple[str, int, int | None], tuple[int, float]] = {}

    def forget_job(self, uid: str) -> None:
        """Drop every watch tag for a job whose attempt was torn down
        (preemption/slice-loss requeue). Without this, grace/progress
        clocks started against the dead attempt would survive into the
        intentionally-Queued job and bill its next attempt for time it
        never ran."""
        prefix = f"{uid}/"
        for tags in (self._running_since, self._progress):
            for tag in [t for t in tags if t[0].startswith(prefix)]:
                del tags[tag]

    def check(self, now: float | None = None) -> list[str]:
        """One supervision pass; returns the keys it killed. ``now`` is a
        ``time.monotonic()`` reading: every clock here (startup grace,
        beat staleness, progress stall) measures a duration, and a
        wall-clock step must never execute a healthy worker."""
        now = time.monotonic() if now is None else now
        killed: list[str] = []
        live: set[tuple[str, int, int | None]] = set()
        for uid, job in self.jobs.list():
            policy = job.spec.elastic
            if policy is None or job.status.finished:
                continue
            if (
                policy.heartbeat_timeout_seconds is None
                and policy.progress_timeout_seconds is None
            ):
                continue
            for _, w in self.workers.list(prefix=f"{uid}/"):
                if w.phase is not WorkerPhase.RUNNING:
                    continue
                if w.replica_type not in policy.supervised_types():
                    continue  # only supervised groups are expected to beat
                tag = (w.key, w.restarts, w.pid)
                live.add(tag)
                since = self._running_since.setdefault(tag, now)
                if self._is_hung(job, w, since, now):
                    if self.launcher.kill(w.key):
                        killed.append(w.key)
        # forget workers that restarted or went away
        for tag in list(self._running_since):
            if tag not in live:
                del self._running_since[tag]
                self._progress.pop(tag, None)
        return killed

    def _is_hung(
        self,
        job,
        w: WorkerStatus,
        running_since: float,
        now: float,
    ) -> bool:
        policy = job.spec.elastic
        path = hb.heartbeat_path(
            self.launcher.workdir(w.job_uid), w.replica_type, w.index
        )
        beat = hb.read_heartbeat(path)
        if beat is None or beat.attempt < w.restarts:
            # No beat from this attempt yet: hung only past the grace.
            if now - running_since > policy.heartbeat_grace_seconds:
                logger.warning(
                    "killing %s: no heartbeat within grace %.1fs",
                    w.key, policy.heartbeat_grace_seconds,
                )
                KILLS.labels(reason="no_heartbeat").inc()
                return True
            return False
        timeout = policy.heartbeat_timeout_seconds
        if timeout is not None and beat.age(now) > timeout:
            logger.warning(
                "killing %s: heartbeat stale %.1fs (timeout %.1fs, step %d)",
                w.key, beat.age(now), timeout, beat.step,
            )
            KILLS.labels(reason="stale_heartbeat").inc()
            return True
        return self._progress_stalled(policy, w, beat, now)

    def _progress_stalled(
        self, policy, w: WorkerStatus, beat: hb.Heartbeat, now: float
    ) -> bool:
        """Fresh beats but a frozen step counter ⇒ the main thread is
        wedged while the writer's daemon thread keeps beating."""
        p_timeout = policy.progress_timeout_seconds
        if p_timeout is None:
            return False
        tag = (w.key, w.restarts, w.pid)
        last = self._progress.get(tag)
        if last is None or beat.step > last[0]:
            self._progress[tag] = (beat.step, now)
            return False
        if now - last[1] > p_timeout:
            logger.warning(
                "killing %s: step stuck at %d for %.1fs (timeout %.1fs)",
                w.key, beat.step, now - last[1], p_timeout,
            )
            KILLS.labels(reason="no_progress").inc()
            return True
        return False
