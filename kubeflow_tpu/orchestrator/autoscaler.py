"""Metrics-driven elastic scaling: the HPA analog for elastic jobs.

Reference analog (SURVEY.md §2.1 PyTorchJob row — "creates HPA for
elastic" — UNVERIFIED, mount empty, §0): upstream's elastic PyTorchJob
materializes a HorizontalPodAutoscaler that resizes the worker group
from metrics. Here the whole control plane is one process, so the HPA is
a small loop: scrape a metric from the job's own stdout (the SAME
zero-SDK regex contract the tuner's metrics collector uses — tune/
metrics.py), run the HPA recommendation formula, and apply it through
``LocalCluster.scale()`` — which re-forms the gang at the new size and
resumes from checkpoint (orchestrator/reconciler.py scale machinery).

HPA semantics kept: proportional recommendation with a tolerance
dead-band, immediate scale-UP, stabilized scale-DOWN (a shrink must hold
for ``scale_down_stabilization_s`` before it is applied), and a resize
cooldown. Two metric modes:

- ``utilization`` — the K8s formula: the metric is per-replica load
  (queue depth per worker, batch backlog); desired =
  ceil(replicas * measured / target).
- ``rate_floor`` — throughput SLO: the metric is an aggregate rate to
  keep at or above ``target`` (steps_per_sec); falling short scales up
  proportionally, exceeding it with headroom scales down.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Any, Callable

from kubeflow_tpu.tune.metrics import collect_from_text, latest

logger = logging.getLogger(__name__)

MODES = ("utilization", "rate_floor")


@dataclasses.dataclass
class AutoscalePolicy:
    target: float
    metric: str = "steps_per_sec"
    mode: str = "rate_floor"
    group: str = "worker"              # the elastic replica group
    min_replicas: int = 1
    max_replicas: int = 8
    tolerance: float = 0.1             # dead-band around target
    scale_down_stabilization_s: float = 30.0
    cooldown_s: float = 10.0           # min seconds between applied resizes

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(f"mode {self.mode!r} not in {MODES}")
        if self.target <= 0:
            raise ValueError("target must be > 0")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min {self.min_replicas} <= max "
                f"{self.max_replicas}"
            )

    def desired(self, replicas: int, measured: float) -> int:
        """The HPA recommendation for the next size (unclamped by fleet —
        ``LocalCluster.scale`` clamps to the job's ElasticPolicy)."""
        if measured <= 0:
            return replicas  # no signal ≠ scale to zero
        if self.mode == "utilization":
            ratio = measured / self.target
        else:  # rate_floor: below target → MORE replicas
            ratio = self.target / measured
        if abs(ratio - 1.0) <= self.tolerance:
            return replicas
        desired = math.ceil(replicas * ratio - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclasses.dataclass
class _JobState:
    policy: AutoscalePolicy
    #: (desired, since) — a pending scale-down recommendation being
    #: stabilized; cleared whenever the recommendation stops shrinking
    down_pending: tuple[int, float] | None = None
    #: -inf, not 0: time.monotonic() starts near 0 on some hosts and the
    #: FIRST resize must never be cooldown-gated
    last_resize: float = float("-inf")
    last_measured: float | None = None


class ElasticAutoscaler:
    """One background loop autoscaling any number of registered jobs.

    ``metric_fn(uid, policy) -> float | None`` overrides the default
    scrape (worker-0 stdout through the tuner's regex collector) — tests
    and richer deployments (Prometheus, engine gauges) inject their own.
    """

    def __init__(
        self,
        cluster: Any,
        *,
        interval_s: float = 5.0,
        metric_fn: Callable[[str, AutoscalePolicy], float | None] | None = None,
    ):
        self.cluster = cluster
        self.interval_s = interval_s
        self.metric_fn = metric_fn or self._scrape_stdout
        self._jobs: dict[str, _JobState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.events: list[dict] = []   # applied resizes, for observability

    # ------------------------------------------------------------------ #

    def register(self, uid: str, policy: AutoscalePolicy) -> None:
        with self._lock:
            self._jobs[uid] = _JobState(policy=policy)

    def unregister(self, uid: str) -> None:
        with self._lock:
            self._jobs.pop(uid, None)

    def _scrape_stdout(self, uid: str, policy: AutoscalePolicy) -> float | None:
        try:
            text = self.cluster.logs(uid, policy.group, 0)
        except (KeyError, OSError):
            return None
        series = collect_from_text(text, policy.metric)
        return latest(series[policy.metric.lower()])

    # ------------------------------------------------------------------ #

    def tick(self, now: float | None = None) -> dict[str, int]:
        """One evaluation pass; returns {uid: replicas} for resizes
        APPLIED this tick. Finished jobs unregister themselves."""
        now = time.monotonic() if now is None else now
        applied: dict[str, int] = {}
        with self._lock:
            jobs = dict(self._jobs)
        for uid, st in jobs.items():
            # LocalCluster returns None for unknown uids (a finished job
            # can be TTL'd out of the store between ticks) — treat gone
            # like finished, never let one dead uid starve the rest
            try:
                status = self.cluster.status(uid)
                job = self.cluster.get(uid)
            except KeyError:
                status = job = None
            if status is None or job is None or status.finished:
                self.unregister(uid)
                continue
            try:
                self._evaluate(uid, st, job, now, applied)
            except Exception:  # noqa: BLE001 — one job's bad policy or a
                # failed scale() must not starve the jobs after it
                logger.exception("autoscale evaluation failed for %s", uid)
        return applied

    def _evaluate(self, uid, st, job, now, applied) -> None:
        pol = st.policy
        replicas = job.spec.replicas[pol.group].replicas
        measured = self.metric_fn(uid, pol)
        st.last_measured = measured
        if measured is None:
            return  # no signal yet (booting, no metrics logged)
        desired = pol.desired(replicas, measured)
        if desired == replicas:
            st.down_pending = None
            return
        if now - st.last_resize < pol.cooldown_s:
            return
        if desired > replicas:
            st.down_pending = None  # up wins immediately (HPA)
        else:
            # stabilize: a shrink must HOLD for the window, and what
            # gets applied is the MOST CONSERVATIVE (largest)
            # recommendation seen during it — K8s HPA's scale-down
            # stabilization: a brief dip must never shrink deeper
            # than the standing load justifies
            if st.down_pending is None:
                st.down_pending = (desired, now)
                return
            held, since = st.down_pending
            held = max(held, desired)
            st.down_pending = (held, since)
            if now - since < pol.scale_down_stabilization_s:
                return
            desired = held
            st.down_pending = None
            if desired >= replicas:
                return
        got = self.cluster.scale(uid, desired)
        st.last_resize = now
        self.events.append(
            {
                "uid": uid, "from": replicas, "to": got,
                "measured": measured, "target": pol.target,
                "at": now,
            }
        )
        logger.info(
            "autoscale %s: %d -> %d (%s=%.4g target=%.4g)",
            uid, replicas, got, pol.metric, measured, pol.target,
        )
        applied[uid] = got

    # ------------------------------------------------------------------ #

    def start(self) -> "ElasticAutoscaler":
        if self._thread is not None:
            return self  # already running: don't leak a second loop
        self._stop.clear()  # a stop()/start() cycle must actually restart
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="kft-autoscaler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a bad tick must not kill the loop
                logger.exception("autoscaler tick failed")

    def __enter__(self) -> "ElasticAutoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
