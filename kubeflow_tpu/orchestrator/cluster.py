"""LocalCluster: the whole control plane wired together on one host.

store + gang scheduler + launcher + controller loop — the single-binary
analog of apiserver + scheduler + kubelet + training-operator for this
clusterless dev environment (SURVEY.md §7 env constraints). The controller
loop is event-driven (store watches) with a periodic resync for time-based
policies (deadlines, TTL, restart backoff), like controller-runtime's
informer resync.
"""

from __future__ import annotations

import logging
import tempfile
import threading
import time

from kubeflow_tpu.obs import names, prom
from kubeflow_tpu.orchestrator.envwire import WiringConfig
from kubeflow_tpu.orchestrator.gang import GangScheduler
from kubeflow_tpu.orchestrator.launcher import ProcessLauncher
from kubeflow_tpu.orchestrator.reconciler import JobController, JobObject
from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.orchestrator.spec import JobSpec, JobStatus
from kubeflow_tpu.orchestrator.store import ObjectStore
from kubeflow_tpu.orchestrator.supervisor import HeartbeatSupervisor
from kubeflow_tpu.orchestrator.webhooks import AdmissionChain

logger = logging.getLogger(__name__)

SYNC_SECONDS = prom.REGISTRY.histogram(
    names.RECONCILE_SECONDS, "controller sync_all wall time"
)
JOBS_BY_PHASE = prom.REGISTRY.gauge(
    names.JOBS_BY_PHASE, "jobs currently in the store by phase",
    labels=("phase",),
)


class LocalCluster:
    def __init__(
        self,
        fleet: Fleet | None = None,
        wiring: WiringConfig | None = None,
        *,
        base_dir: str | None = None,
        persist_path: str | None = None,
        resync_period: float = 0.1,
        restart_backoff_base: float = 1.0,
        admission: "AdmissionChain | None" = None,
        queues=None,
        preemption_grace_seconds: float = 5.0,
    ):
        self.fleet = fleet or Fleet.single_host(chips=8)
        self.wiring = wiring or WiringConfig(platform="cpu_sim")
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="kft-cluster-")
        if persist_path:
            # etcd analog: jobs survive a control-plane restart. Worker
            # records deliberately do NOT — they describe live processes of
            # the dead incarnation; the reconciler re-forms each unfinished
            # job's gang from desired state (training resumes from its own
            # checkpoints, same shape as elastic resize).
            from kubeflow_tpu.orchestrator.store import SqliteObjectStore

            from kubeflow_tpu.orchestrator.spec import JobConditionType as CT

            self.jobs = SqliteObjectStore("jobs", persist_path)
            for uid, job in self.jobs.list():
                if not job.status.finished:
                    job.coordinator_port = 0
                    job.service_ports = {}
                    job.status.push(
                        CT.RESTARTING,
                        reason="ControllerRestart",
                        message="control plane restarted; re-forming gang",
                    )
                    self.jobs.checkpoint(uid)
        else:
            self.jobs = ObjectStore("jobs")
        self.workers = ObjectStore("workers")
        if queues is not None:
            # multi-tenant quota admission (the Kueue analog): queues may
            # be a QueueConfig or an iterable of queue specs/manifests
            from kubeflow_tpu.sched import QueueConfig, QuotaScheduler

            config = (
                queues
                if isinstance(queues, QueueConfig)
                else QueueConfig.from_specs(queues)
            )
            self.scheduler: GangScheduler = QuotaScheduler(
                self.fleet,
                config,
                preemption_grace_seconds=preemption_grace_seconds,
            )
        else:
            self.scheduler = GangScheduler(self.fleet)
        self.launcher = ProcessLauncher(self.workers, self.base_dir)
        self.supervisor = HeartbeatSupervisor(
            self.jobs, self.workers, self.launcher
        )
        self.controller = JobController(
            self.jobs,
            self.workers,
            self.scheduler,
            self.launcher,
            self.wiring,
            restart_backoff_base=restart_backoff_base,
            supervisor=self.supervisor,
        )
        self.admission = admission or AdmissionChain()
        if queues is not None:
            from kubeflow_tpu.orchestrator.webhooks import (
                queue_membership_validator,
            )

            self.admission.add_validator(
                queue_membership_validator(self.scheduler)
            )
        # admission validators read live state (quota usage); serializing
        # admit+create closes the check-then-act window between concurrent
        # submits (concurrent deletes only free capacity, the safe direction)
        self._submit_lock = threading.Lock()
        self._resync = resync_period
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._watches = []

    # ------------------------------------------------------------------ #

    def start(self) -> "LocalCluster":
        if self._thread is not None:
            return self
        for store in (self.jobs, self.workers):
            watch = store.watch()
            self._watches.append(watch)
            threading.Thread(
                target=self._pump, args=(watch,), daemon=True
            ).start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _pump(self, watch) -> None:
        for _ in watch:
            self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self._resync)
            self._wake.clear()
            if self._stop.is_set():
                return
            with SYNC_SECONDS.time():
                self.supervisor.check()
                self.controller.sync_all()
            phases: dict[str, int] = {}
            for _, job in self.jobs.list():
                phases[job.status.phase] = phases.get(job.status.phase, 0) + 1
            # "Unknown" = submitted but not yet reconciled (no conditions)
            for phase in ("Unknown", "Created", "Queued", "Running",
                          "Restarting", "Succeeded", "Failed"):
                JOBS_BY_PHASE.labels(phase=phase).set(phases.get(phase, 0))

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        for w in self._watches:
            w.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.launcher.shutdown()
        close = getattr(self.scheduler, "close", None)
        if close is not None:  # QuotaScheduler: drop its /metrics collector
            close()

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- job API (what the SDK client calls) --------------------------- #

    def submit(self, spec: JobSpec) -> str:
        with self._submit_lock:
            spec = self.admission.admit(spec)
            self.jobs.create(spec.uid, JobObject(spec=spec))
        self._wake.set()
        return spec.uid

    def get(self, uid: str) -> JobObject | None:
        return self.jobs.get(uid)

    def find(self, name: str, namespace: str = "default") -> JobObject | None:
        for _, job in self.jobs.list():
            if job.spec.name == name and job.spec.namespace == namespace:
                return job
        return None

    def status(self, uid: str) -> JobStatus | None:
        job = self.jobs.get(uid)
        return job.status if job else None

    def delete(self, uid: str) -> None:
        job: JobObject | None = self.jobs.get(uid)
        if job is None:
            return
        job.deletion_requested = True
        self.jobs.update(uid, job)
        self._wake.set()

    def wait(
        self,
        uid: str,
        timeout: float = 300.0,
        *,
        poll: float = 0.05,
    ) -> JobStatus:
        """Block until the job reaches a terminal condition (or is deleted)."""
        deadline = time.time() + timeout
        last: JobStatus | None = None
        while time.time() < deadline:
            job = self.jobs.get(uid)
            if job is None:
                if last is not None:
                    return last  # TTL'd away after finishing
                raise KeyError(f"job {uid} not found")
            last = job.status
            if job.status.finished:
                return job.status
            time.sleep(poll)
        raise TimeoutError(
            f"job {uid} not finished after {timeout}s "
            f"(phase {last.phase if last else 'Unknown'})"
        )

    def scale(self, uid: str, replicas: int) -> int:
        """Resize an elastic job's scalable group (HPA analog); the gang
        re-forms at the new size and resumes from checkpoint."""
        applied = self.controller.scale(uid, replicas)
        self._wake.set()
        return applied

    def logs(self, uid: str, rtype: str, index: int, attempt: int | None = None) -> str:
        """Concatenated (or single-attempt) worker logs."""
        w = self.workers.get(f"{uid}/{rtype}-{index}")
        attempts = (
            [attempt]
            if attempt is not None
            else range((w.restarts if w else 0) + 1)
        )
        chunks = []
        for a in attempts:
            p = self.launcher.log_path(uid, rtype, index, a)
            if p.exists():
                chunks.append(p.read_text(errors="replace"))
        return "".join(chunks)
