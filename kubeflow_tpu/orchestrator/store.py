"""In-process object store with watch semantics — the apiserver/etcd analog.

The reference control plane is controller-runtime watching the K8s apiserver
(SURVEY.md §3.1); this dev environment has no cluster (SURVEY.md §0), so the
store is a thread-safe dict with resource versions and watch queues. The
reconciler only sees this interface, so a real K8s-backed implementation can
be swapped in without touching controller logic — the same layering the
envtest strategy exploits (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
from typing import Any, Callable, Iterator


@dataclasses.dataclass(frozen=True)
class Event:
    """Watch event: ADDED / MODIFIED / DELETED."""

    kind: str
    key: str
    obj: Any
    resource_version: int


class ObjectStore:
    """Versioned keyed storage for one object kind, with watches."""

    def __init__(self, name: str = "objects"):
        self.name = name
        self._lock = threading.RLock()
        self._objects: dict[str, Any] = {}
        self._version = itertools.count(1)
        self._watchers: list[queue.SimpleQueue[Event]] = []

    # -- CRUD ----------------------------------------------------------- #

    def create(self, key: str, obj: Any) -> None:
        with self._lock:
            if key in self._objects:
                raise KeyError(f"{self.name}/{key} already exists")
            self._objects[key] = obj
            self._notify("ADDED", key, obj)

    def update(self, key: str, obj: Any) -> None:
        with self._lock:
            if key not in self._objects:
                raise KeyError(f"{self.name}/{key} not found")
            self._objects[key] = obj
            self._notify("MODIFIED", key, obj)

    def upsert(self, key: str, obj: Any) -> None:
        with self._lock:
            kind = "MODIFIED" if key in self._objects else "ADDED"
            self._objects[key] = obj
            self._notify(kind, key, obj)

    def delete(self, key: str) -> Any | None:
        with self._lock:
            obj = self._objects.pop(key, None)
            if obj is not None:
                self._notify("DELETED", key, obj)
            return obj

    def get(self, key: str) -> Any | None:
        with self._lock:
            return self._objects.get(key)

    def list(self, prefix: str = "") -> list[tuple[str, Any]]:
        with self._lock:
            return [
                (k, v) for k, v in self._objects.items() if k.startswith(prefix)
            ]

    def mutate(self, key: str, fn: Callable[[Any], Any | None]) -> Any:
        """Atomic read-modify-write; ``fn`` may mutate in place or return a
        replacement. Returns the stored object."""
        with self._lock:
            if key not in self._objects:
                raise KeyError(f"{self.name}/{key} not found")
            obj = self._objects[key]
            replacement = fn(obj)
            if replacement is not None:
                obj = replacement
            self._objects[key] = obj
            self._notify("MODIFIED", key, obj)
            return obj

    # -- watches -------------------------------------------------------- #

    def watch(self) -> "Watch":
        """New watch; immediately replays current state as ADDED events
        (informer list+watch semantics)."""
        q: queue.SimpleQueue[Event] = queue.SimpleQueue()
        with self._lock:
            version = next(self._version)
            for k, v in self._objects.items():
                q.put(Event("ADDED", k, v, version))
            self._watchers.append(q)
        return Watch(self, q)

    def _unwatch(self, q: queue.SimpleQueue) -> None:
        with self._lock:
            if q in self._watchers:
                self._watchers.remove(q)

    def _notify(self, kind: str, key: str, obj: Any) -> None:
        version = next(self._version)
        for q in self._watchers:
            q.put(Event(kind, key, obj, version))


class Watch:
    def __init__(self, store: ObjectStore, q: queue.SimpleQueue):
        self._store = store
        self._q = q
        self._stopped = threading.Event()

    def __iter__(self) -> Iterator[Event]:
        while not self._stopped.is_set():
            try:
                yield self._q.get(timeout=0.2)
            except queue.Empty:
                continue

    def poll(self, timeout: float = 0.0) -> Event | None:
        try:
            return self._q.get(timeout=timeout) if timeout else self._q.get_nowait()
        except queue.Empty:
            return None

    def stop(self) -> None:
        self._stopped.set()
        self._store._unwatch(self._q)
