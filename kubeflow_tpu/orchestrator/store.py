"""In-process object store with watch semantics — the apiserver/etcd analog.

The reference control plane is controller-runtime watching the K8s apiserver
(SURVEY.md §3.1); this dev environment has no cluster (SURVEY.md §0), so the
store is a thread-safe dict with resource versions and watch queues. The
reconciler only sees this interface, so a real K8s-backed implementation can
be swapped in without touching controller logic — the same layering the
envtest strategy exploits (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
from typing import Any, Callable, Iterator


@dataclasses.dataclass(frozen=True)
class Event:
    """Watch event: ADDED / MODIFIED / DELETED."""

    kind: str
    key: str
    obj: Any
    resource_version: int


class ObjectStore:
    """Versioned keyed storage for one object kind, with watches."""

    def __init__(self, name: str = "objects"):
        self.name = name
        self._lock = threading.RLock()
        self._objects: dict[str, Any] = {}
        self._version = itertools.count(1)
        self._watchers: list[queue.SimpleQueue[Event]] = []

    # -- CRUD ----------------------------------------------------------- #

    def create(self, key: str, obj: Any) -> None:
        with self._lock:
            if key in self._objects:
                raise KeyError(f"{self.name}/{key} already exists")
            self._objects[key] = obj
            self._notify("ADDED", key, obj)

    def update(self, key: str, obj: Any) -> None:
        with self._lock:
            if key not in self._objects:
                raise KeyError(f"{self.name}/{key} not found")
            self._objects[key] = obj
            self._notify("MODIFIED", key, obj)

    def upsert(self, key: str, obj: Any) -> None:
        with self._lock:
            kind = "MODIFIED" if key in self._objects else "ADDED"
            self._objects[key] = obj
            self._notify(kind, key, obj)

    def delete(self, key: str) -> Any | None:
        with self._lock:
            obj = self._objects.pop(key, None)
            if obj is not None:
                self._notify("DELETED", key, obj)
            return obj

    def get(self, key: str) -> Any | None:
        with self._lock:
            return self._objects.get(key)

    def list(self, prefix: str = "") -> list[tuple[str, Any]]:
        with self._lock:
            return [
                (k, v) for k, v in self._objects.items() if k.startswith(prefix)
            ]

    def mutate(self, key: str, fn: Callable[[Any], Any | None]) -> Any:
        """Atomic read-modify-write; ``fn`` may mutate in place or return a
        replacement. Returns the stored object."""
        with self._lock:
            if key not in self._objects:
                raise KeyError(f"{self.name}/{key} not found")
            obj = self._objects[key]
            replacement = fn(obj)
            if replacement is not None:
                obj = replacement
            self._objects[key] = obj
            self._notify("MODIFIED", key, obj)
            return obj

    # -- watches -------------------------------------------------------- #

    def watch(self) -> "Watch":
        """New watch; immediately replays current state as ADDED events
        (informer list+watch semantics)."""
        q: queue.SimpleQueue[Event] = queue.SimpleQueue()
        with self._lock:
            version = next(self._version)
            for k, v in self._objects.items():
                q.put(Event("ADDED", k, v, version))
            self._watchers.append(q)
        return Watch(self, q)

    def _unwatch(self, q: queue.SimpleQueue) -> None:
        with self._lock:
            if q in self._watchers:
                self._watchers.remove(q)

    def _notify(self, kind: str, key: str, obj: Any) -> None:
        version = next(self._version)
        for q in self._watchers:
            q.put(Event(kind, key, obj, version))


class SqliteObjectStore(ObjectStore):
    """Write-through persistent ObjectStore (sqlite) — the etcd half of the
    apiserver analog.

    Reference analog: the K8s control plane survives controller restarts
    because CRs live in etcd; Katib additionally keeps observations in
    MySQL via the db-manager (SURVEY.md §2.3 "DB manager" row). Here every
    ADDED/MODIFIED/DELETED is mirrored to sqlite under the store lock, and
    a fresh process re-loads the surviving objects — the reconciler then
    re-forms gangs from desired state (checkpoint-restart semantics, the
    same shape as elastic resize).

    In-process reference semantics are preserved: reads return the live
    objects from memory; sqlite only matters at (re)start. Values are
    pickled — these are our own dataclasses, not untrusted input.
    """

    def __init__(self, name: str, path: str):
        super().__init__(name)
        import os
        import sqlite3

        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS objects ("
            " store TEXT NOT NULL, key TEXT NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (store, key))"
        )
        self._db.commit()
        import pickle

        self._pickle = pickle
        for key, blob in self._db.execute(
            "SELECT key, value FROM objects WHERE store=?", (name,)
        ).fetchall():
            self._objects[key] = pickle.loads(blob)

    def _notify(self, kind: str, key: str, obj: Any) -> None:
        # called under self._lock by every CRUD path
        if kind == "DELETED":
            self._db.execute(
                "DELETE FROM objects WHERE store=? AND key=?", (self.name, key)
            )
        else:
            self._db.execute(
                "INSERT OR REPLACE INTO objects (store, key, value)"
                " VALUES (?,?,?)",
                (self.name, key, self._pickle.dumps(obj)),
            )
        self._db.commit()
        super()._notify(kind, key, obj)

    def checkpoint(self, key: str) -> None:
        """Persist the current in-memory state of ``key`` (for callers that
        mutated a stored object in place without going through update)."""
        with self._lock:
            if key in self._objects:
                self._notify("MODIFIED", key, self._objects[key])

    def close(self) -> None:
        with self._lock:
            self._db.close()


class Watch:
    def __init__(self, store: ObjectStore, q: queue.SimpleQueue):
        self._store = store
        self._q = q
        self._stopped = threading.Event()

    def __iter__(self) -> Iterator[Event]:
        while not self._stopped.is_set():
            try:
                yield self._q.get(timeout=0.2)
            except queue.Empty:
                continue

    def poll(self, timeout: float = 0.0) -> Event | None:
        try:
            return self._q.get(timeout=timeout) if timeout else self._q.get_nowait()
        except queue.Empty:
            return None

    def stop(self) -> None:
        self._stopped.set()
        self._store._unwatch(self._q)
