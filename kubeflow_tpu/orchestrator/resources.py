"""Simulated TPU fleet: slice pools with ICI topology.

The reference delegates capacity to the K8s scheduler over ``nvidia.com/gpu``
counts; TPU capacity is *topological* — you claim whole slices (or sub-slice
chip groups) whose shape determines the ICI mesh. This model is what the gang
scheduler places against (SURVEY.md §7 "hard part 1": a rigorous simulated
capacity model, since no real cluster exists in this env).

A fleet is a set of ``SlicePool``s (e.g. 4 slices of v5e-16 "4x4"). A claim
asks for ``chips`` within one slice (sub-slice claim, like GKE multi-host
sub-scheduling) or a whole slice by topology string.
"""

from __future__ import annotations

import dataclasses
import math
import threading

from kubeflow_tpu.core.mesh import slice_topology


def parse_topology(s: str) -> tuple[int, ...]:
    """'4x4' → (4, 4)."""
    try:
        dims = tuple(int(p) for p in s.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"bad topology string {s!r}") from e
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"bad topology string {s!r}")
    return dims


def topology_chips(s: str) -> int:
    return math.prod(parse_topology(s))


@dataclasses.dataclass
class Slice:
    """One TPU pod slice: an atomic ICI domain."""

    slice_id: str
    topology: str
    generation: str = "v5e"
    free_chips: int = dataclasses.field(default=-1)

    def __post_init__(self) -> None:
        if self.free_chips < 0:
            self.free_chips = self.total_chips

    @property
    def total_chips(self) -> int:
        return topology_chips(self.topology)


@dataclasses.dataclass(frozen=True)
class Claim:
    """A granted placement: chips on one slice."""

    slice_id: str
    chips: int


class Fleet:
    """Thread-safe capacity ledger over a set of slices.

    ``claim_gang`` is all-or-nothing: every member's chips must fit
    simultaneously (each member within a single slice — chips never span
    slices, because a jax process's local devices are one ICI domain), else
    nothing is allocated. This is the PodGroup minMember semantic.
    """

    def __init__(self, slices: list[Slice] | None = None):
        self._lock = threading.Lock()
        self._slices: dict[str, Slice] = {}
        for s in slices or []:
            self.add_slice(s)

    @classmethod
    def homogeneous(
        cls, num_slices: int, topology: str, generation: str = "v5e"
    ) -> "Fleet":
        return cls(
            [
                Slice(f"slice-{i}", topology, generation)
                for i in range(num_slices)
            ]
        )

    @classmethod
    def single_host(cls, chips: int = 1, generation: str = "v5e") -> "Fleet":
        topo = "x".join(str(d) for d in slice_topology(chips))
        return cls([Slice("slice-0", topo, generation)])

    def add_slice(self, s: Slice) -> None:
        with self._lock:
            if s.slice_id in self._slices:
                raise KeyError(f"slice {s.slice_id} already registered")
            self._slices[s.slice_id] = s

    def remove_slice(self, slice_id: str) -> None:
        """Simulate slice loss (preemption/maintenance) — claims vanish."""
        with self._lock:
            self._slices.pop(slice_id, None)

    def has_slice(self, slice_id: str) -> bool:
        """False once a slice is lost — what the reconciler polls to turn
        an invisible capacity change into a gang requeue."""
        with self._lock:
            return slice_id in self._slices

    # ------------------------------------------------------------------ #

    def snapshot(self) -> dict[str, Slice]:
        with self._lock:
            return {k: dataclasses.replace(v) for k, v in self._slices.items()}

    def total_chips(self) -> int:
        with self._lock:
            return sum(s.total_chips for s in self._slices.values())

    def free_chips(self) -> int:
        with self._lock:
            return sum(s.free_chips for s in self._slices.values())

    def _plan_locked(
        self,
        free: dict[str, int],
        requests: list[tuple[int, str | None, str]],
    ) -> list[Claim] | None:
        """Placement planning over a free-chips map (lock held); mutates
        ``free`` as it places. Returns claims in request order, or None."""
        # Place whole-slice (topology) requests first: they are the most
        # constrained.
        order = sorted(
            range(len(requests)),
            key=lambda i: (requests[i][1] is None, -requests[i][0]),
        )
        placed: dict[int, Claim] = {}
        for i in order:
            chips, topo, gen = requests[i]
            candidates = []
            for sid, s in self._slices.items():
                if s.generation != gen:
                    continue
                if topo is not None:
                    if s.topology != topo or free[sid] != s.total_chips:
                        continue
                    need = s.total_chips
                else:
                    need = chips
                    if free[sid] < need:
                        continue
                candidates.append((free[sid], sid, need))
            if not candidates:
                return None
            # Best-fit: least free capacity that still fits.
            candidates.sort()
            _, sid, need = candidates[0]
            free[sid] -= need
            placed[i] = Claim(sid, need)
        return [placed[i] for i in range(len(requests))]

    def claim_gang(
        self,
        requests: list[tuple[int, str | None, str]],
    ) -> list[Claim] | None:
        """Try to place a gang atomically.

        ``requests``: per member ``(chips, topology_or_None, generation)``.
        A topology request means "a whole slice of exactly this shape".
        Placement is best-fit (fullest feasible slice first) to reduce
        fragmentation across concurrent gangs (the Katib 16-trial pressure
        case, SURVEY.md §3.4). Returns claims in request order, or None.
        """
        with self._lock:
            free = {k: s.free_chips for k, s in self._slices.items()}
            claims = self._plan_locked(free, requests)
            if claims is None:
                return None
            for c in claims:
                self._slices[c.slice_id].free_chips -= c.chips
            return claims

    def fits_gang(
        self,
        requests: list[tuple[int, str | None, str]],
        extra_free: "dict[str, int] | None" = None,
    ) -> bool:
        """Feasibility probe: would the gang place if ``extra_free`` chips
        (slice_id → chips) were returned to their slices first? Claims
        nothing — this is how the quota scheduler asks "would evicting
        these victims actually make room for the preemptor"."""
        with self._lock:
            free = {k: s.free_chips for k, s in self._slices.items()}
            for sid, chips in (extra_free or {}).items():
                s = self._slices.get(sid)
                if s is not None:
                    free[sid] = min(free[sid] + chips, s.total_chips)
            return self._plan_locked(free, requests) is not None

    def release(self, claims: list[Claim]) -> None:
        with self._lock:
            for c in claims:
                s = self._slices.get(c.slice_id)
                if s is not None:
                    s.free_chips = min(s.free_chips + c.chips, s.total_chips)
