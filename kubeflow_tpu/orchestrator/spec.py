"""JAXJob API types: the CRD schema with training-operator semantics.

Mirrors the semantics of the reference's common API types so that reference
job manifests translate 1:1 (SURVEY.md §2.1 "API types" row; upstream analog
[training-operator] pkg/apis/kubeflow.org/v1/common_types.go — UNVERIFIED,
mount empty, SURVEY.md §0):

- ``ReplicaSpec``     ← replicas / template / restartPolicy (incl. ExitCode)
- ``RunPolicy``       ← backoffLimit, activeDeadlineSeconds, cleanPodPolicy,
                        ttlSecondsAfterFinished, schedulingPolicy
- ``JobCondition``    ← Created / Running / Restarting / Succeeded / Failed
- ``SchedulingPolicy``← gang minAvailable / queue / priority

TPU-first additions: ``TPURequest`` (accelerator topology replaces
``nvidia.com/gpu`` counts) and ``MeshSpec`` embedding (the job carries its
logical parallelism layout, SURVEY.md §2.6).
"""

from __future__ import annotations

import dataclasses
import enum
import time
import uuid
from typing import Any, Mapping

from kubeflow_tpu.core.mesh import MeshSpec


class RestartPolicy(str, enum.Enum):
    """Per-replica restart semantics (training-operator compatible).

    ``EXIT_CODE``: retry only on *retryable* exit codes — 128+ (signal
    deaths: SIGKILL=137, SIGSEGV=139, preemption) — and permanently fail on
    1..127 (application errors). This is the subtle state machine SURVEY.md
    §7 "hard part 5" warns about.
    """

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"

    def should_restart(self, exit_code: int) -> bool:
        if self is RestartPolicy.ALWAYS:
            return True
        if self is RestartPolicy.ON_FAILURE:
            return exit_code != 0
        if self is RestartPolicy.EXIT_CODE:
            return exit_code >= 128
        return False


class CleanPodPolicy(str, enum.Enum):
    """Which workers to kill when the job finishes."""

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class SuccessPolicy(str, enum.Enum):
    """When the job counts as Succeeded.

    ``ALL_WORKERS`` is the right default for SPMD gangs (every jax process
    exits together); ``RANK0`` mirrors PyTorchJob's master-exit semantics.
    """

    ALL_WORKERS = "AllWorkers"
    RANK0 = "Rank0"


class JobConditionType(str, enum.Enum):
    CREATED = "Created"
    QUEUED = "Queued"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class JobCondition:
    type: JobConditionType
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition: float = dataclasses.field(default_factory=time.time)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["type"] = self.type.value
        return d


@dataclasses.dataclass(frozen=True)
class TPURequest:
    """Accelerator claim: the ``google.com/tpu`` + topology-selector analog.

    ``topology`` is an ICI shape string ("2x4"); ``chips`` per worker. The
    gang scheduler matches these against slice pools (SURVEY.md §3.1 "TPU
    mapping": ``google.com/tpu: 4`` + ``gke-tpu-topology`` selector).
    """

    chips: int = 0
    topology: str | None = None
    generation: str = "v5e"

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "TPURequest":
        return cls(
            chips=int(d.get("chips", 0)),
            topology=d.get("topology"),
            generation=d.get("generation", "v5e"),
        )


@dataclasses.dataclass(frozen=True)
class SchedulingPolicy:
    """Gang scheduling knobs (the Volcano PodGroup analog).

    Under plain gang scheduling ``queue`` is an opaque label (independent
    FIFO lanes). When the cluster runs the quota scheduler
    (``LocalCluster(queues=...)``), ``queue`` names a **LocalQueue**
    (``kubeflow_tpu.sched``) whose ClusterQueue's chip quota admits the
    gang — unknown names are rejected at submission, and ``priority``
    additionally orders preemption victim selection.
    """

    gang: bool = True
    min_available: int | None = None  # default: all replicas
    queue: str = "default"
    priority: int = 0
    timeout_seconds: float | None = None  # fail if unschedulable this long

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "SchedulingPolicy":
        return cls(
            gang=bool(d.get("gang", True)),
            min_available=d.get("min_available"),
            queue=d.get("queue", "default"),
            priority=int(d.get("priority", 0)),
            timeout_seconds=d.get("timeout_seconds"),
        )


@dataclasses.dataclass(frozen=True)
class ElasticPolicy:
    """Checkpoint-restart elasticity (the PyTorchJob ElasticPolicy analog).

    JAX SPMD worlds are static, so elasticity is restart-shaped (SURVEY.md
    §5.3): ``scale()`` re-forms the gang at a new size and training resumes
    from the latest checkpoint onto the reshaped mesh (Orbax re-shards on
    load). ``min/max_replicas`` bound the scalable replica group;
    ``heartbeat_timeout_seconds`` arms the supervisor's hung-worker
    detection (exit deaths need no heartbeat — the launcher sees those).
    """

    replica_type: str = "worker"
    min_replicas: int = 1
    max_replicas: int | None = None
    heartbeat_timeout_seconds: float | None = None
    heartbeat_grace_seconds: float = 30.0
    #: kill a worker whose heartbeat *step* hasn't advanced in this long —
    #: catches a wedged main thread whose background beat thread still runs
    #: (deadlocked collective). Budget for the longest expected XLA compile.
    progress_timeout_seconds: float | None = None
    #: replica groups the heartbeat supervisor watches. None → just the
    #: elastic group. Include the coordinator group ("master") when its
    #: payload is a trainer that beats (PyTorchJob-style); leave out groups
    #: that legitimately never beat (an MPI launcher).
    supervised_replica_types: tuple[str, ...] | None = None

    def __post_init__(self) -> None:
        if self.max_replicas is not None and self.min_replicas > self.max_replicas:
            raise ValueError(
                f"min_replicas {self.min_replicas} > max_replicas "
                f"{self.max_replicas}"
            )

    def supervised_types(self) -> tuple[str, ...]:
        if self.supervised_replica_types is not None:
            return self.supervised_replica_types
        return (self.replica_type,)

    def clamp(self, replicas: int) -> int:
        lo = max(1, self.min_replicas)
        hi = self.max_replicas if self.max_replicas is not None else replicas
        return max(lo, min(replicas, hi))

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ElasticPolicy":
        return cls(
            replica_type=d.get("replica_type", "worker"),
            min_replicas=int(d.get("min_replicas", 1)),
            max_replicas=(
                int(d["max_replicas"]) if d.get("max_replicas") is not None else None
            ),
            heartbeat_timeout_seconds=d.get("heartbeat_timeout_seconds"),
            heartbeat_grace_seconds=float(d.get("heartbeat_grace_seconds", 30.0)),
            progress_timeout_seconds=d.get("progress_timeout_seconds"),
            supervised_replica_types=(
                tuple(d["supervised_replica_types"])
                if d.get("supervised_replica_types") is not None
                else None
            ),
        )


@dataclasses.dataclass(frozen=True)
class RunPolicy:
    backoff_limit: int = 3
    active_deadline_seconds: float | None = None
    ttl_seconds_after_finished: float | None = None
    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.RUNNING
    scheduling: SchedulingPolicy = dataclasses.field(default_factory=SchedulingPolicy)
    success_policy: SuccessPolicy = SuccessPolicy.ALL_WORKERS

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "RunPolicy":
        return cls(
            backoff_limit=int(d.get("backoff_limit", 3)),
            active_deadline_seconds=d.get("active_deadline_seconds"),
            ttl_seconds_after_finished=d.get("ttl_seconds_after_finished"),
            clean_pod_policy=CleanPodPolicy(d.get("clean_pod_policy", "Running")),
            scheduling=SchedulingPolicy.from_dict(d.get("scheduling", {})),
            success_policy=SuccessPolicy(d.get("success_policy", "AllWorkers")),
        )


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """One replica group (Master/Worker analog).

    ``command`` is the container entrypoint (argv). ``env`` is merged under
    the orchestrator's wiring (the wiring wins). ``tpu`` is the accelerator
    claim used for gang placement and for ``JAX_LOCAL_DEVICE_IDS``
    partitioning in CPU simulation.
    """

    replicas: int = 1
    command: tuple[str, ...] = ()
    env: Mapping[str, str] = dataclasses.field(default_factory=dict)
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE
    tpu: TPURequest = dataclasses.field(default_factory=TPURequest)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ReplicaSpec":
        return cls(
            replicas=int(d.get("replicas", 1)),
            command=tuple(d.get("command", ())),
            env=dict(d.get("env", {})),
            restart_policy=RestartPolicy(d.get("restart_policy", "OnFailure")),
            tpu=TPURequest.from_dict(d.get("tpu", {})),
        )

    def to_dict(self) -> dict:
        return {
            "replicas": self.replicas,
            "command": list(self.command),
            "env": dict(self.env),
            "restart_policy": self.restart_policy.value,
            "tpu": dataclasses.asdict(self.tpu),
        }


#: Replica-type names that carry rank 0 (coordinator placement), in priority
#: order — mirrors master/chief-first ordering in the reference controllers.
COORDINATOR_TYPES = ("master", "chief", "launcher")


@dataclasses.dataclass
class JobSpec:
    """The JAXJob object (metadata + spec)."""

    name: str
    replicas: dict[str, ReplicaSpec]
    run_policy: RunPolicy = dataclasses.field(default_factory=RunPolicy)
    elastic: ElasticPolicy | None = None
    mesh: MeshSpec | None = None
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    uid: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:12])
    #: CRD kind this job translates (JAXJob | PyTorchJob | TFJob | MPIJob |
    #: XGBoostJob | PaddleJob); selects the rendezvous env contract the
    #: workers get (kubeflow_tpu.orchestrator.kinds).
    kind: str = "JAXJob"

    def __post_init__(self) -> None:
        from kubeflow_tpu.orchestrator.kinds import KINDS

        if self.kind not in KINDS:
            raise ValueError(f"unknown kind {self.kind!r}; expected {KINDS}")
        if not self.replicas:
            raise ValueError("JobSpec needs at least one replica group")
        for rtype, spec in self.replicas.items():
            if spec.replicas < 1:
                raise ValueError(f"replica group {rtype!r} needs replicas >= 1")
            if not spec.command:
                raise ValueError(f"replica group {rtype!r} needs a command")
        if self.elastic is not None:
            if self.elastic.replica_type not in self.replicas:
                raise ValueError(
                    f"elastic.replica_type {self.elastic.replica_type!r} "
                    "is not a replica group of this job"
                )
            unknown = [
                t
                for t in self.elastic.supervised_types()
                if t not in self.replicas
            ]
            if unknown:
                # a typo here would silently disarm hung-worker detection
                raise ValueError(
                    f"supervised_replica_types {unknown} are not replica "
                    f"groups of this job (groups: {sorted(self.replicas)})"
                )

    # ------------------------------------------------------------------ #

    @property
    def total_replicas(self) -> int:
        return sum(r.replicas for r in self.replicas.values())

    def replica_order(self) -> list[str]:
        """Deterministic rank order: coordinator types first, then others
        in insertion order — so rank 0 lands on the master analog."""
        names = list(self.replicas)
        return sorted(
            names,
            key=lambda n: (
                COORDINATOR_TYPES.index(n.lower())
                if n.lower() in COORDINATOR_TYPES
                else len(COORDINATOR_TYPES)
            ),
        )

    def global_ranks(self) -> dict[tuple[str, int], int]:
        """(replica_type, index) → global process id."""
        out: dict[tuple[str, int], int] = {}
        rank = 0
        for rtype in self.replica_order():
            for i in range(self.replicas[rtype].replicas):
                out[(rtype, i)] = rank
                rank += 1
        return out

    # ------------------------------------------------------------------ #

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "JobSpec":
        mesh = d.get("mesh")
        return cls(
            name=d["name"],
            replicas={
                k: ReplicaSpec.from_dict(v) for k, v in d["replicas"].items()
            },
            run_policy=RunPolicy.from_dict(d.get("run_policy", {})),
            elastic=(
                ElasticPolicy.from_dict(d["elastic"])
                if d.get("elastic") is not None
                else None
            ),
            mesh=MeshSpec.from_dict(mesh) if mesh else None,
            namespace=d.get("namespace", "default"),
            labels=dict(d.get("labels", {})),
            uid=d.get("uid", uuid.uuid4().hex[:12]),
            kind=d.get("kind", "JAXJob"),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "replicas": {k: v.to_dict() for k, v in self.replicas.items()},
            "run_policy": {
                "backoff_limit": self.run_policy.backoff_limit,
                "active_deadline_seconds": self.run_policy.active_deadline_seconds,
                "ttl_seconds_after_finished": self.run_policy.ttl_seconds_after_finished,
                "clean_pod_policy": self.run_policy.clean_pod_policy.value,
                "scheduling": dataclasses.asdict(self.run_policy.scheduling),
                "success_policy": self.run_policy.success_policy.value,
            },
            "elastic": (
                dataclasses.asdict(self.elastic) if self.elastic else None
            ),
            "mesh": self.mesh.to_dict() if self.mesh else None,
            "namespace": self.namespace,
            "labels": dict(self.labels),
            "uid": self.uid,
            "kind": self.kind,
        }


class WorkerPhase(str, enum.Enum):
    """Pod-phase analog for a gang worker process."""

    PENDING = "Pending"       # created, not yet placed
    SCHEDULED = "Scheduled"   # gang-admitted, awaiting start
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class WorkerStatus:
    """The "pod" record the reconciler diffs against (desired vs actual)."""

    job_uid: str
    replica_type: str
    index: int
    phase: WorkerPhase = WorkerPhase.PENDING
    restarts: int = 0
    exit_code: int | None = None
    pid: int | None = None
    slice_id: str | None = None  # placement decision from the gang scheduler
    message: str = ""

    @property
    def key(self) -> str:
        return worker_key(self.job_uid, self.replica_type, self.index)

    @property
    def finished(self) -> bool:
        return self.phase in (WorkerPhase.SUCCEEDED, WorkerPhase.FAILED)


def worker_key(job_uid: str, rtype: str, index: int) -> str:
    return f"{job_uid}/{rtype}-{index}"


@dataclasses.dataclass
class JobStatus:
    """Aggregated status (the CRD .status analog)."""

    conditions: list[JobCondition] = dataclasses.field(default_factory=list)
    replica_statuses: dict[str, dict[str, int]] = dataclasses.field(default_factory=dict)
    restart_count: int = 0
    start_time: float | None = None
    completion_time: float | None = None

    #: Phase precedence (most decisive first) and which condition types a
    #: newly-True condition switches off — the reference's one-entry-per-type
    #: convention with status flags.
    _PRECEDENCE = (
        JobConditionType.FAILED,
        JobConditionType.SUCCEEDED,
        JobConditionType.RESTARTING,
        JobConditionType.RUNNING,
        JobConditionType.QUEUED,
        JobConditionType.CREATED,
    )
    _EXCLUSIVE = {
        JobConditionType.RUNNING: (
            JobConditionType.RESTARTING,
            JobConditionType.QUEUED,
        ),
        JobConditionType.RESTARTING: (JobConditionType.RUNNING,),
        JobConditionType.SUCCEEDED: (
            JobConditionType.RUNNING,
            JobConditionType.RESTARTING,
            JobConditionType.QUEUED,
        ),
        JobConditionType.FAILED: (
            JobConditionType.RUNNING,
            JobConditionType.RESTARTING,
            JobConditionType.QUEUED,
        ),
    }

    def condition(self) -> JobCondition | None:
        """The active condition of highest precedence (the job's phase)."""
        active = {c.type: c for c in self.conditions if c.status}
        for ctype in self._PRECEDENCE:
            if ctype in active:
                return active[ctype]
        return None

    def has_condition(self, ctype: JobConditionType) -> bool:
        return any(c.type is ctype for c in self.conditions)

    @property
    def phase(self) -> str:
        c = self.condition()
        return c.type.value if c else "Unknown"

    @property
    def finished(self) -> bool:
        return self.has_condition(JobConditionType.SUCCEEDED) or self.has_condition(
            JobConditionType.FAILED
        )

    def push(self, ctype: JobConditionType, reason: str = "", message: str = "") -> bool:
        """Set condition ``ctype`` True (one entry per type, K8s-style),
        switching off mutually exclusive conditions. True if this flipped
        state (a real transition)."""
        entry = next((c for c in self.conditions if c.type is ctype), None)
        transitioned = entry is None or not entry.status or entry.reason != reason
        if entry is None:
            self.conditions.append(
                JobCondition(type=ctype, reason=reason, message=message)
            )
        elif transitioned:
            entry.status = True
            entry.reason = reason
            entry.message = message
            entry.last_transition = time.time()
        if transitioned:
            for other in self._EXCLUSIVE.get(ctype, ()):
                for c in self.conditions:
                    if c.type is other and c.status:
                        c.status = False
                        c.last_transition = time.time()
        return transitioned
