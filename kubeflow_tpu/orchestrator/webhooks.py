"""Admission webhooks: mutating + validating hooks on job submission.

The reference guards its CRDs with validating admission webhooks per kind
(SURVEY.md §2.1 "Webhooks"; upstream analog [training-operator]
pkg/webhooks/ — UNVERIFIED, SURVEY.md §0) and mutates pods with the
PodDefaults webhook (§2.5). In the clusterless control plane the same
contract is a hook chain run inside ``LocalCluster.submit``: mutators first
(in registration order, each returning a possibly-new JobSpec), then
validators (raise ``AdmissionError`` to reject). Platform policies —
quotas, pod defaults — plug in here rather than patching the reconciler.
"""

from __future__ import annotations

from typing import Callable

from kubeflow_tpu.orchestrator.spec import JobSpec

Mutator = Callable[[JobSpec], JobSpec]
Validator = Callable[[JobSpec], None]


class AdmissionError(ValueError):
    """Job rejected at admission; the message is the user-facing reason."""


class AdmissionChain:
    def __init__(
        self,
        mutators: list[Mutator] | None = None,
        validators: list[Validator] | None = None,
    ):
        self.mutators: list[Mutator] = list(mutators or ())
        self.validators: list[Validator] = [validate_scheduling]
        self.validators.extend(validators or ())

    def add_mutator(self, m: Mutator) -> None:
        self.mutators.append(m)

    def add_validator(self, v: Validator) -> None:
        self.validators.append(v)

    def admit(self, spec: JobSpec) -> JobSpec:
        for m in self.mutators:
            out = m(spec)
            if out is not None:
                spec = out
        for v in self.validators:
            v(spec)
        return spec


def validate_scheduling(spec: JobSpec) -> None:
    """Built-in sanity the reference webhooks enforce: gang minAvailable
    can't exceed the replica total."""
    sched = spec.run_policy.scheduling
    if (
        sched.min_available is not None
        and sched.min_available > spec.total_replicas
    ):
        raise AdmissionError(
            f"schedulingPolicy.minAvailable {sched.min_available} exceeds "
            f"total replicas {spec.total_replicas}"
        )


def queue_membership_validator(scheduler) -> Validator:
    """When quota scheduling is on, every job must name a **known**
    LocalQueue — a typo'd queue would otherwise sit Queued forever with no
    signal (the Kueue webhook's localQueueName validation). Installed by
    ``LocalCluster`` whenever it is built with ``queues=``."""

    def validate(spec: JobSpec) -> None:
        queue = spec.run_policy.scheduling.queue
        if not scheduler.knows_queue(queue):
            raise AdmissionError(
                f"unknown LocalQueue {queue!r}: known queues are "
                f"{scheduler.known_queues()} — declare a LocalQueue "
                "manifest for it or submit with an existing --queue"
            )

    return validate
