"""Gang scheduler: all-or-nothing, topology-aware, queued.

The Volcano/coscheduling PodGroup analog the reference creates when
``RunPolicy.schedulingPolicy`` is set (SURVEY.md §2.1 "Gang scheduling" row):
a job's workers are admitted only when the whole gang fits the fleet, so 16
concurrent tuning trials (SURVEY.md §3.4) can't deadlock holding partial
slice claims.

Policy: per-queue strict priority, then FIFO; no backfill past a blocked
higher-priority gang within the same queue (prevents starvation of large
gangs — the failure mode strict gang scheduling exists to avoid). Separate
queues (``SchedulingPolicy.queue``) are independent.

Multi-tenant admission lives above this class:
``kubeflow_tpu.sched.scheduler.QuotaScheduler`` subclasses it, treating
``PodGroup.queue`` as a LocalQueue name and replacing ``try_schedule`` with
quota-aware admission (nominal quotas, cohort borrowing, preemption) while
reusing the same all-or-nothing ``Fleet.claim_gang`` topology claims via
``_admit_locked``.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from kubeflow_tpu.orchestrator.resources import Claim, Fleet


@dataclasses.dataclass
class PodGroup:
    """One gang awaiting (or holding) placement."""

    job_uid: str
    # per member, in worker order: (worker_key, chips, topology|None, generation)
    requests: list[tuple[str, int, str | None, str]]
    queue: str = "default"
    priority: int = 0
    timeout_seconds: float | None = None
    enqueued_at: float = dataclasses.field(default_factory=time.time)
    claims: dict[str, Claim] | None = None  # worker_key → claim once admitted

    @property
    def admitted(self) -> bool:
        return self.claims is not None

    @property
    def expired(self) -> bool:
        return (
            self.timeout_seconds is not None
            and not self.admitted
            and time.time() - self.enqueued_at > self.timeout_seconds
        )


class GangScheduler:
    def __init__(self, fleet: Fleet):
        self.fleet = fleet
        self._lock = threading.Lock()
        self._pending: dict[str, PodGroup] = {}  # job_uid → group
        self._held: dict[str, PodGroup] = {}     # admitted, claims held

    def enqueue(self, group: PodGroup) -> None:
        with self._lock:
            if group.job_uid in self._pending or group.job_uid in self._held:
                return
            self._pending[group.job_uid] = group

    def cancel(self, job_uid: str) -> None:
        """Drop from queue and release claims if held."""
        with self._lock:
            self._pending.pop(job_uid, None)
            group = self._held.pop(job_uid, None)
        if group and group.claims:
            self.fleet.release(list(group.claims.values()))

    def claims_for(self, job_uid: str) -> dict[str, Claim] | None:
        with self._lock:
            g = self._held.get(job_uid)
            return dict(g.claims) if g and g.claims else None

    def timed_out(self) -> list[PodGroup]:
        with self._lock:
            out = [g for g in self._pending.values() if g.expired]
            for g in out:
                del self._pending[g.job_uid]
            return out

    def _admit_locked(self, g: PodGroup) -> bool:
        """Claim fleet capacity for one pending gang (lock held); on
        success fills ``g.claims`` and moves it pending → held."""
        claims = self.fleet.claim_gang(
            [(chips, topo, gen) for _, chips, topo, gen in g.requests]
        )
        if claims is None:
            return False
        g.claims = {
            g.requests[i][0]: claims[i] for i in range(len(claims))
        }
        del self._pending[g.job_uid]
        self._held[g.job_uid] = g
        return True

    def try_schedule(self) -> list[PodGroup]:
        """Admit every gang that fits, honoring per-queue priority+FIFO
        without skipping a blocked head-of-line gang. Returns newly admitted
        groups (claims filled in)."""
        admitted: list[PodGroup] = []
        with self._lock:
            by_queue: dict[str, list[PodGroup]] = {}
            for g in self._pending.values():
                by_queue.setdefault(g.queue, []).append(g)
            for q, groups in by_queue.items():
                groups.sort(key=lambda g: (-g.priority, g.enqueued_at))
                for g in groups:
                    if not self._admit_locked(g):
                        break  # head-of-line blocks the rest of this queue
                    admitted.append(g)
        return admitted

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)
