"""Per-worker environment construction — the ``setPodEnv`` analog.

Where the reference's controllers write ``MASTER_ADDR/RANK/WORLD_SIZE``
(PyTorchJob), ``TF_CONFIG`` (TFJob) or hostfiles (MPIJob), the JAXJob
control plane writes the ``jax.distributed`` contract consumed by
``kubeflow_tpu.core.distributed`` plus job-identity vars (SURVEY.md §2.7
"c10d TCPStore" row; upstream analog [training-operator]
pkg/controller.v1/pytorch/envvar.go — UNVERIFIED, SURVEY.md §0).

Two wiring modes:

- ``tpu``:     workers inherit the host's TPU env (real chips).
- ``cpu_sim``: workers get JAX_PLATFORMS=cpu and a virtual device count —
  the gloo-on-kind analog (SURVEY.md §4) for exercising real cross-process
  collectives on one host.
"""

from __future__ import annotations

import dataclasses
import os
import socket

from kubeflow_tpu.core import distributed as dist
from kubeflow_tpu.orchestrator.spec import JobSpec

ENV_JOB_NAME = "KFT_JOB_NAME"
ENV_JOB_UID = "KFT_JOB_UID"
ENV_NAMESPACE = "KFT_NAMESPACE"
ENV_REPLICA_TYPE = "KFT_REPLICA_TYPE"
ENV_REPLICA_INDEX = "KFT_REPLICA_INDEX"
ENV_WORKDIR = "KFT_WORKDIR"
ENV_ATTEMPT = "KFT_ATTEMPT"


def free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass(frozen=True)
class WiringConfig:
    """How a job's gang is wired on this host."""

    platform: str = "cpu_sim"  # "cpu_sim" | "tpu"
    devices_per_worker: int = 1
    coordinator_host: str = "127.0.0.1"

    def __post_init__(self) -> None:
        if self.platform not in ("cpu_sim", "tpu"):
            raise ValueError(f"unknown platform {self.platform!r}")


def build_worker_env(
    job: JobSpec,
    rtype: str,
    index: int,
    *,
    coordinator_port: int,
    wiring: WiringConfig,
    workdir: str,
    attempt: int,
    service_ports: dict[str, int] | None = None,
    base_env: dict[str, str] | None = None,
) -> dict[str, str]:
    """Full child environment for one gang member."""
    from kubeflow_tpu.orchestrator import kinds

    env = dict(os.environ if base_env is None else base_env)
    env.update(job.replicas[rtype].env)
    # kind-specific rendezvous contract (MASTER_ADDR / TF_CONFIG / DMLC_* /
    # hostfile / PADDLE_*) — the per-kind controllers' env wiring, unified.
    env.update(
        kinds.kind_env(
            job,
            rtype,
            index,
            host=wiring.coordinator_host,
            service_ports=service_ports or {},
            workdir=workdir,
        )
    )

    ranks = job.global_ranks()
    rank = ranks[(rtype, index)]
    world = job.total_replicas

    env.update(
        {
            dist.ENV_COORDINATOR_ADDRESS: f"{wiring.coordinator_host}:{coordinator_port}",
            dist.ENV_NUM_PROCESSES: str(world),
            dist.ENV_PROCESS_ID: str(rank),
            ENV_JOB_NAME: job.name,
            ENV_JOB_UID: job.uid,
            ENV_NAMESPACE: job.namespace,
            ENV_REPLICA_TYPE: rtype,
            ENV_REPLICA_INDEX: str(index),
            ENV_WORKDIR: workdir,
            ENV_ATTEMPT: str(attempt),
            # GKE-parity topology surface (SURVEY.md §5.8)
            dist.ENV_TPU_WORKER_ID: str(rank),
            dist.ENV_TPU_WORKER_HOSTNAMES: ",".join(
                [wiring.coordinator_host] * world
            ),
            "PYTHONUNBUFFERED": "1",
        }
    )

    if wiring.platform == "cpu_sim":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            p
            for p in flags.split()
            if not p.startswith("--xla_force_host_platform_device_count")
        )
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{wiring.devices_per_worker}"
        ).strip()
        # Disable this image's axon sitecustomize TPU registration in
        # children: one real chip can't be shared by a gang, and the
        # registration would override JAX_PLATFORMS (see tests/conftest.py).
        for k in list(env):
            if k.startswith("PALLAS_AXON"):
                del env[k]
    return env
