"""Versioned model store: sqlite records + content-addressed blobs.

Reference analog: [model-registry]'s MLMD backing store (UNVERIFIED,
mount empty, SURVEY.md §0) — RegisteredModel/ModelVersion rows over
MySQL, artifacts by URI. Here the artifact bytes live IN the store,
content-addressed by sha256 under ``<root>/blobs/``, so registering the
same checkpoint twice (two pipeline runs, a retrain that converged to
identical weights) costs one copy — and the serving path can pin the
exact digest it resolved (`fetcher.canonicalize`).

Concurrency follows ``tune/db.py``: one connection, one lock, explicit
commits; the stage state machine (`stages.py`) runs inside the same
lock via :meth:`ModelStore.tx` so promotion/rollback is atomic.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import sqlite3
import threading
import time
import uuid

from kubeflow_tpu.registry.spec import (
    STAGES,
    LineageEdge,
    ModelVersion,
    RegisteredModel,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS models (
    name        TEXT PRIMARY KEY,
    description TEXT NOT NULL DEFAULT '',
    created     REAL NOT NULL,
    updated     REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS versions (
    model      TEXT NOT NULL REFERENCES models(name),
    version    INTEGER NOT NULL,
    sha256     TEXT NOT NULL,
    stage      TEXT NOT NULL DEFAULT 'none',
    source_uri TEXT NOT NULL DEFAULT '',
    created    REAL NOT NULL,
    metadata   TEXT NOT NULL DEFAULT '{}',
    PRIMARY KEY (model, version)
);
CREATE TABLE IF NOT EXISTS blobs (
    sha256  TEXT PRIMARY KEY,
    is_dir  INTEGER NOT NULL,
    size    INTEGER NOT NULL,
    created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS lineage (
    model    TEXT NOT NULL,
    version  INTEGER NOT NULL,
    kind     TEXT NOT NULL,
    ref      TEXT NOT NULL,
    metadata TEXT NOT NULL DEFAULT '{}',
    created  REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_lineage_mv ON lineage(model, version);
CREATE TABLE IF NOT EXISTS aliases (
    model   TEXT NOT NULL,
    alias   TEXT NOT NULL,
    version INTEGER NOT NULL,
    PRIMARY KEY (model, alias)
);
CREATE TABLE IF NOT EXISTS promotions (
    id           INTEGER PRIMARY KEY AUTOINCREMENT,
    model        TEXT NOT NULL,
    stage        TEXT NOT NULL,
    from_version INTEGER,
    to_version   INTEGER NOT NULL,
    ts           REAL NOT NULL
);
"""


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def content_hash(path: str) -> tuple[str, bool, int]:
    """(digest, is_dir, total_bytes) for a file or directory payload.

    A file hashes to its byte sha256 — the same digest
    ``serve.storage.download(expected_sha256=...)`` pins, so a resolved
    version verifies end-to-end. A directory hashes its sorted
    (relpath, file-sha256) manifest."""
    if os.path.isfile(path):
        return _sha256_file(path), False, os.path.getsize(path)
    entries = []
    total = 0
    for root, _, files in os.walk(path):
        for name in sorted(files):
            p = os.path.join(root, name)
            entries.append((os.path.relpath(p, path), _sha256_file(p)))
            total += os.path.getsize(p)
    h = hashlib.sha256()
    for rel, digest in sorted(entries):
        h.update(rel.encode())
        h.update(b"\0")
        h.update(digest.encode())
        h.update(b"\0")
    return h.hexdigest(), True, total


class ModelStore:
    """``<root>/registry.sqlite`` + ``<root>/blobs/<sha256>`` payloads."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.blob_root = os.path.join(self.root, "blobs")
        os.makedirs(self.blob_root, exist_ok=True)
        self._db = sqlite3.connect(
            os.path.join(self.root, "registry.sqlite"),
            check_same_thread=False,
        )
        self._db.executescript(_SCHEMA)
        self._db.commit()
        self._lock = threading.RLock()

    @contextlib.contextmanager
    def tx(self):
        """One atomic unit: lock + commit, rollback on any exception.
        The stage machine (`stages.py`) composes multi-row updates here."""
        with self._lock:
            try:
                yield self._db
                self._db.commit()
            except BaseException:
                self._db.rollback()
                raise

    # -- models --------------------------------------------------------- #

    def create_model(self, name: str, description: str = "") -> RegisteredModel:
        if not name or name.startswith(".") or any(
            c in name for c in ("@", "\\", "\n", "\r")
        ):
            raise ValueError(f"invalid model name {name!r}")
        now = time.time()
        with self.tx() as db:
            db.execute(
                "INSERT INTO models (name, description, created, updated)"
                " VALUES (?,?,?,?)"
                " ON CONFLICT(name) DO UPDATE SET updated=excluded.updated,"
                " description=CASE WHEN excluded.description != ''"
                " THEN excluded.description ELSE models.description END",
                (name, description, now, now),
            )
        return self.get_model(name)

    def get_model(self, name: str) -> RegisteredModel:
        with self._lock:
            row = self._db.execute(
                "SELECT name, description, created, updated FROM models"
                " WHERE name=?",
                (name,),
            ).fetchone()
            if row is None:
                raise KeyError(f"model {name!r} not registered")
            latest = self._db.execute(
                "SELECT MAX(version) FROM versions WHERE model=?", (name,)
            ).fetchone()[0]
            stages = dict(
                self._db.execute(
                    "SELECT stage, version FROM versions WHERE model=?"
                    " AND stage IN ('staging','production')",
                    (name,),
                ).fetchall()
            )
        return RegisteredModel(
            name=row[0], description=row[1], created=row[2], updated=row[3],
            latest_version=latest or 0, stages=stages,
        )

    def list_models(self) -> list[RegisteredModel]:
        with self._lock:
            names = [
                r[0]
                for r in self._db.execute(
                    "SELECT name FROM models ORDER BY name"
                ).fetchall()
            ]
        return [self.get_model(n) for n in names]

    # -- blobs ---------------------------------------------------------- #

    def blob_path(self, sha256: str) -> str:
        p = os.path.join(self.blob_root, sha256)
        if not os.path.exists(p):
            raise FileNotFoundError(f"blob {sha256} missing from {self.blob_root}")
        return p

    def _ingest_blob(self, path: str) -> tuple[str, bool, int]:
        """Copy ``path`` into the blob store, deduplicating by content:
        an already-present digest costs zero bytes. Returns
        (sha256, is_dir, size)."""
        digest, is_dir, size = content_hash(path)
        dest = os.path.join(self.blob_root, digest)
        if not os.path.exists(dest):
            staging = os.path.join(
                self.blob_root, f".staging-{uuid.uuid4().hex[:8]}"
            )
            try:
                if is_dir:
                    shutil.copytree(path, staging)
                else:
                    shutil.copy2(path, staging)
                # a racing ingest of the same content may beat us: either
                # replace wins, the bytes are identical
                os.replace(staging, dest)
            finally:
                if os.path.isdir(staging):
                    shutil.rmtree(staging, ignore_errors=True)
                elif os.path.exists(staging):
                    os.remove(staging)
        return digest, is_dir, size

    # -- versions ------------------------------------------------------- #

    def register_version(
        self,
        name: str,
        path: str,
        *,
        source_uri: str = "",
        metadata: dict | None = None,
        stage: str | None = None,
        lineage: list[tuple[str, str, dict]] | None = None,
    ) -> ModelVersion:
        """Ingest a file/directory payload as the next version of
        ``name`` (the model record is created on first use). ``lineage``
        rows are (kind, ref, metadata) producer edges; ``stage`` promotes
        atomically right after registration."""
        if not os.path.exists(path):
            raise FileNotFoundError(f"model payload {path!r} does not exist")
        self.create_model(name)
        digest, is_dir, size = self._ingest_blob(path)
        now = time.time()
        with self.tx() as db:
            db.execute(
                "INSERT OR IGNORE INTO blobs (sha256, is_dir, size, created)"
                " VALUES (?,?,?,?)",
                (digest, int(is_dir), size, now),
            )
            version = (
                db.execute(
                    "SELECT COALESCE(MAX(version), 0) + 1 FROM versions"
                    " WHERE model=?",
                    (name,),
                ).fetchone()[0]
            )
            db.execute(
                "INSERT INTO versions"
                " (model, version, sha256, stage, source_uri, created,"
                "  metadata) VALUES (?,?,?,?,?,?,?)",
                (name, version, digest, "none", source_uri, now,
                 json.dumps(metadata or {})),
            )
            db.execute(
                "UPDATE models SET updated=? WHERE name=?", (now, name)
            )
            for kind, ref, meta in lineage or []:
                db.execute(
                    "INSERT INTO lineage"
                    " (model, version, kind, ref, metadata, created)"
                    " VALUES (?,?,?,?,?,?)",
                    (name, version, kind, ref, json.dumps(meta or {}), now),
                )
        mv = self.get_version(name, version)
        if stage is not None:
            from kubeflow_tpu.registry import stages as _stages

            _stages.promote(self, name, version, stage)
            mv = self.get_version(name, version)
        return mv

    def _version_from_row(self, row) -> ModelVersion:
        model, version, sha, stage, uri, created, meta = row
        return ModelVersion(
            model=model, version=version, sha256=sha, stage=stage,
            source_uri=uri, created=created, metadata=json.loads(meta),
        )

    def get_version(self, name: str, version: int) -> ModelVersion:
        with self._lock:
            row = self._db.execute(
                "SELECT model, version, sha256, stage, source_uri, created,"
                " metadata FROM versions WHERE model=? AND version=?",
                (name, int(version)),
            ).fetchone()
        if row is None:
            raise KeyError(f"model {name!r} has no version {version}")
        return self._version_from_row(row)

    def list_versions(self, name: str) -> list[ModelVersion]:
        self.get_model(name)  # KeyError on unknown model
        with self._lock:
            rows = self._db.execute(
                "SELECT model, version, sha256, stage, source_uri, created,"
                " metadata FROM versions WHERE model=? ORDER BY version",
                (name,),
            ).fetchall()
        return [self._version_from_row(r) for r in rows]

    def resolve(self, name: str, selector: str | None = None) -> ModelVersion:
        """Resolve a mutable selector to a concrete version:

        - ``None`` / ``"latest"`` → highest version number
        - a stage name (``production``/``staging``) → its current holder
        - a custom alias → its pinned version
        - ``"v3"`` / ``"3"`` → that exact version
        """
        model = self.get_model(name)
        if selector is None or selector == "latest":
            if not model.latest_version:
                raise KeyError(f"model {name!r} has no versions")
            return self.get_version(name, model.latest_version)
        if selector in STAGES:
            if selector not in model.stages:
                raise KeyError(
                    f"model {name!r} has no version in stage {selector!r}"
                )
            return self.get_version(name, model.stages[selector])
        with self._lock:
            row = self._db.execute(
                "SELECT version FROM aliases WHERE model=? AND alias=?",
                (name, selector),
            ).fetchone()
        if row is not None:
            return self.get_version(name, row[0])
        digits = selector[1:] if selector.startswith("v") else selector
        if digits.isdigit():
            return self.get_version(name, int(digits))
        raise KeyError(
            f"cannot resolve {name!r}@{selector!r}: not a stage, alias, or"
            " version number"
        )

    def set_alias(self, name: str, alias: str, version: int) -> None:
        """Pin a custom alias (``champion``, ``canary``…) to a version.
        Stage names are reserved — they are managed by promotion."""
        if alias in STAGES or alias == "latest" or not alias:
            raise ValueError(f"alias {alias!r} is reserved")
        self.get_version(name, version)  # KeyError if missing
        with self.tx() as db:
            db.execute(
                "INSERT OR REPLACE INTO aliases (model, alias, version)"
                " VALUES (?,?,?)",
                (name, alias, int(version)),
            )

    # -- lineage -------------------------------------------------------- #

    def add_lineage(
        self, name: str, version: int, kind: str, ref: str,
        metadata: dict | None = None,
    ) -> None:
        self.get_version(name, version)
        with self.tx() as db:
            db.execute(
                "INSERT INTO lineage"
                " (model, version, kind, ref, metadata, created)"
                " VALUES (?,?,?,?,?,?)",
                (name, int(version), kind, ref, json.dumps(metadata or {}),
                 time.time()),
            )

    def lineage_of(self, name: str, version: int) -> list[LineageEdge]:
        self.get_version(name, version)
        with self._lock:
            rows = self._db.execute(
                "SELECT kind, ref, metadata, created FROM lineage"
                " WHERE model=? AND version=? ORDER BY created, rowid",
                (name, int(version)),
            ).fetchall()
        return [
            LineageEdge(kind=k, ref=r, metadata=json.loads(m), created=c)
            for k, r, m, c in rows
        ]

    def promotion_history(self, name: str, stage: str) -> list[dict]:
        with self._lock:
            rows = self._db.execute(
                "SELECT id, from_version, to_version, ts FROM promotions"
                " WHERE model=? AND stage=? ORDER BY id",
                (name, stage),
            ).fetchall()
        return [
            {"id": i, "from_version": f, "to_version": t, "ts": ts}
            for i, f, t, ts in rows
        ]

    def close(self) -> None:
        with self._lock:
            self._db.close()


# --------------------------------------------------------------------------- #
# process-default store — what `registry://` fetches resolve against
# --------------------------------------------------------------------------- #

_DEFAULT: ModelStore | None = None


def set_default_store(store: ModelStore | None) -> None:
    global _DEFAULT
    _DEFAULT = store


def default_store() -> ModelStore:
    """The processwide registry: set explicitly (tests, embedded servers)
    or implied by ``KFT_REGISTRY_ROOT`` (CLI, serving containers)."""
    global _DEFAULT
    if _DEFAULT is None:
        root = os.environ.get("KFT_REGISTRY_ROOT")
        if not root:
            raise RuntimeError(
                "no model registry configured: call"
                " registry.set_default_store(ModelStore(root)) or set"
                " KFT_REGISTRY_ROOT"
            )
        _DEFAULT = ModelStore(root)
    return _DEFAULT
