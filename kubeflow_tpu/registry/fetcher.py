"""``registry://name@selector`` — serving straight from the registry.

Reference analog: KServe's ``storage-initializer`` resolving a model URI
before the server starts. The registry scheme adds one governance step:
the mutable selector (``@production``, ``@staging``, an alias, or
nothing for latest) is **canonicalized to an immutable version + content
hash at download time** (:func:`canonicalize`), so

- the bytes a server loads are exactly the bytes the promoted version
  hashed to (single-file payloads are further pinned end-to-end via
  ``expected_sha256``), and
- a later promotion changes what the NEXT download resolves — it can
  never mutate a cached copy under a running server (the cache key is
  the immutable ``registry://name@vN`` spelling).
"""

from __future__ import annotations

import os
import shutil

from kubeflow_tpu.registry.spec import ModelVersion
from kubeflow_tpu.registry.store import ModelStore, default_store


def parse_ref(uri: str) -> tuple[str, str | None]:
    """``registry://name[@selector]`` → (name, selector|None). The name
    may contain ``/`` (pipelines register as ``<pipeline>/<output>``)."""
    if not uri.startswith("registry://"):
        raise ValueError(f"not a registry uri: {uri!r}")
    rest = uri[len("registry://"):]
    name, sep, selector = rest.partition("@")
    if not name:
        raise ValueError(f"registry uri {uri!r} has no model name")
    return name, (selector if sep else None) or None


def resolve(uri: str, store: ModelStore | None = None) -> ModelVersion:
    name, selector = parse_ref(uri)
    return (store or default_store()).resolve(name, selector)


def canonicalize(
    uri: str, store: ModelStore | None = None
) -> tuple[str, str | None]:
    """Mutable ref → (immutable ``registry://name@vN`` uri, pinned sha256
    for single-file payloads, None for directories). ``serve.storage``
    calls this before its cache check so stage moves are never masked by
    a stale cached copy."""
    store = store or default_store()
    mv = resolve(uri, store)
    blob = store.blob_path(mv.sha256)
    return mv.ref, (None if os.path.isdir(blob) else mv.sha256)


def _fetch_registry(uri: str, staging: str) -> str:
    """Scheme fetcher for ``serve.storage.download``: materialise the
    resolved version's blob into the staging dir."""
    store = default_store()
    mv = resolve(uri, store)
    src = store.blob_path(mv.sha256)
    # one filesystem name per (model, version): distinct versions must not
    # collide in a shared model dir, and "/" in model names must not
    # escape it
    name = f"{mv.model.replace('/', '-')}-v{mv.version}"
    staged = os.path.join(staging, name)
    if os.path.isdir(src):
        shutil.copytree(src, staged)
    else:
        shutil.copy2(src, staged)
    return staged


def register() -> None:
    from kubeflow_tpu.serve import storage

    storage.register_fetcher("registry", _fetch_registry)


register()
