"""Model Registry REST API.

Reference analog: [model-registry]'s REST surface (UNVERIFIED, mount
empty, SURVEY.md §0) — upstream serves ``/api/model_registry/v1alpha3``
with registered_models / model_versions resources; route shapes here
follow that naming (the `pipelines/api.py` idiom: aiohttp on a daemon
thread, KeyError→404 / ValueError→400 guard).

Registration POSTs take a server-local ``path`` — this platform runs
in-process, so "upload" is an ingest of a path the trainer already
wrote. Promotion and rollback are POST actions mirroring the
``:promote`` / ``:rollback`` CLI verbs.
"""

from __future__ import annotations

from kubeflow_tpu.obs.webhost import ThreadedAiohttpServer
from kubeflow_tpu.registry import stages as _stages
from kubeflow_tpu.registry.store import ModelStore

_PFX = "/api/model_registry/v1alpha3"


class ModelRegistryAPIServer(ThreadedAiohttpServer):
    """The write path for the registry: everything the dashboard's
    read-only ``/api/models`` view cannot do."""

    thread_name = "kft-model-registry"

    def __init__(
        self, store: ModelStore, *, host: str = "127.0.0.1", port: int = 0
    ):
        super().__init__(host=host, port=port)
        self.store = store

    def _make_app(self):
        from aiohttp import web

        def guard(fn):
            """KeyError → 404, ValueError/TypeError → 400 — the same
            error contract as the pipelines API."""

            async def h(request):
                try:
                    return web.json_response(await fn(request))
                except KeyError as e:
                    return web.json_response({"error": str(e)}, status=404)
                except (ValueError, TypeError, FileNotFoundError) as e:
                    return web.json_response(
                        {"error": f"{type(e).__name__}: {e}"}, status=400
                    )

            return h

        async def list_models(_request):
            return {
                "registered_models": [
                    m.to_dict() for m in self.store.list_models()
                ]
            }

        async def create_model(request):
            body = await request.json()
            if "name" not in body:
                raise ValueError("registered model needs 'name'")
            m = self.store.create_model(
                body["name"], body.get("description", "")
            )
            return m.to_dict()

        async def get_model(request):
            return self.store.get_model(request.match_info["name"]).to_dict()

        async def list_versions(request):
            name = request.match_info["name"]
            return {
                "model_versions": [
                    v.to_dict() for v in self.store.list_versions(name)
                ]
            }

        async def create_version(request):
            name = request.match_info["name"]
            body = await request.json()
            if "path" not in body:
                raise ValueError(
                    "version registration needs 'path' (server-local"
                    " payload to ingest)"
                )
            lineage = [
                (e["kind"], e["ref"], e.get("metadata", {}))
                for e in body.get("lineage", [])
            ]
            mv = self.store.register_version(
                name,
                body["path"],
                source_uri=body.get("source_uri", ""),
                metadata=body.get("metadata"),
                stage=body.get("stage"),
                lineage=lineage,
            )
            return mv.to_dict()

        async def get_version(request):
            return self.store.get_version(
                request.match_info["name"], int(request.match_info["v"])
            ).to_dict()

        async def promote(request):
            body = await request.json()
            if "stage" not in body:
                raise ValueError("promote needs 'stage'")
            return _stages.promote(
                self.store,
                request.match_info["name"],
                int(request.match_info["v"]),
                body["stage"],
            )

        async def rollback(request):
            return _stages.rollback(
                self.store,
                request.match_info["name"],
                request.match_info["stage"],
            )

        async def lineage(request):
            name = request.match_info["name"]
            v = int(request.match_info["v"])
            return {
                "lineage": [
                    e.to_dict() for e in self.store.lineage_of(name, v)
                ]
            }

        async def healthz(_request):
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_get("/healthz", healthz)
        app.router.add_get(f"{_PFX}/registered_models", guard(list_models))
        app.router.add_post(f"{_PFX}/registered_models", guard(create_model))
        # model names may contain "/" (pipeline-scoped registrations) —
        # accept them with a greedy path segment
        app.router.add_get(
            f"{_PFX}/registered_models/{{name:.+}}/versions/{{v:\\d+}}/lineage",
            guard(lineage),
        )
        app.router.add_post(
            f"{_PFX}/registered_models/{{name:.+}}/versions/{{v:\\d+}}:promote",
            guard(promote),
        )
        app.router.add_post(
            f"{_PFX}/registered_models/{{name:.+}}/stages/{{stage}}:rollback",
            guard(rollback),
        )
        app.router.add_get(
            f"{_PFX}/registered_models/{{name:.+}}/versions/{{v:\\d+}}",
            guard(get_version),
        )
        app.router.add_get(
            f"{_PFX}/registered_models/{{name:.+}}/versions",
            guard(list_versions),
        )
        app.router.add_post(
            f"{_PFX}/registered_models/{{name:.+}}/versions",
            guard(create_version),
        )
        app.router.add_get(
            f"{_PFX}/registered_models/{{name:.+}}", guard(get_model)
        )
        return app
