"""Stage lifecycle: ``none → staging → production → archived``.

Reference analog: model-registry / MLflow stage transitions. Two
invariants the rest of the platform leans on:

- **Exclusivity** — at most one version per model holds ``staging`` or
  ``production`` at any instant, so ``registry://name@production`` is a
  total function. Promotion demotes the previous holder to ``archived``
  in the same transaction.
- **Reversibility** — every promotion appends to a history log, and
  :func:`rollback` restores the previous holder atomically (the
  "which model was in production before this one, put it back" path).
"""

from __future__ import annotations

import time

from kubeflow_tpu.registry.spec import EXCLUSIVE_STAGES, STAGES
from kubeflow_tpu.registry.store import ModelStore


def _require_version(db, model: str, version: int) -> str:
    row = db.execute(
        "SELECT stage FROM versions WHERE model=? AND version=?",
        (model, int(version)),
    ).fetchone()
    if row is None:
        raise KeyError(f"model {model!r} has no version {version}")
    return row[0]


def promote(store: ModelStore, model: str, version: int, stage: str) -> dict:
    """Move ``version`` into ``stage`` atomically. For exclusive stages
    the previous holder is archived in the same transaction and the
    transition is recorded for :func:`rollback`. Returns a summary dict
    {model, stage, version, previous}."""
    if stage not in STAGES or stage == "none":
        raise ValueError(
            f"cannot promote to stage {stage!r} (valid: "
            f"{[s for s in STAGES if s != 'none']})"
        )
    with store.tx() as db:
        _require_version(db, model, version)
        previous = None
        if stage in EXCLUSIVE_STAGES:
            row = db.execute(
                "SELECT version FROM versions WHERE model=? AND stage=?",
                (model, stage),
            ).fetchone()
            previous = row[0] if row else None
            if previous == version:
                return {"model": model, "stage": stage, "version": version,
                        "previous": previous}
            if previous is not None:
                db.execute(
                    "UPDATE versions SET stage='archived'"
                    " WHERE model=? AND version=?",
                    (model, previous),
                )
            db.execute(
                "INSERT INTO promotions"
                " (model, stage, from_version, to_version, ts)"
                " VALUES (?,?,?,?,?)",
                (model, stage, previous, int(version), time.time()),
            )
        db.execute(
            "UPDATE versions SET stage=? WHERE model=? AND version=?",
            (stage, model, int(version)),
        )
        db.execute(
            "UPDATE models SET updated=? WHERE name=?", (time.time(), model)
        )
    return {"model": model, "stage": stage, "version": int(version),
            "previous": previous}


def rollback(store: ModelStore, model: str, stage: str) -> dict:
    """Undo the most recent promotion into an exclusive ``stage``: the
    current holder steps down to ``archived`` and the previous holder
    (recorded at promotion time) is restored — or the stage empties if
    the undone promotion was the first. Atomic; consumes one history
    entry per call, so repeated rollbacks walk further back."""
    if stage not in EXCLUSIVE_STAGES:
        raise ValueError(
            f"rollback applies to exclusive stages {EXCLUSIVE_STAGES},"
            f" not {stage!r}"
        )
    with store.tx() as db:
        last = db.execute(
            "SELECT id, from_version, to_version FROM promotions"
            " WHERE model=? AND stage=? ORDER BY id DESC LIMIT 1",
            (model, stage),
        ).fetchone()
        if last is None:
            raise KeyError(
                f"model {model!r} has no promotion history for {stage!r}"
            )
        pid, from_version, to_version = last
        holder = db.execute(
            "SELECT version FROM versions WHERE model=? AND stage=?",
            (model, stage),
        ).fetchone()
        if holder is None or holder[0] != to_version:
            raise RuntimeError(
                f"stage {stage!r} of {model!r} is held by"
                f" {holder[0] if holder else None}, but the last recorded"
                f" promotion installed {to_version} — refusing a blind"
                " rollback"
            )
        db.execute(
            "UPDATE versions SET stage='archived' WHERE model=? AND version=?",
            (model, to_version),
        )
        if from_version is not None:
            _require_version(db, model, from_version)
            db.execute(
                "UPDATE versions SET stage=? WHERE model=? AND version=?",
                (stage, model, from_version),
            )
        db.execute("DELETE FROM promotions WHERE id=?", (pid,))
        db.execute(
            "UPDATE models SET updated=? WHERE name=?", (time.time(), model)
        )
    return {"model": model, "stage": stage, "version": from_version,
            "previous": to_version}


def archive(store: ModelStore, model: str, version: int) -> dict:
    """Retire a version outright (also the way to empty an exclusive
    stage without installing a successor)."""
    with store.tx() as db:
        _require_version(db, model, version)
        db.execute(
            "UPDATE versions SET stage='archived' WHERE model=? AND version=?",
            (model, int(version)),
        )
    return {"model": model, "stage": "archived", "version": int(version)}
