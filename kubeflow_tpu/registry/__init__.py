"""Model Registry: versioned, governed model artifacts with lineage.

Reference analog (VERDICT.md §1 gap): [model-registry] — upstream
kubeflow/model-registry, a Go REST service over ML-Metadata that turns
"a checkpoint on disk" into a RegisteredModel → ModelVersion → Artifact
chain with stage promotion, connecting training, pipelines, and serving.
Here the same data model rides sqlite (the `tune/db.py` idiom) plus a
content-addressed blob store, and the serving link is a `registry://`
scheme registered into `serve/storage.py` so an InferenceService resolves
`registry://name@production` to an exact content hash at load time.

Modules:

- ``spec``    — records (RegisteredModel, ModelVersion, LineageEdge) and
  the stage vocabulary.
- ``store``   — ``ModelStore``: sqlite + sha256-deduplicated blobs.
- ``stages``  — stage lifecycle: atomic promote / rollback / archive.
- ``api``     — REST surface (the model-registry REST analog).
- ``fetcher`` — ``registry://`` resolution for the storage initializer.
"""

from kubeflow_tpu.registry.spec import (  # noqa: F401
    STAGES,
    LineageEdge,
    ModelVersion,
    RegisteredModel,
    RegisterOnSave,
)
from kubeflow_tpu.registry.store import (  # noqa: F401
    ModelStore,
    default_store,
    set_default_store,
)
