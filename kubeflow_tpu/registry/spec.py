"""Registry records and the stage vocabulary.

Reference analog: [model-registry]'s RegisteredModel / ModelVersion /
ModelArtifact entities (MLMD-typed contexts and artifacts — UNVERIFIED,
mount empty, SURVEY.md §0). One deliberate narrowing: an artifact here is
exactly one content-addressed blob (file or directory) per version, which
is what the serving path needs to pin bytes end-to-end.
"""

from __future__ import annotations

import dataclasses
from typing import Any

#: The stage lifecycle. ``staging`` and ``production`` are exclusive —
#: at most one version of a model holds each at a time (the per-stage
#: alias the serving path resolves); ``none``/``archived`` are unbounded.
STAGES = ("none", "staging", "production", "archived")
EXCLUSIVE_STAGES = ("staging", "production")


@dataclasses.dataclass
class RegisteredModel:
    """The model name-level record: versions hang off it."""

    name: str
    description: str = ""
    created: float = 0.0
    updated: float = 0.0
    latest_version: int = 0
    #: exclusive-stage holders, e.g. {"production": 3, "staging": 5}
    stages: dict[str, int] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ModelVersion:
    """One immutable version: content hash + stage + metadata."""

    model: str
    version: int
    sha256: str
    stage: str = "none"
    source_uri: str = ""
    created: float = 0.0
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ref(self) -> str:
        """The immutable ``registry://`` spelling of this version."""
        return f"registry://{self.model}@v{self.version}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LineageEdge:
    """Producer edge: which pipeline run / tune trial / checkpoint made a
    version (the MLMD event analog, collapsed to the output direction)."""

    kind: str            # "pipeline_run" | "tune_trial" | "checkpoint" | ...
    ref: str             # run_id, "<experiment>/<trial_id>", ckpt path…
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)
    created: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RegisterOnSave:
    """``Checkpointer.save(..., register=RegisterOnSave(...))`` payload:
    where and as what to register a just-written checkpoint."""

    store: Any                    # registry.store.ModelStore
    name: str
    stage: str | None = None      # promote right after registering
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)
