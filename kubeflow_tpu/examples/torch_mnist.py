"""PyTorchJob MNIST worker — runs the REFERENCE's stack under OUR control
plane (BASELINE config 1, exactly: DDP over the gloo CPU backend).

Where ``kubeflow_tpu.examples.mnist`` is the TPU-native replacement, this
worker is the compatibility proof: a torch ``DistributedDataParallel``
training loop (the reference example's shape — SURVEY.md §2.1 "Examples"
row, §3.1 hot loop) that rendezvouses purely from the env the JAXJob
control plane wrote for kind=PyTorchJob (MASTER_ADDR/MASTER_PORT/RANK/
WORLD_SIZE — kubeflow_tpu.orchestrator.kinds). A reference user's torch
job therefore ports by swapping the manifest, not the training code.

Synthetic class-prototype data (no dataset downloads in this image), CNN
sized like the canonical mnist example, loss printed in the tuner-scrapable
``key=value`` format.
"""

from __future__ import annotations

import argparse
import os
import time


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--log-every", type=int, default=5)
    p.add_argument("--backend", type=str, default="gloo")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    import numpy as np
    import torch
    import torch.distributed as dist
    import torch.nn as nn
    from torch.nn.parallel import DistributedDataParallel

    rank = int(os.environ["RANK"])
    world = int(os.environ["WORLD_SIZE"])
    # MASTER_ADDR/MASTER_PORT are read from env by init_process_group.
    dist.init_process_group(args.backend, rank=rank, world_size=world)
    print(
        f"process {rank}/{world}: torch {args.backend} process group up",
        flush=True,
    )

    torch.manual_seed(args.seed)
    model = nn.Sequential(
        nn.Conv2d(1, 32, 3, padding=1), nn.ReLU(), nn.MaxPool2d(2),
        nn.Conv2d(32, 64, 3, padding=1), nn.ReLU(), nn.MaxPool2d(2),
        nn.Flatten(), nn.Linear(64 * 7 * 7, 128), nn.ReLU(),
        nn.Linear(128, 10),
    )
    ddp = DistributedDataParallel(model)
    opt = torch.optim.Adam(ddp.parameters(), lr=args.lr)
    loss_fn = nn.CrossEntropyLoss()

    # Same synthetic distribution as the JAX example: fixed class
    # prototypes + noise, rank-sharded batches.
    proto_rng = np.random.default_rng(args.seed)
    protos = proto_rng.normal(size=(10, 28, 28)).astype("float32")
    if args.global_batch < world:
        raise SystemExit(
            f"--global-batch {args.global_batch} smaller than world size "
            f"{world}: every rank needs at least one sample"
        )
    local_batch = args.global_batch // world

    t0 = time.perf_counter()
    for step in range(1, args.steps + 1):
        rng = np.random.default_rng(args.seed + step * world + rank)
        labels = rng.integers(0, 10, size=local_batch)
        images = protos[labels] + 0.3 * rng.normal(
            size=(local_batch, 28, 28)
        ).astype("float32")
        x = torch.from_numpy(images).unsqueeze(1)
        y = torch.from_numpy(labels)

        opt.zero_grad()
        out = ddp(x)
        loss = loss_fn(out, y)
        loss.backward()  # ← DDP's bucketed gloo allreduce fires here
        opt.step()

        if rank == 0 and (step % args.log_every == 0 or step == args.steps):
            acc = (out.argmax(dim=1) == y).float().mean().item()
            sps = step / (time.perf_counter() - t0)
            print(
                f"step={step} loss={loss.item():.6g} accuracy={acc:.6g} "
                f"steps_per_sec={sps:.6g}",
                flush=True,
            )

    dist.barrier()
    if rank == 0:
        print(f"final_loss={loss.item():.6g}", flush=True)
    dist.destroy_process_group()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
