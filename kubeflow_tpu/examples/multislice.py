"""Multislice worker — the DCN story (SURVEY.md §2.7 "DCN" row, §7 hard
part 6).

Each jax.distributed process stands in for one TPU slice: the mesh gets a
leading ``dcn_data`` axis equal to the process count, data parallelism runs
ACROSS slices (over DCN) while tensor/FSDP parallelism stays WITHIN a slice
(over ICI) — the placement the scaling playbook prescribes, since DCN is
orders of magnitude thinner than ICI.

The script asserts the placement (every DCN block of the mesh contains
exactly one process's devices), runs a cross-slice psum, then trains the
transformer for a few steps. Run under the orchestrator as a JAXJob with
N workers, or standalone in one process (dcn_data=1).
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--log-every", type=int, default=2)
    args = p.parse_args(argv)

    from kubeflow_tpu.core.distributed import initialize_from_env

    initialize_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    print(
        f"devices: {jax.local_device_count()} local / "
        f"{jax.device_count()} global, process {jax.process_index()}"
    )

    from kubeflow_tpu.core.mesh import Axis, MeshSpec, build_mesh
    from kubeflow_tpu.data.synthetic import TokenLMDataset, local_shard_iterator
    from kubeflow_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        make_init_fn,
        make_loss_fn,
    )
    from kubeflow_tpu.parallel.sharding import transformer_rules
    from kubeflow_tpu.train.loop import TrainConfig, Trainer

    n_slices = jax.process_count()
    per_slice = jax.local_device_count()
    # TP (model) + FSDP within the slice; DP across slices via DCN.
    model_par = 2 if per_slice % 2 == 0 else 1
    fsdp = per_slice // model_par
    spec = MeshSpec(dcn_data=n_slices, fsdp=fsdp, model=model_par)
    mesh = build_mesh(spec)

    # -- placement: each DCN block must be exactly one process ---------- #
    data_pos = Axis.ALL.index(Axis.DATA)
    blocks = np.moveaxis(mesh.devices, data_pos, 0)
    for i in range(n_slices):
        procs = {d.process_index for d in blocks[i].flat}
        assert procs == {i}, (
            f"dcn block {i} spans processes {procs}; cross-slice traffic "
            "would ride axes meant for ICI"
        )
    print(f"dcn placement ok: {n_slices} slices x {per_slice} devices")

    # -- cross-slice collective ----------------------------------------- #
    from jax.sharding import NamedSharding, PartitionSpec as P

    @jax.jit
    def cross_slice_sum(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P())
        ).sum()

    local = jnp.ones((n_slices * fsdp,), jnp.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P((Axis.DATA, Axis.FSDP))),
        np.ones((fsdp,), np.float32) * (jax.process_index() + 1),
        (n_slices * fsdp,),
    )
    del local
    total = float(cross_slice_sum(arr))
    want = sum((i + 1) * fsdp for i in range(n_slices))
    assert total == want, (total, want)
    print(f"cross-slice psum ok: {total}")

    # -- DP-across / TP-within training --------------------------------- #
    cfg = TransformerConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=128,
        attn_impl="reference",
        dtype=jnp.float32,
        embed_impl="onehot",
    )
    model = TransformerLM(cfg)
    global_batch = 2 * spec.batch_partitions
    trainer = Trainer(
        init_params=make_init_fn(model, args.seq_len, spec.batch_partitions),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adamw(1e-3),
        config=TrainConfig(
            mesh=spec,
            global_batch=global_batch,
            steps=args.steps,
            log_every=args.log_every,
        ),
        param_spec_fn=transformer_rules(),
    )
    ds = TokenLMDataset(vocab_size=256, seq_len=args.seq_len)
    state, history = trainer.fit(
        lambda s: local_shard_iterator(ds, global_batch, start_step=s)
    )
    assert int(state.step) == args.steps
    if jax.process_index() == 0:
        print(f"multislice training ok: steps={int(state.step)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
