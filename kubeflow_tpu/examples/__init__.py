"""Runnable JAXJob entrypoints — the analogs of the reference's examples/."""
