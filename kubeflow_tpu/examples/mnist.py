"""JAXJob MNIST worker — BASELINE config 1 (`pytorchjob-mnist` analog).

The reference example does ``dist.init_process_group(backend); DDP(model)``
inside a PyTorchJob pod (SURVEY.md §3.1 hot loop). The JAXJob version:
bootstrap ``jax.distributed`` from the env contract the orchestrator wrote,
build a data-parallel mesh over ALL global devices, and run the jitted SPMD
step — the gradient allreduce is XLA-emitted (ICI on TPU; gloo between CPU
sim processes, coincidentally the very backend of BASELINE config 1).

Run under the orchestrator:
    JobSpec(replicas={"worker": ReplicaSpec(replicas=N,
        command=(python, "-m", "kubeflow_tpu.examples.mnist", ...))})
or standalone on any host with devices.
"""

from __future__ import annotations

import argparse

import optax


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--global-batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--checkpoint-every", type=int, default=50)
    p.add_argument(
        "--checkpoint-sync", action="store_true",
        help="synchronous saves: every saved step is durable (with its "
             "sha256 manifest) before the next step runs — what the chaos "
             "kill-mid-train tests rely on for exact-step resume",
    )
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--metrics-logdir", type=str, default=None)
    p.add_argument(
        "--grad-accum-steps", type=int, default=1,
        help="in-graph microbatch accumulation (one optimizer update)",
    )
    p.add_argument(
        "--prefetch-depth", type=int, default=2,
        help="device-prefetch depth; 0 runs the input pipeline inline",
    )
    args = p.parse_args(argv)

    # Rendezvous BEFORE any device access (the torchrun-analog moment).
    from kubeflow_tpu.core.distributed import initialize_from_env

    cfg = initialize_from_env()

    import jax

    from kubeflow_tpu.core.mesh import MeshSpec
    from kubeflow_tpu.data.synthetic import (
        ClassPrototypeDataset,
        local_shard_iterator,
    )
    from kubeflow_tpu.models.mnist_cnn import MnistCNN, make_init_fn, make_loss_fn
    from kubeflow_tpu.train.checkpoint import CheckpointConfig
    from kubeflow_tpu.train.loop import TrainConfig, Trainer

    print(
        f"process {cfg.process_id}/{cfg.num_processes}: "
        f"{jax.local_device_count()} local / {jax.device_count()} global "
        f"{jax.default_backend()} devices",
        flush=True,
    )

    model = MnistCNN()
    trainer = Trainer(
        init_params=make_init_fn(model),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(args.lr),
        config=TrainConfig(
            mesh=MeshSpec.data_parallel(jax.device_count()),
            global_batch=args.global_batch,
            steps=args.steps,
            log_every=args.log_every,
            seed=args.seed,
            checkpoint=(
                CheckpointConfig(
                    directory=args.checkpoint_dir,
                    save_every_steps=args.checkpoint_every,
                    async_save=not args.checkpoint_sync,
                )
                if args.checkpoint_dir
                else None
            ),
            resume=not args.no_resume,
            metrics_logdir=args.metrics_logdir,
            grad_accum_steps=args.grad_accum_steps,
            prefetch_depth=args.prefetch_depth,
        ),
    )
    # Factory form: on checkpoint resume the stream continues at the
    # restored step instead of replaying batch 0.
    data = lambda start_step: local_shard_iterator(  # noqa: E731
        ClassPrototypeDataset(seed=args.seed),
        args.global_batch,
        start_step=start_step,
    )
    _state, history = trainer.fit(data)

    if jax.process_index() == 0 and history:
        first, last = history[0], history[-1]
        print(
            f"final_loss={last['loss']:.6g} final_accuracy={last['accuracy']:.6g} "
            f"steps_per_sec={last['steps_per_sec']:.6g}",
            flush=True,
        )
        if not (last["loss"] < first["loss"] or last["accuracy"] > 0.9):
            print("WARNING: loss did not improve", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
