"""kserve-bert — BASELINE config 5 / north-star second example.

The reference flow (SURVEY.md §3.3): `InferenceService(predictor:
huggingface, model=bert-base-uncased)` → storage-initializer downloads to
/mnt/models → `ModelServer` tokenizes and runs the torch forward on GPU.

The TPU-native flow here: point ``--model-dir`` at the same HF-format
directory a reference user has (config.json + pytorch_model.bin +
vocab.txt). The checkpoint is converted to flax once at load
(models/convert.py), weights live HBM-resident, the forward is the jitted
bucketed path with the Pallas flash-attention kernel, and tokenization is
the real WordPiece over the checkpoint's own vocab.txt — token ids match
the training vocab exactly.

Run:
    python -m kubeflow_tpu.examples.bert_serve --model-dir /mnt/models/bert
    curl -d '{"instances": ["the capital of france is [MASK]."]}' \\
        localhost:8080/v1/models/bert:predict

Without --model-dir it serves a randomly-initialized bert-base (latency-
representative; this env has no egress to fetch real weights).

An InferenceService manifest for the controller path is in
``examples/manifests/bert_isvc.yaml``; `serve.controller.ServeController`
reconciles it into replicas of exactly this server.
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model-dir", type=str, default=None,
                   help="HF-format dir (config.json + pytorch_model.bin + "
                        "vocab.txt) or Orbax checkpoint dir")
    p.add_argument("--name", type=str, default="bert")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--tiny", action="store_true",
                   help="bert-tiny config (CPU-friendly smoke runs)")
    p.add_argument("--interpret", action="store_true",
                   help="Pallas interpret mode (no TPU present)")
    args = p.parse_args(argv)

    import jax

    from kubeflow_tpu.models.bert import bert_base, bert_tiny
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel
    from kubeflow_tpu.serve.server import ModelServer

    cfg = None
    if args.tiny:
        cfg = bert_tiny()
    elif args.model_dir is None:
        cfg = bert_base()
    # else: config comes from the model dir's config.json

    # Compiled Pallas kernels need a TPU; on CPU fall back to the XLA
    # reference attention (or interpret mode if explicitly asked).
    if jax.default_backend() == "cpu" or args.interpret:
        import dataclasses
        import json
        import os

        if cfg is None:
            from kubeflow_tpu.models.convert import bert_config_from_hf

            cfg_file = os.path.join(args.model_dir, "config.json")
            if os.path.isfile(cfg_file):
                cfg = bert_config_from_hf(json.loads(open(cfg_file).read()))
        if cfg is not None:
            cfg = dataclasses.replace(
                cfg,
                attn_impl=cfg.attn_impl if args.interpret else "reference",
                interpret_kernels=args.interpret,
            )

    model = BertRuntimeModel(args.name, args.model_dir, config=cfg)
    model.load()  # fail-closed: a corrupt --model-dir dies HERE, not mid-request

    server = ModelServer(http_port=args.port)
    server.register(model)
    print(f"serving {args.name!r} on :{args.port} "
          f"(backend={jax.default_backend()}, "
          f"tokenizer={type(model.tokenizer).__name__})")
    server.start()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
